"""Driver benchmark: prints ONE JSON line.

Round-2 workloads — END-TO-END (SQL text -> host result) per the
round-1 verdict, BASELINE.md configs 2-4:

  q6_sf1   : TPC-H q6 at SF1   — scan + filter/project + global agg
  q3_sf10  : TPC-H q3 at SF10  — 3-way join + group-by, single chip
  q5_sf100 : TPC-H q5-shaped at SF100 — 6-way join; lineitem (600M rows,
             ~19GB) exceeds HBM, so it streams through the bounded-memory
             chunked driver (exec/chunked.py). Only q5's columns are
             generated (dbgen formulas; full SF100 generation needs >75GB
             host RAM) — the VERDICT's "q5-shaped SF100 run".

Methodology (testing/trino-benchto-benchmarks/.../tpch.yaml: prewarm then
measured runs, concurrency 1): per config we report cold (first run incl.
XLA compile + host->device ingest), steady-state median end-to-end wall
(parse -> plan -> execute -> decode), and an identical-results check
against the CPU baseline. Scan data is device-resident in steady state
for EVERY config — configs 2-3 via the int64 scan cache, config 4 via
the narrowed fact-column cache (exec/device_cache.py: int32/int8 range-
compressed columns, 7.8 GB in HBM for SF100 q5's lineitem) — matching
the reference benchmarks reading in-memory pages; the chunked driver
still bounds per-chunk intermediates. Baselines are single-node
vectorized numpy implementations of the same queries (the stand-in for
the single-node Java operator pipeline). NOTE: this environment reaches
the TPU through a network tunnel measured at ~30 MB/s host->device for
incompressible data (~60 MB/s compressible) and ~100-260 ms per fetch
round trip; real v5e host links are orders of magnitude faster, so
tunnel-crossing (cold/ingest) numbers are a LOWER bound on the hardware.

Config order is information value (round-3 verdict): q5 SF100 first so
a driver timeout can't starve it. vs_baseline = cpu_ms / tpu_steady_ms
for the headline config (q3_sf10 when present).
"""

import json
import os
import statistics
import sys
import threading
import time

import numpy as np

PREWARM = 1
RUNS = 3
# Hard self-budget, kept WELL below any plausible driver timeout (round-2's
# single end-of-run emit was erased by an rc=124 driver kill).  A watchdog
# thread force-emits whatever has finished and exits before this expires.
BUDGET_S = float(os.environ.get("TRINO_TPU_BENCH_BUDGET_S", 780))
T0 = time.monotonic()

_emit_lock = threading.Lock()
_detail = {}


def emit(final=False):
    """Print the CUMULATIVE result as one complete JSON line.

    Called after EVERY finished config (not only at exit) so that a driver
    timeout preserves every config that completed.  The driver records the
    last JSON line it sees; each emission is a full, self-contained record.
    """
    with _emit_lock:
        headline = _detail.get("q3_sf10") or _detail.get("q5_sf100") \
            or _detail.get("q6_sf1")
        if headline is None:
            return
        print(json.dumps({
            "metric": "tpch_e2e_sql_to_result_wall_ms",
            "value": headline["tpu_steady_ms"],
            "unit": "ms",
            "vs_baseline": headline["speedup"],
            "detail": dict(_detail, elapsed_s=round(time.monotonic() - T0, 1),
                           final=final),
        }), flush=True)


def _watchdog():
    deadline = T0 + BUDGET_S - 10
    while time.monotonic() < deadline:
        time.sleep(min(5.0, max(0.1, deadline - time.monotonic())))
    _detail["watchdog"] = "budget expired; emitting finished configs"
    emit(final=True)
    sys.stdout.flush()
    os._exit(0)

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate, l_orderkey
LIMIT 10
"""

Q5 = """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey
  AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey
  AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-01-01' + INTERVAL '1' YEAR
GROUP BY n_name
ORDER BY revenue DESC
"""


# ---------------------------------------------------------------------------
# CPU baselines: single-node vectorized numpy over the same host arrays
# ---------------------------------------------------------------------------

def col(table, name):
    return np.asarray(table.columns[table.schema.index_of(name)])


def _days(s):
    return (np.datetime64(s) - np.datetime64("1970-01-01")).astype(int)


def numpy_q6(tables):
    li = tables["lineitem"]
    ship = col(li, "l_shipdate")
    disc = col(li, "l_discount")
    qty = col(li, "l_quantity")
    price = col(li, "l_extendedprice")
    m = (ship >= _days("1994-01-01")) & (ship < _days("1995-01-01")) & \
        (disc >= 5) & (disc <= 7) & (qty < 2400)
    return int((price[m] * disc[m]).sum())


def numpy_q3(tables):
    cust, orders, li = tables["customer"], tables["orders"], \
        tables["lineitem"]
    seg_pool = cust.schema.field("c_mktsegment").dictionary
    seg_code = seg_pool.index("BUILDING")
    ck = col(cust, "c_custkey")[col(cust, "c_mktsegment") == seg_code]
    cutoff = _days("1995-03-15")
    od = col(orders, "o_orderdate")
    om = od < cutoff
    okey, ocust = col(orders, "o_orderkey")[om], \
        col(orders, "o_custkey")[om]
    od_f, oprio = od[om], col(orders, "o_shippriority")[om]
    ck_sorted = np.sort(ck)
    pos = np.clip(np.searchsorted(ck_sorted, ocust), 0,
                  len(ck_sorted) - 1)
    keep = ck_sorted[pos] == ocust
    okey, od_f, oprio = okey[keep], od_f[keep], oprio[keep]
    order_o = np.argsort(okey, kind="stable")
    okey_s, od_s = okey[order_o], od_f[order_o]
    lk = col(li, "l_orderkey")
    lm = col(li, "l_shipdate") > cutoff
    lk, price, disc = lk[lm], col(li, "l_extendedprice")[lm], \
        col(li, "l_discount")[lm]
    pos = np.clip(np.searchsorted(okey_s, lk), 0, len(okey_s) - 1)
    keep = okey_s[pos] == lk
    lk = lk[keep]
    rev = price[keep] * (100 - disc[keep])     # scaled 1e4
    uniq, inv = np.unique(lk, return_inverse=True)
    sums = np.bincount(inv, weights=rev.astype(np.float64))
    upos = np.clip(np.searchsorted(okey_s, uniq), 0, len(okey_s) - 1)
    order = np.lexsort((uniq, od_s[upos], -sums))
    top = order[:10]
    return [(int(uniq[i]), float(sums[i]) / 1e4) for i in top]


def numpy_q5(tables, chunk=1 << 26):
    nat, reg = tables["nation"], tables["region"]
    sup, cust = tables["supplier"], tables["customer"]
    orders, li = tables["orders"], tables["lineitem"]
    r_pool = reg.schema.field("r_name").dictionary
    asia = r_pool.index("ASIA")
    asia_regionkeys = col(reg, "r_regionkey")[col(reg, "r_name") == asia]
    asia_nations = col(nat, "n_nationkey")[
        np.isin(col(nat, "n_regionkey"), asia_regionkeys)]
    od = col(orders, "o_orderdate")
    om = (od >= _days("1994-01-01")) & (od < _days("1995-01-01"))
    okey, ocust = col(orders, "o_orderkey")[om], \
        col(orders, "o_custkey")[om]
    c_nation = col(cust, "c_nationkey")      # custkey dense 1..N
    o_nation = c_nation[ocust - 1]
    ok = np.isin(o_nation, asia_nations)
    okey, o_nation = okey[ok], o_nation[ok]
    order_o = np.argsort(okey, kind="stable")
    okey_s, onat_s = okey[order_o], o_nation[order_o]
    s_nation = col(sup, "s_nationkey")
    acc = np.zeros(25, dtype=np.float64)
    n = li.num_rows
    lk_all, ls_all = col(li, "l_orderkey"), col(li, "l_suppkey")
    price_all, disc_all = col(li, "l_extendedprice"), \
        col(li, "l_discount")
    for start in range(0, n, chunk):
        lk = lk_all[start:start + chunk]
        ls = ls_all[start:start + chunk]
        price = price_all[start:start + chunk]
        disc = disc_all[start:start + chunk]
        pos = np.clip(np.searchsorted(okey_s, lk), 0, len(okey_s) - 1)
        keep = okey_s[pos] == lk
        snat = s_nation[ls[keep] - 1]
        match = snat == onat_s[pos[keep]]
        rev = (price[keep][match] * (100 - disc[keep][match])
               ).astype(np.float64)
        acc += np.bincount(snat[match], weights=rev, minlength=25)
    n_pool = nat.schema.field("n_name").dictionary
    name_of = {int(k): n_pool[int(c)]
               for k, c in zip(col(nat, "n_nationkey"),
                               col(nat, "n_name"))}
    return [(name_of[i], acc[i] / 1e4)
            for i in np.argsort(-acc) if acc[i] > 0]


# ---------------------------------------------------------------------------
# q5-shaped SF100 generation (pruned columns, dbgen formulas)
# ---------------------------------------------------------------------------

def q5_tables(scale: float, seed: int = 19920101):
    """The q5 columns only, same shapes/distributions as datagen.py.
    Persisted through the on-disk table cache (connectors/diskcache.py)
    so generation cost is paid once per machine, not per bench run."""
    from trino_tpu.connectors.diskcache import load_table, save_table
    from trino_tpu.connectors.tpch.datagen import TableData as _TD
    dataset = f"bench_q5_sf{scale:g}_s{seed}"
    names = ["region", "nation", "supplier", "customer", "orders",
             "lineitem"]
    cached = {}
    for nm in names:
        t = load_table(dataset, nm, _TD)
        if t is None:
            break
        cached[nm] = t
    else:
        return cached
    tables = _q5_tables_generate(scale, seed)
    for t in tables.values():
        save_table(dataset, t)
    return tables


def _q5_tables_generate(scale: float, seed: int = 19920101):
    from trino_tpu.batch import Field, Schema
    from trino_tpu.connectors.tpch.datagen import (ENDDATE, NATIONS,
                                                   REGIONS, STARTDATE,
                                                   TableData, _codes_for,
                                                   retail_price_cents)
    from trino_tpu.types import BIGINT, DATE, VARCHAR, decimal
    rng = np.random.default_rng(seed)
    t = {}
    t["region"] = TableData(
        "region", Schema.of(Field("r_regionkey", BIGINT),
                            Field("r_name", VARCHAR,
                                  dictionary=tuple(sorted(REGIONS)))),
        [np.arange(5, dtype=np.int64),
         _codes_for(REGIONS, sorted(REGIONS))],
        primary_key=("r_regionkey",))
    n_names = [n for n, _ in NATIONS]
    t["nation"] = TableData(
        "nation", Schema.of(Field("n_nationkey", BIGINT),
                            Field("n_name", VARCHAR,
                                  dictionary=tuple(sorted(n_names))),
                            Field("n_regionkey", BIGINT)),
        [np.arange(25, dtype=np.int64),
         _codes_for(n_names, sorted(n_names)),
         np.array([r for _, r in NATIONS], dtype=np.int64)],
        primary_key=("n_nationkey",))
    n_supp = int(scale * 10_000)
    t["supplier"] = TableData(
        "supplier", Schema.of(Field("s_suppkey", BIGINT),
                              Field("s_nationkey", BIGINT)),
        [np.arange(1, n_supp + 1, dtype=np.int64),
         rng.integers(0, 25, n_supp).astype(np.int64)],
        primary_key=("s_suppkey",))
    n_cust = int(scale * 150_000)
    t["customer"] = TableData(
        "customer", Schema.of(Field("c_custkey", BIGINT),
                              Field("c_nationkey", BIGINT)),
        [np.arange(1, n_cust + 1, dtype=np.int64),
         rng.integers(0, 25, n_cust).astype(np.int64)],
        primary_key=("c_custkey",))
    n_ord = int(scale * 1_500_000)
    idx = np.arange(n_ord, dtype=np.int64)
    orderkey = (idx // 8) * 32 + (idx % 8) + 1
    m_active = max(1, n_cust - n_cust // 3)
    j = rng.integers(1, m_active + 1, n_ord).astype(np.int64)
    o_custkey = np.clip(j + (j - 1) // 2, 1, n_cust)
    o_orderdate = rng.integers(STARTDATE, ENDDATE - 151 + 1,
                               n_ord).astype(np.int32)
    t["orders"] = TableData(
        "orders", Schema.of(Field("o_orderkey", BIGINT),
                            Field("o_custkey", BIGINT),
                            Field("o_orderdate", DATE)),
        [orderkey, o_custkey, o_orderdate],
        primary_key=("o_orderkey",))
    lines_per_order = rng.integers(1, 8, n_ord)
    l_orderkey = np.repeat(orderkey, lines_per_order)
    n_li = len(l_orderkey)
    l_partkey = rng.integers(1, int(scale * 200_000) + 1,
                             n_li).astype(np.int64)
    li_i = rng.integers(0, 4, n_li).astype(np.int64)
    l_suppkey = ((l_partkey + li_i * (n_supp // 4 + (l_partkey - 1)
                                      // n_supp)) % n_supp) + 1
    l_quantity = rng.integers(1, 51, n_li).astype(np.int64)
    l_extendedprice = l_quantity * retail_price_cents(l_partkey)
    del l_partkey, li_i, l_quantity
    l_discount = rng.integers(0, 11, n_li).astype(np.int64)
    d122 = decimal(12, 2)
    t["lineitem"] = TableData(
        "lineitem", Schema.of(Field("l_orderkey", BIGINT),
                              Field("l_suppkey", BIGINT),
                              Field("l_extendedprice", d122),
                              Field("l_discount", d122)),
        [l_orderkey, l_suppkey, l_extendedprice, l_discount])
    return t


class BenchConnector:
    """Prebuilt q5-shaped tables under one schema."""
    name = "bench"

    def __init__(self, tables, schema):
        self._tables = tables
        self._schema = schema
        self._cache = {schema: tables}         # stats-probe shape

    def scale_for_schema(self, schema):
        return schema

    def schema_names(self):
        return [self._schema]

    def table_names(self, schema):
        return sorted(self._tables)

    def get_table(self, schema, table):
        return self._tables[table]


# ---------------------------------------------------------------------------
# --gather-micro: ns/row of the Pallas tiled-gather kernel vs jnp.take
# ---------------------------------------------------------------------------

def gather_micro(table_sizes=None, probe_rows=None, n_tables=3, runs=3,
                 out_path="BENCH_gather_micro.json"):
    """Microbenchmark the dense-probe gather: kernel vs jnp.take ns per
    gathered row across table sizes, recorded as one JSON artifact so
    the per-round trajectory toward the ~4 ns/row break-even
    (BENCH_NOTES round 5) is measurable.

    On TPU this times the compiled kernel; under JAX_PLATFORMS=cpu it
    drops to a tiny smoke configuration in Pallas interpret mode (the
    numbers are meaningless there — the run exists so tier-1 exercises
    the harness end to end). Returns the record dict it wrote."""
    import jax
    import jax.numpy as jnp

    from trino_tpu.ops import pallas_gather as pg

    import functools

    on_tpu = jax.default_backend() == "tpu"
    mode = "device" if on_tpu else "interpret"
    if table_sizes is None:
        table_sizes = [1 << 12, 1 << 14, 1 << 16] if on_tpu else [1 << 12]
    if probe_rows is None:
        probe_rows = (1 << 22) if on_tpu else (1 << 13)
    rng = np.random.default_rng(7)

    def timed(fn):
        jax.block_until_ready(fn())                # warm (compile)
        walls = []
        for _ in range(runs):
            t0 = time.monotonic()
            jax.block_until_ready(fn())
            walls.append(time.monotonic() - t0)
        return min(walls)

    records = []
    for w in table_sizes:
        tables = [jnp.asarray(rng.integers(-(1 << 40), 1 << 40, w))
                  for _ in range(n_tables)]
        idx = jnp.asarray(rng.integers(0, w, probe_rows))

        take = jax.jit(lambda ts, ix: [jnp.take(t, ix, axis=0)
                                       for t in ts])
        # the pre-jitted kernel entry points route their XLA compiles
        # through the central recorder (exec/profiler.py), so the
        # microbench's compile costs land in /v1/jit like every other
        # jit site's
        kernel = functools.partial(pg.gather_columns_jit, mode=mode)
        t_take = timed(lambda: take(tables, idx))
        t_kernel = timed(lambda: kernel(tables, idx))
        elems = probe_rows * n_tables
        rec = {"table_rows": w, "probe_rows": probe_rows,
               "n_tables": n_tables,
               "take_ns_per_elem": round(t_take * 1e9 / elems, 3),
               "kernel_ns_per_elem": round(t_kernel * 1e9 / elems, 3),
               "kind": "scan"}
        records.append(rec)

        # windowed kernel on near-sorted indices (the chunked fact-scan
        # shape): per-probe cost independent of table size
        idx_s = jnp.sort(idx)
        planes = pg.prepare_word_planes(tables[0])
        win = functools.partial(pg.gather_word_windowed_jit,
                                word_dtype="int64", mode=mode)
        t_win = timed(lambda: win(planes, idx_s))
        records.append({
            "table_rows": w, "probe_rows": probe_rows, "n_tables": 1,
            "take_ns_per_elem": round(t_take * 1e9 / elems, 3),
            "kernel_ns_per_elem": round(t_win * 1e9 / probe_rows, 3),
            "kind": "windowed"})

    out = {"metric": "gather_micro_ns_per_elem",
           "device": str(jax.devices()[0]), "mode": mode,
           "smoke": not on_tpu, "records": records}
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# --agg-micro: hash vs sort vs direct aggregation across cardinalities
# ---------------------------------------------------------------------------

def agg_micro(cardinalities=None, rows=None, runs=3,
              out_path="BENCH_agg_micro.json"):
    """Microbenchmark the aggregation strategies (ops/pallas_hash.py
    hash table, ops/aggregate.py sort kernel, direct masked reductions
    where the domain allows) across group cardinalities, recording the
    per-strategy walls as one JSON artifact so the q18-class trajectory
    (hash >= 5x sort at high cardinality) is measurable round over
    round and gated by --check-regressions.

    On TPU this sweeps 10^2..10^7 groups over a large batch; under
    JAX_PLATFORMS=cpu it drops to a tiny smoke configuration in Pallas
    interpret mode (numbers meaningless there — the run exists so
    tier-1 exercises the harness end to end)."""
    import jax
    import jax.numpy as jnp

    from trino_tpu.batch import batch_from_numpy
    from trino_tpu.ops import pallas_hash as ph
    from trino_tpu.ops.aggregate import (AggSpec, direct_group_aggregate,
                                         key_pack_plan,
                                         sort_group_aggregate)

    on_tpu = jax.default_backend() == "tpu"
    mode = "device" if on_tpu else "interpret"
    if cardinalities is None:
        cardinalities = [100, 1000, 10_000, 100_000, 1_000_000,
                         10_000_000] if on_tpu else [16, 256]
    if rows is None:
        rows = (1 << 24) if on_tpu else (1 << 12)
    rng = np.random.default_rng(11)

    def timed(fn):
        import jax as _jax
        _jax.block_until_ready(fn())            # warm (compile)
        walls = []
        for _ in range(runs):
            t0 = time.monotonic()
            _jax.block_until_ready(fn())
            walls.append(time.monotonic() - t0)
        return min(walls) * 1000

    records = []
    aggs = (AggSpec("sum", 1), AggSpec("count_star", None))
    for groups in cardinalities:
        keys = rng.integers(0, groups, rows)
        vals = rng.integers(-(1 << 40), 1 << 40, rows)
        batch = batch_from_numpy([keys, vals])
        cap = 1 << max(10, int(1.3 * groups).bit_length())
        rec = {"groups": groups, "rows": rows}

        rec["sort_ms"] = round(timed(lambda: sort_group_aggregate(
            batch, (0,), aggs, min(cap, len(keys) or 1))), 3)
        if groups <= 64:
            rec["direct_ms"] = round(timed(
                lambda: direct_group_aggregate(batch, (0,), (groups,),
                                               aggs)), 3)
        plan = key_pack_plan(batch, (0,))
        if plan is not None:
            kmins, bits = plan
            slots, fits = ph.pick_table_slots(groups, aggs)
            kd = jnp.asarray(kmins)
            out = ph.hash_group_aggregate(batch, kd, (0,), bits, aggs,
                                          slots, mode)
            esc = int(out[1])
            rec["hash_table_slots"] = slots
            rec["hash_escapes"] = esc
            if esc == 0 and fits:
                rec["hash_ms"] = round(timed(
                    lambda: ph.hash_group_aggregate(
                        batch, kd, (0,), bits, aggs, slots, mode)), 3)
                rec["hash_vs_sort"] = round(
                    rec["sort_ms"] / max(rec["hash_ms"], 1e-6), 2)
        records.append(rec)

    out = {"metric": "agg_micro_ms", "device": str(jax.devices()[0]),
           "mode": mode, "smoke": not on_tpu, "records": records}
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# --star-micro: fused multiway star probe vs the pairwise join ladder
# ---------------------------------------------------------------------------

def _star_tables(k, fact_rows, dim_rows, hit_rate, seed=40231):
    """Synthetic star: one fact with k FK columns + a value, k unique-
    keyed dims each carrying one payload column. `hit_rate` sets the
    per-dim probe match fraction (fact keys drawn past the dim's key
    range miss, so the inner join drops 1-hit_rate of rows per hop)."""
    from trino_tpu.batch import Field, Schema
    from trino_tpu.connectors.tpch.datagen import TableData
    from trino_tpu.types import BIGINT
    rng = np.random.default_rng(seed + k)
    t = {}
    span = max(1, int(dim_rows / max(hit_rate, 1e-9)))
    fact_cols = [rng.integers(0, span, fact_rows).astype(np.int64)
                 for _ in range(k)]
    fact_cols.append(rng.integers(0, 1 << 20, fact_rows).astype(np.int64))
    t["fact"] = TableData(
        "fact",
        Schema.of(*[Field(f"f_d{i}key", BIGINT) for i in range(k)],
                  Field("f_value", BIGINT)),
        fact_cols)
    for i in range(k):
        t[f"dim{i}"] = TableData(
            f"dim{i}",
            Schema.of(Field(f"d{i}_key", BIGINT),
                      Field(f"d{i}_attr", BIGINT)),
            [np.arange(dim_rows, dtype=np.int64),
             rng.integers(0, 1000, dim_rows).astype(np.int64)],
            primary_key=(f"d{i}_key",))
    return t


def star_micro(shapes=None, fact_rows=None, dim_rows=None, runs=3,
               out_path="BENCH_star_micro.json"):
    """Microbenchmark the fused multiway star probe (ops/pallas_hash.py
    multiway_probe, one Pallas pass over every VMEM-resident dimension
    table) against the pairwise join ladder it replaces, across star
    widths and probe selectivities. Emits one JSON artifact so the
    ISSUE-13 claim (fused >= 2x pairwise at >= 3 dims on TPU) is
    measurable round over round and gated by --check-regressions.

    Under JAX_PLATFORMS=cpu this drops to a tiny smoke shape in Pallas
    interpret mode (numbers meaningless — the run exists so tier-1
    exercises the harness and the bit-exactness assert end to end)."""
    import jax

    from trino_tpu.catalog import Catalog
    from trino_tpu.exec.session import Session
    from trino_tpu.metrics import MULTIJOIN_FUSED_PROBES

    on_tpu = jax.default_backend() == "tpu"
    mode = "device" if on_tpu else "interpret"
    if shapes is None:
        shapes = [(2, 0.9), (3, 0.9), (3, 0.2), (5, 0.9)] if on_tpu \
            else [(2, 0.9), (3, 0.5)]
    if fact_rows is None:
        fact_rows = (1 << 22) if on_tpu else (1 << 12)
    if dim_rows is None:
        dim_rows = 4096 if on_tpu else 256

    records = []
    for k, hit_rate in shapes:
        tables = _star_tables(k, fact_rows, dim_rows, hit_rate)
        cat = Catalog()
        cat.register("bench", BenchConnector(tables, "star"))
        s = Session(catalog=cat, default_cat="bench",
                    default_schema="star")
        sql = ("SELECT sum(f_value"
               + "".join(f" + d{i}_attr" for i in range(k))
               + ") FROM fact "
               + " ".join(f"JOIN dim{i} ON f_d{i}key = d{i}_key"
                          for i in range(k)))
        rec = {"dims": k, "hit_rate": hit_rate,
               "fact_rows": fact_rows, "dim_rows": dim_rows}

        s.execute("SET SESSION enable_multiway_join = 'true'")
        before = MULTIJOIN_FUSED_PROBES.value()
        fused_res, _, fused_ms = run_config(s, sql, runs=runs, prewarm=2)
        rec["fused_engaged"] = \
            MULTIJOIN_FUSED_PROBES.value() > before
        s.execute("SET SESSION enable_multiway_join = 'false'")
        pair_res, _, pair_ms = run_config(s, sql, runs=runs, prewarm=2)
        assert fused_res.rows == pair_res.rows, \
            (k, hit_rate, fused_res.rows, pair_res.rows)
        rec["fused_ms"] = round(fused_ms, 3)
        rec["pairwise_ms"] = round(pair_ms, 3)
        rec["fused_vs_pairwise"] = round(
            pair_ms / max(fused_ms, 1e-6), 2)
        records.append(rec)

    out = {"metric": "star_micro_ms", "device": str(jax.devices()[0]),
           "mode": mode, "smoke": not on_tpu, "records": records}
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# --scan-micro: zone-map pruning + prefetch-pipeline scan-path microbench
# ---------------------------------------------------------------------------

def scan_micro(rows=None, runs=3, out_path="BENCH_scan_micro.json"):
    """Microbenchmark of the round-14 scan path, three claims in one
    artifact:

    1. `records`: a clustered table swept across predicate
       selectivities with zone-map pruning on vs off — end-to-end
       engine walls (scan cache invalidated so the scan really runs),
       zones/rows-pruned counters, and a bit-exactness check between
       the two modes.
    2. `decode`: the same data written as multi-stripe ORC (zlib) and
       multi-row-group parquet, re-read with read-level `predicates=` —
       decoded rows and skipped stripes/row groups per selectivity
       prove statistics pruning cuts decode work (>= 10x at 0.01%).
    3. `prefetch`: a multi-chunk aggregation with the fact cache
       disabled so exec/chunked.py really decodes per chunk, at
       prefetch_depth 0 (serial) vs 2 (pipelined); chunk_spans record
       decode/compute/wall so overlap is visible (pipelined wall <
       serial decode+compute sum).

    Under JAX_PLATFORMS=cpu the shape shrinks to a smoke configuration
    (walls meaningless there; the decode-reduction ratios are
    measurement-grade anywhere since they count rows, not seconds)."""
    import tempfile

    import jax

    from trino_tpu.batch import Field, Schema
    from trino_tpu.connectors.parquetdir import flatten_table
    from trino_tpu.connectors.tpch.datagen import TableData
    from trino_tpu.exec.session import Session
    from trino_tpu.formats.orc import read_orc_file, write_orc
    from trino_tpu.formats.parquet import read_parquet_file, write_parquet
    from trino_tpu.types import BIGINT, DOUBLE

    on_tpu = jax.default_backend() == "tpu"
    mode = "device" if on_tpu else "cpu"
    if rows is None:
        rows = (1 << 24) if on_tpu else (1 << 17)
    zone_rows = max(1024, rows // 64)            # 64 zones / stripes
    rng = np.random.default_rng(14)
    selectivities = (0.0001, 0.01, 0.5, 1.0)

    # clustered key -> tight zones; v is the aggregated payload
    data = TableData("scan_micro", Schema((
        Field("k", BIGINT), Field("v", DOUBLE))),
        [np.arange(rows, dtype=np.int64),
         rng.standard_normal(rows)])

    s = Session()
    s.catalog.connector("memory").create_table("default", "scan_micro",
                                               data)
    s.execute(f"SET SESSION zone_map_rows = {zone_rows}")

    records = []
    for sel in selectivities:
        lim = max(1, int(rows * sel))
        q = (f"SELECT count(*) AS c, sum(v) AS sv FROM "
             f"memory.default.scan_micro WHERE k < {lim}")
        rec = {"selectivity": sel, "rows": rows, "zone_rows": zone_rows}
        results = {}
        for setting in ("true", "false"):
            s.execute(f"SET SESSION enable_zone_map_pruning = {setting}")
            s.execute(q)                         # warm (compile + plan)
            st = s.executor.stats
            zones0, rowsp0 = st.scan_zones_pruned, st.scan_rows_pruned
            walls = []
            for _ in range(runs):
                s.executor.invalidate_scan_cache()
                t0 = time.monotonic()
                results[setting] = s.execute(q).rows
                walls.append(time.monotonic() - t0)
            tag = "prune_on" if setting == "true" else "prune_off"
            rec[f"{tag}_ms"] = round(min(walls) * 1000, 3)
            if setting == "true":
                rec["zones_pruned_per_run"] = \
                    (st.scan_zones_pruned - zones0) // runs
                rec["rows_pruned_per_run"] = \
                    (st.scan_rows_pruned - rowsp0) // runs
        rec["identical"] = results["true"] == results["false"]
        records.append(rec)

    # ---- claim 2: file-level decode reduction ---------------------------
    tmp = tempfile.mkdtemp(prefix="scan_micro_")
    flat = flatten_table(data, "bench")
    orc_path = os.path.join(tmp, "scan_micro.orc")
    pq_path = os.path.join(tmp, "scan_micro.parquet")
    write_orc(orc_path, *flat, stripe_rows=zone_rows,
              compression="zlib")
    write_parquet(pq_path, *flat, row_group_rows=zone_rows)
    decode = []
    for sel in selectivities:
        lim = max(1, int(rows * sel))
        pred = {"k": (0, lim - 1)}
        of = read_orc_file(orc_path, predicates=pred)
        pf = read_parquet_file(pq_path, predicates=pred)
        decode.append({
            "selectivity": sel,
            "orc_decoded_rows": int(len(of.columns[0])),
            "orc_skipped_stripes": of.skipped_stripes,
            "orc_total_stripes": of.total_stripes,
            "parquet_decoded_rows": int(len(pf.columns[0])),
            "parquet_skipped_row_groups": pf.skipped_row_groups,
            "parquet_total_row_groups": pf.total_row_groups,
            "decode_reduction_x": round(
                rows / max(1, len(of.columns[0])), 1)})
    for p in (orc_path, pq_path):
        try:
            os.remove(p)
        except OSError:
            pass

    # ---- claim 3: prefetch overlap (chunked driver really decoding) ----
    s2 = Session()
    s2.executor.enable_fact_cache = False        # force per-chunk decode
    s2.execute("SET SESSION spill_chunk_rows = 8192")
    s2.execute("SET SESSION enable_zone_map_pruning = false")
    pq_sql = ("SELECT l_returnflag, count(*) AS c, "
              "sum(l_extendedprice) AS s FROM tpch.tiny.lineitem "
              "GROUP BY l_returnflag ORDER BY l_returnflag")
    prefetch = {}
    pf_results = {}
    for depth in (0, 2):
        s2.execute(f"SET SESSION prefetch_depth = {depth}")
        s2.execute(pq_sql)                       # warm (compile)
        walls, spans = [], None
        for _ in range(runs):
            t0 = time.monotonic()
            pf_results[depth] = s2.execute(pq_sql).rows
            walls.append(time.monotonic() - t0)
            spans = getattr(s2.executor, "chunk_spans", None)
        ent = {"wall_ms": round(min(walls) * 1000, 3)}
        if spans:
            for k2, v2 in spans.items():
                ent[k2] = round(v2, 4) if isinstance(v2, float) else v2
        prefetch[f"depth{depth}"] = ent
    prefetch["identical"] = pf_results.get(0) == pf_results.get(2)
    d2 = prefetch["depth2"]
    if "decode_s" in d2 and "compute_s" in d2 and "wall_s" in d2:
        # the overlap headline: the pipelined loop's own wall vs the
        # serialized sum of its decode+compute spans (same run, so no
        # cross-run noise enters the comparison)
        prefetch["serialized_sum_ms"] = round(
            (d2["decode_s"] + d2["compute_s"]) * 1000, 3)
        prefetch["overlap_win"] = \
            d2["wall_s"] * 1000 < prefetch["serialized_sum_ms"]

    out = {"metric": "scan_micro_ms", "device": str(jax.devices()[0]),
           "mode": mode, "smoke": not on_tpu, "records": records,
           "decode": decode, "prefetch": prefetch}
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# --chaos: seeded randomized fault-injection soak (round-7 robustness PR)
# ---------------------------------------------------------------------------

# name -> (sql, unordered): unordered queries (no ORDER BY) compare as
# multisets — page arrival order legitimately varies under retry/hedging
CHAOS_QUERIES = {
    "agg": (("SELECT l_returnflag, l_linestatus, sum(l_quantity) AS q, "
             "count(*) AS c FROM lineitem WHERE l_shipdate <= DATE "
             "'1998-09-02' GROUP BY l_returnflag, l_linestatus "
             "ORDER BY l_returnflag, l_linestatus"), False),
    "concat": (("SELECT l_orderkey, l_quantity FROM lineitem "
                "WHERE l_shipdate > DATE '1998-11-01'"), True),
    "sort": (("SELECT l_orderkey, l_linenumber FROM lineitem "
              "WHERE l_shipdate > DATE '1998-10-01' "
              "ORDER BY l_orderkey, l_linenumber"), False),
}


def _chaos_rows(rows):
    return [tuple(v if v is None or isinstance(v, (int, float, str, bool))
                  else str(v) for v in r) for r in rows]


def chaos_soak(n_seeds=None, cluster=None, out_path="BENCH_chaos.json"):
    """Seeded chaos soak: run the query matrix under generated fault
    schedules (crash / delay / drop / corrupt at every distributed
    control-plane point) and require bit-identical results vs the
    fault-free run — zero wrong-answer escapes, corrupted pages always
    caught by the CRC32C page checksums and recovered via task retry.

    CPU smoke path: a 3-worker in-process cluster over real HTTP, tiny
    schema, small splits. Emits BENCH_chaos.json with injected-fault
    counts and recovery latencies (fault wall minus fault-free median).
    Pass `cluster=(coord, workers, session)` to reuse a live cluster
    (the slow-tier pytest soak does); `out_path=None` skips the file."""
    from trino_tpu.client.client import Client, QueryError
    from trino_tpu.exec.session import Session
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.failuredetector import HeartbeatFailureDetector
    from trino_tpu.server.failureinjector import FailureInjector
    from trino_tpu.server.worker import WorkerServer

    n = n_seeds if n_seeds is not None else \
        int(os.environ.get("TRINO_TPU_CHAOS_SEEDS", 50))
    budget_s = float(os.environ.get("TRINO_TPU_CHAOS_BUDGET_S", 600))
    t_start = time.monotonic()
    owns = cluster is None
    detector = None
    if owns:
        session = Session(default_schema="tiny")
        coord = CoordinatorServer(session, retry_policy="QUERY").start()
        coord.state.scheduler.split_rows = 8192
        workers = [WorkerServer(f"chaos-w{i}", coord.uri,
                                announce_interval_s=0.1,
                                catalog=session.catalog).start()
                   for i in range(3)]
        detector = HeartbeatFailureDetector(coord.state,
                                            interval_s=0.2).start()
    else:
        coord, workers, session = cluster
        detector = coord.state.failure_detector
    sched = coord.state.scheduler
    saved = (sched.max_task_retries, sched.hedge_min_s,
             sched.hedge_multiplier)
    # chaos schedules can burn several retry rounds; hedge threshold
    # sits well below the injected straggler delays (up to 1s) so DELAY
    # faults actually exercise the speculative re-dispatch path
    sched.max_task_retries = 8
    sched.hedge_min_s, sched.hedge_multiplier = 0.3, 2.0
    client = Client(coord.uri, user="chaos", timeout_s=120)

    def wait_active(k=3, timeout=5.0):
        deadline = time.time() + timeout
        while len(coord.state.active_nodes()) < k and \
                time.time() < deadline:
            time.sleep(0.05)

    wait_active()
    # fault-free baselines THROUGH the cluster (also warms the worker
    # fragments so XLA compile doesn't pollute recovery latencies)
    baselines, base_wall = {}, {}
    for name, (q, unordered) in CHAOS_QUERIES.items():
        walls = []
        for _ in range(2):
            sched.spool.clear()
            t0 = time.monotonic()
            r = client.execute(q)
            walls.append(time.monotonic() - t0)
        rows = _chaos_rows(r.rows)
        baselines[name] = sorted(rows) if unordered else rows
        base_wall[name] = min(walls)

    rec = {"metric": "chaos_soak", "schedules": 0, "queries_run": 0,
           "wrong_answers": 0, "failed_queries": 0, "injected_total": 0,
           "injected_by_fault": {}, "corrupt_detected": 0,
           "recovery_latency_s": [], "task_retries": 0,
           "hedged_tasks": 0, "spool_hits": 0, "budget_exhausted": False}
    retries0 = sched.stats["task_retries"]
    hedged0 = sched.stats["hedged_tasks"]
    spool0 = sched.stats["spool_hits"]
    crc0 = sched.stats["checksum_failures"]
    for seed in range(n):
        if time.monotonic() - t_start > budget_s:
            rec["budget_exhausted"] = True
            break
        inj = FailureInjector.from_seed(seed, max_delay_s=1.0)
        sched.failure_injector = inj
        if detector is not None:
            detector.injector = inj
        for w in workers:
            w.task_manager.injector = inj
        try:
            for name, (q, unordered) in CHAOS_QUERIES.items():
                sched.spool.clear()
                fired_before = inj.injected_count
                t0 = time.monotonic()
                try:
                    r = client.execute(q)
                except QueryError:
                    rec["failed_queries"] += 1
                    continue
                wall = time.monotonic() - t0
                rec["queries_run"] += 1
                got = _chaos_rows(r.rows)
                if unordered:
                    got = sorted(got)
                if got != baselines[name]:
                    rec["wrong_answers"] += 1
                if inj.injected_count > fired_before:
                    rec["recovery_latency_s"].append(
                        round(max(0.0, wall - base_wall[name]), 3))
        finally:
            sched.failure_injector = None
            if detector is not None:
                detector.injector = None
            for w in workers:
                w.task_manager.injector = None
        rec["schedules"] += 1
        rec["injected_total"] += inj.injected_count
        for fault, cnt in inj.injected_by_fault.items():
            if cnt:
                rec["injected_by_fault"][fault] = \
                    rec["injected_by_fault"].get(fault, 0) + cnt
        inj.clear()
        wait_active()
    rec["task_retries"] = sched.stats["task_retries"] - retries0
    rec["hedged_tasks"] = sched.stats["hedged_tasks"] - hedged0
    rec["spool_hits"] = sched.stats["spool_hits"] - spool0
    rec["corrupt_detected"] = sched.stats["checksum_failures"] - crc0 + \
        sched.spool.checksum_rejects
    lat = sorted(rec["recovery_latency_s"])
    rec["recovery_p50_s"] = lat[len(lat) // 2] if lat else 0.0
    rec["recovery_p95_s"] = lat[int(len(lat) * 0.95)] if lat else 0.0
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    sched.max_task_retries, sched.hedge_min_s, sched.hedge_multiplier = \
        saved
    if owns:
        if detector is not None:
            detector.stop()
        for w in workers:
            w.stop()
        coord.stop()
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


# ---------------------------------------------------------------------------
# --overload: deadlines / cancellation / admission-control soak (round-22)
# ---------------------------------------------------------------------------

def overload_soak(cluster=None, out_path="BENCH_overload.json"):
    """Query-lifetime enforcement soak: saturating admission against a
    shrunken resource group (queue-full + queued-time rejections),
    HANG-wedged distributed queries that only the coordinator-stamped
    deadline can unstick, and a mass-cancel wave DELETEing mid-flight
    queries. Hard gates: 0 wrong answers among everything that
    FINISHED, every expired/canceled query terminal on every node
    within grace, and worker memory pools drained to zero. Emits
    BENCH_overload.json; the cancel-to-terminal and deadline-overshoot
    walls gate as their own --check-regressions series."""
    from trino_tpu.client.client import Client, QueryError
    from trino_tpu.exec.session import Session
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.failureinjector import (DELAY, HANG,
                                                  FailureInjector)
    from trino_tpu.server.worker import WorkerServer

    t_start = time.monotonic()
    owns = cluster is None
    if owns:
        session = Session(default_schema="tiny")
        coord = CoordinatorServer(session, retry_policy="QUERY").start()
        coord.state.scheduler.split_rows = 8192
        workers = [WorkerServer(f"ovl-w{i}", coord.uri,
                                announce_interval_s=0.1,
                                catalog=session.catalog).start()
                   for i in range(3)]
    else:
        coord, workers, session = cluster
    sched = coord.state.scheduler
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)

    q_agg, _ = CHAOS_QUERIES["agg"]
    # fault-free baseline THROUGH the cluster (rows as the protocol
    # serializes them) — also warms the worker fragments so XLA compile
    # never eats a deadline
    want = _chaos_rows(
        Client(coord.uri, user="overload").execute(q_agg).rows)

    rec = {"metric": "overload", "submitted": 0, "finished": 0,
           "wrong_answers": 0, "rejected_queue_full": 0,
           "rejected_queued_deadline": 0, "deadline_kills": 0,
           "canceled": 0, "unexpected_errors": 0, "errors": []}

    def note_error(stage, e):
        rec["unexpected_errors"] += 1
        if len(rec["errors"]) < 8:
            rec["errors"].append(f"{stage}: {e}")

    # -- wave 1: saturating admission against a shrunken root group ----
    client_sets = Client(coord.uri, user="overload")
    client_sets.execute("SET SESSION query_max_queued_time_s = 0.5")
    root = coord.state.dispatcher.resource_groups.root
    saved_rg = (root.config.hard_concurrency_limit,
                root.config.max_queued)
    root.config.hard_concurrency_limit = 1
    root.config.max_queued = 2
    lock = threading.Lock()

    def one_query():
        rec["submitted"] += 1
        try:
            r = Client(coord.uri, user="overload",
                       timeout_s=120).execute(q_agg)
        except QueryError as e:
            with lock:
                if e.error_name == "QUERY_QUEUE_FULL":
                    rec["rejected_queue_full"] += 1
                elif e.error_name == "QUERY_EXCEEDED_QUEUED_TIME":
                    rec["rejected_queued_deadline"] += 1
                else:
                    note_error("admission", e)
            return
        with lock:
            rec["finished"] += 1
            if _chaos_rows(r.rows) != want:
                rec["wrong_answers"] += 1

    try:
        threads = [threading.Thread(target=one_query)
                   for _ in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        root.config.hard_concurrency_limit, root.config.max_queued = \
            saved_rg
        # reset via the session dict, not a SET statement: a SET issued
        # while the deadline property is still armed gets stamped with
        # that deadline and can itself be killed mid-drain
        session.properties.pop("query_max_queued_time_s", None)

    # -- wave 2: HANG-wedged queries unstuck only by their deadline ----
    n_hang = 3
    deadline_s = 1.0
    client_sets.execute(
        f"SET SESSION query_max_run_time_s = {deadline_s}")
    inj = FailureInjector(seed=722)
    inj.inject("WORKER_TASK_RUN", times=4 * n_hang, fault=HANG,
               delay_s=8.0)
    for w in workers:
        w.task_manager.injector = inj
    overshoots = []
    try:
        for _ in range(n_hang):
            # drop spooled task results so the query actually re-runs
            # on the workers (and hits the HANG) instead of being
            # served from the exchange spool
            sched.spool.clear()
            rec["submitted"] += 1
            t0 = time.monotonic()
            try:
                Client(coord.uri, user="overload",
                       timeout_s=30).execute(q_agg)
                note_error("hang", "wedged query FINISHED under a "
                                   "deadline that should have fired")
            except QueryError as e:
                wall = time.monotonic() - t0
                if e.error_name == "QUERY_EXCEEDED_RUN_TIME":
                    rec["deadline_kills"] += 1
                    overshoots.append(
                        round(max(0.0, wall - deadline_s) * 1000, 1))
                else:
                    note_error("hang", e)
    finally:
        inj.clear()                       # release every live HANG
        for w in workers:
            w.task_manager.injector = None
        session.properties.pop("query_max_run_time_s", None)

    # -- wave 3: mass-cancel of mid-flight distributed queries ---------
    n_cancel = 4
    inj = FailureInjector(seed=723)
    inj.inject("WORKER_TASK_RUN", times=8 * n_cancel, fault=DELAY,
               delay_s=1.0)
    for w in workers:
        w.task_manager.injector = inj
    cancel_walls = []
    try:
        # same spool hazard as wave 2: released wave-2 tasks may have
        # spooled their pages, and a spool-served query FINISHES before
        # the DELETE can land
        sched.spool.clear()
        cancel_client = Client(coord.uri, user="overload")
        live = []
        for _ in range(n_cancel):
            rec["submitted"] += 1
            doc = cancel_client._submit(q_agg)
            live.append((doc["id"], doc.get("nextUri")))
        # wait until the wave is mid-flight (remote tasks dispatched —
        # the exec lock serializes dispatch, so the rest of the wave is
        # canceled wherever it stands: queued, planning, or waiting),
        # then DELETE everything back-to-back
        deadline = time.time() + 15
        while time.time() < deadline and not any(
                sched._live_tasks.get(qid) for qid, _ in live):
            time.sleep(0.02)
        for qid, next_uri in live:
            t0 = time.monotonic()
            try:
                cancel_client._request("DELETE", next_uri)
            except Exception as e:  # noqa: BLE001
                note_error("cancel", e)
                continue
            tq = coord.state.tracker.get(qid)
            deadline = time.time() + 10
            while not tq.state_machine.is_done() and \
                    time.time() < deadline:
                time.sleep(0.01)
            if tq.state == "CANCELED":
                rec["canceled"] += 1
                cancel_walls.append(
                    round((time.monotonic() - t0) * 1000, 1))
            else:
                note_error("cancel", f"{qid} ended {tq.state}")
    finally:
        inj.clear()
        for w in workers:
            w.task_manager.injector = None

    # -- grace: every node terminal, every pool drained ----------------
    def all_tasks_terminal():
        return all(t.state not in ("PENDING", "RUNNING")
                   for w in workers
                   for t in list(w.task_manager.tasks.values()))

    def pools_drained():
        return all(w.task_manager.memory_info().get("reserved", 0) == 0
                   for w in workers)

    grace = time.time() + 15
    while not (all_tasks_terminal() and pools_drained()) and \
            time.time() < grace:
        time.sleep(0.05)
    rec["tasks_terminal"] = all_tasks_terminal()
    rec["pools_drained"] = pools_drained()

    cancel_walls.sort()
    overshoots.sort()
    rec["cancel_terminal_p50_ms"] = \
        cancel_walls[len(cancel_walls) // 2] if cancel_walls else None
    rec["cancel_terminal_max_ms"] = \
        cancel_walls[-1] if cancel_walls else None
    rec["deadline_overshoot_p50_ms"] = \
        overshoots[len(overshoots) // 2] if overshoots else None
    rec["rejected_total"] = (rec["rejected_queue_full"] +
                             rec["rejected_queued_deadline"])
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    rec["passed"] = bool(
        rec["wrong_answers"] == 0 and rec["unexpected_errors"] == 0 and
        rec["deadline_kills"] == n_hang and
        rec["canceled"] == n_cancel and rec["finished"] >= 1 and
        rec["tasks_terminal"] and rec["pools_drained"])
    if owns:
        for w in workers:
            w.stop()
        coord.stop()
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


# ---------------------------------------------------------------------------
# --write-chaos: exactly-once distributed-write soak (round-18 PR)
# ---------------------------------------------------------------------------

WRITE_CHAOS_SRC = ("SELECT o_orderkey, o_custkey, o_orderstatus, "
                   "o_totalprice FROM tpch.tiny.orders")


def write_chaos_soak(n_seeds=None, out_path="BENCH_write_chaos.json"):
    """Seeded write-chaos soak: distributed CTAS with kills injected at
    each write-protocol boundary (WRITE_STAGE / WRITE_COMMIT /
    WRITE_PUBLISH, faults rotating through RAISE / CRASH / DELAY plus
    torn-journal CORRUPT appends, some seeds with forced duplicate
    hedged attempts). Every seed's committed table must equal the
    fault-free row multiset — 0 lost rows, 0 duplicate rows — and leave
    0 orphaned staging files or journals. Pre-intent failures are
    retried under the SAME query id, so the soak also proves commit
    idempotence across whole-query retries. Emits BENCH_write_chaos.json
    with per-point commit-wall percentiles for the regression gate."""
    import shutil as _shutil
    import tempfile
    from collections import Counter

    from trino_tpu.connectors.orcdir import OrcConnector
    from trino_tpu.exec.session import Session
    from trino_tpu.server import writeprotocol as wp
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.failureinjector import (CORRUPT, CRASH, DELAY,
                                                  RAISE, WRITE_COMMIT,
                                                  WRITE_POINTS,
                                                  FailureInjector)
    from trino_tpu.server.worker import WorkerServer

    n = n_seeds if n_seeds is not None else \
        int(os.environ.get("TRINO_TPU_WRITE_CHAOS_SEEDS", 27))
    budget_s = float(os.environ.get("TRINO_TPU_WRITE_CHAOS_BUDGET_S", 420))
    t_start = time.monotonic()
    root = tempfile.mkdtemp(prefix="write_chaos_")
    os.makedirs(os.path.join(root, "out"))
    session = Session(default_schema="tiny")
    conn = OrcConnector(root)
    session.catalog.register("orc", conn)
    coord = CoordinatorServer(session, retry_policy="QUERY").start()
    sched = coord.state.scheduler
    sched.split_rows = 4096
    workers = [WorkerServer(f"wchaos-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)

    baseline = Counter(_chaos_rows(session.execute(WRITE_CHAOS_SRC).rows))
    rec = {"metric": "write_chaos", "seeds": 0, "writes_committed": 0,
           "failed_writes": 0, "query_retries": 0, "lost_rows": 0,
           "dup_rows": 0, "orphans": 0, "hedged_seeds": 0,
           "attempts_deduped": 0, "injected_total": 0,
           "injected_by_fault": {}, "injected_by_point": {},
           "points": {}, "budget_exhausted": False}
    walls = {p: [] for p in WRITE_POINTS}
    try:
        for seed in range(n):
            if time.monotonic() - t_start > budget_s:
                rec["budget_exhausted"] = True
                break
            point = WRITE_POINTS[seed % len(WRITE_POINTS)]
            fault = (RAISE, CRASH, DELAY)[(seed // 3) % 3]
            if point == WRITE_COMMIT and seed % 9 == 4:
                fault = CORRUPT          # torn intent-journal append
            inj = FailureInjector(seed=seed)
            inj.inject(point, times=1, fault=fault)
            sched.failure_injector = inj
            for w in workers:
                w.task_manager.injector = inj
            sched.force_write_hedge = seed % 4 == 3
            if sched.force_write_hedge:
                rec["hedged_seeds"] += 1
            tbl = f"w{seed}"
            qid = f"wchaos_{seed}"
            sql = f"CREATE TABLE orc.out.{tbl} AS {WRITE_CHAOS_SRC}"
            res = None
            t0 = time.monotonic()
            for _attempt in range(3):
                try:
                    res = sched.execute(sql, query_id=qid)
                    break
                except Exception:
                    # pre-intent abort: the QUERY retry policy reruns
                    # the same query id — exactly-once must hold
                    rec["query_retries"] += 1
            wall_ms = (time.monotonic() - t0) * 1000
            sched.failure_injector = None
            sched.force_write_hedge = False
            for w in workers:
                w.task_manager.injector = None
            rec["seeds"] += 1
            rec["injected_total"] += inj.injected_count
            rec["injected_by_point"][point] = \
                rec["injected_by_point"].get(point, 0) + inj.injected_count
            for f, cnt in inj.injected_by_fault.items():
                if cnt:
                    rec["injected_by_fault"][f] = \
                        rec["injected_by_fault"].get(f, 0) + cnt
            if res is None:
                rec["failed_writes"] += 1
                continue
            rec["writes_committed"] += 1
            walls[point].append(wall_ms)
            wr = (sched.last_query or {}).get("write") or {}
            rec["attempts_deduped"] += int(wr.get("deduped", 0))
            got = Counter(_chaos_rows(session.execute(
                f"SELECT o_orderkey, o_custkey, o_orderstatus, "
                f"o_totalprice FROM orc.out.{tbl}").rows))
            rec["lost_rows"] += sum((baseline - got).values())
            rec["dup_rows"] += sum((got - baseline).values())
            td = conn._table_dir("out", tbl)
            rec["orphans"] += len(os.listdir(wp.staging_dir(td))) \
                if os.path.isdir(wp.staging_dir(td)) else 0
            rec["orphans"] += sum(1 for f in os.listdir(td)
                                  if f.endswith(".journal")
                                  or f.startswith(".tmp."))
            conn.drop_table("out", tbl)
        # nothing may survive outside the published tables either
        for dirpath, dirnames, filenames in os.walk(root):
            rec["orphans"] += sum(1 for d in dirnames if d == ".staging")
            rec["orphans"] += sum(1 for f in filenames
                                  if f.endswith(".journal")
                                  or f.startswith(".tmp."))
    finally:
        sched.failure_injector = None
        sched.force_write_hedge = False
        for w in workers:
            w.task_manager.injector = None
            w.stop()
        coord.stop()
        _shutil.rmtree(root, ignore_errors=True)
    for point, ws in walls.items():
        if ws:
            ws = sorted(ws)
            rec["points"][point] = {
                "commits": len(ws),
                "p50_ms": round(ws[len(ws) // 2], 1),
                "p95_ms": round(ws[int(len(ws) * 0.95)], 1)}
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    rec["passed"] = (rec["lost_rows"] == 0 and rec["dup_rows"] == 0
                     and rec["orphans"] == 0
                     and rec["failed_writes"] == 0
                     and rec["injected_total"] >= rec["seeds"])
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


COORD_CHAOS_PHASES = ("QUEUED", "PLANNING", "RUNNING", "FINISHING",
                      "WRITE_COMMIT")


def coordinator_chaos_soak(n_seeds=None,
                           out_path="BENCH_coordinator_chaos.json"):
    """Seeded coordinator-kill soak (round 20 acceptance): for every
    seed, bring up a primary + warm standby sharing one durable query
    ledger and spool root plus two workers, submit a query through a
    multi-address client, and kill the primary at a rotating lifecycle
    phase (QUEUED / PLANNING / RUNNING / FINISHING / WRITE_COMMIT —
    the write phase crashes the staged-write commit mid-flight so
    exactly-once must hold across the failover). Promotion alternates
    by seed parity between detector-driven and admin `PUT
    /v1/info/state`. The client must finish every seed with bit-exact
    rows and NO visible error: 0 wrong results, 0 lost rows, 0
    duplicate rows. Emits BENCH_coordinator_chaos.json with
    failover-to-first-result percentiles for the regression gate."""
    import shutil as _shutil
    import tempfile
    import threading
    from collections import Counter
    from urllib.request import Request, urlopen

    from trino_tpu.client.client import Client
    from trino_tpu.connectors.orcdir import OrcConnector
    from trino_tpu.exec.session import Session
    from trino_tpu.metrics import COORDINATOR_FAILOVERS
    from trino_tpu.server import ledger as led
    from trino_tpu.server import writeprotocol as wp
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.failureinjector import (CRASH, DELAY,
                                                  WRITE_COMMIT,
                                                  FailureInjector)
    from trino_tpu.server.security import internal_headers
    from trino_tpu.server.worker import WorkerServer

    n = n_seeds if n_seeds is not None else \
        int(os.environ.get("TRINO_TPU_COORD_CHAOS_SEEDS", 20))
    budget_s = float(os.environ.get("TRINO_TPU_COORD_CHAOS_BUDGET_S",
                                    600))
    t_start = time.monotonic()
    read_sql = ("SELECT n_regionkey, count(*) AS c FROM nation "
                "GROUP BY n_regionkey ORDER BY n_regionkey")
    read_expect = [(0, 5), (1, 5), (2, 5), (3, 5), (4, 5)]
    write_src = ("SELECT o_orderkey, o_custkey, o_orderstatus, "
                 "o_totalprice FROM tpch.tiny.orders")
    rec = {"metric": "coordinator_chaos", "seeds": 0,
           "wrong_results": 0, "lost_rows": 0, "dup_rows": 0,
           "client_errors": 0, "failovers": 0,
           "detector_promotions": 0, "admin_promotions": 0,
           "kills_by_phase": {}, "resumed_by_mode": {},
           "budget_exhausted": False}
    fo_walls = []
    write_baseline = None
    for seed in range(n):
        if time.monotonic() - t_start > budget_s:
            rec["budget_exhausted"] = True
            break
        phase = COORD_CHAOS_PHASES[seed % len(COORD_CHAOS_PHASES)]
        admin = seed % 2 == 1           # else detector-driven
        write_phase = phase == "WRITE_COMMIT"
        root = tempfile.mkdtemp(prefix="coord_chaos_")
        ledger = os.path.join(root, "query.ledger")
        spool = os.path.join(root, "spool")
        s1 = Session(default_schema="tiny")
        s2 = Session(default_schema="tiny")
        conn2 = None
        if write_phase:
            os.makedirs(os.path.join(root, "orc", "out"))
            s1.catalog.register("orc", OrcConnector(
                os.path.join(root, "orc")))
            conn2 = OrcConnector(os.path.join(root, "orc"))
            s2.catalog.register("orc", conn2)
        primary = CoordinatorServer(s1, ledger_path=ledger,
                                    node_id=f"p{seed}",
                                    spool_root=spool).start()
        standby = CoordinatorServer(s2, ledger_path=ledger,
                                    node_id=f"s{seed}", role="standby",
                                    peer_uri=primary.uri,
                                    spool_root=spool,
                                    standby_interval_s=0.1,
                                    auto_promote=not admin).start()
        workers = [WorkerServer(f"cc{seed}w{i}", primary.uri,
                                announce_interval_s=0.1,
                                catalog=s1.catalog).start()
                   for i in range(2)]
        deadline = time.time() + 10
        while len(primary.state.active_nodes()) < 2 and \
                time.time() < deadline:
            time.sleep(0.02)
        for w in workers:
            w.announce_once()           # learn the standby address now
        inj = FailureInjector(seed=seed)
        if write_phase:
            primary.state.scheduler.split_rows = 4096
            primary.state.scheduler.failure_injector = inj
            # the commit dies mid-flight on the (sealed) primary; the
            # promoted standby re-executes and must dedup to one table
            inj.inject(WRITE_COMMIT, times=1, fault=CRASH)
            sql = f"CREATE TABLE orc.out.c{seed} AS {write_src}"
        else:
            primary.state.dispatcher.failure_injector = inj
            if phase in ("RUNNING", "FINISHING"):
                inj.inject("EXECUTION", times=1, fault=DELAY,
                           delay_s=1.5, match_sql="n_regionkey")
            sql = read_sql
        client = Client([primary.uri, standby.uri],
                        user=f"chaos{seed}", timeout_s=120)
        out = {}

        def run(client=client, sql=sql, out=out):
            try:
                out["r"] = client.execute(sql)
            except Exception as e:  # noqa: BLE001 — the gate counts it
                out["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # kill when the primary's registry first shows the query at (or
        # past) the target phase — a bounded watch, so late phases that
        # flash by still get a kill near the boundary
        target = "RUNNING" if write_phase else phase
        observed = None
        deadline = time.time() + 8
        while time.time() < deadline and observed is None:
            for tq in primary.state.tracker.all():
                if led._rank(tq.state) >= led._rank(target):
                    observed = tq.state
                    break
            if observed is None:
                time.sleep(0.002)
        if phase == "FINISHING" and observed == "RUNNING":
            time.sleep(1.2)             # drift toward the boundary
        t_kill = time.monotonic()
        primary.kill()
        rec["kills_by_phase"][phase] = \
            rec["kills_by_phase"].get(phase, 0) + 1
        if admin:
            try:
                req = Request(f"{standby.uri}/v1/info/state",
                              data=json.dumps(
                                  {"state": "PRIMARY"}).encode(),
                              headers={"Content-Type":
                                       "application/json",
                                       **internal_headers()},
                              method="PUT")
                with urlopen(req, timeout=15):
                    pass
                rec["admin_promotions"] += 1
            except Exception:  # noqa: BLE001 — client error will gate
                pass
        else:
            rec["detector_promotions"] += 1
        t.join(timeout=120)
        rec["seeds"] += 1
        if "r" not in out or t.is_alive():
            rec["client_errors"] += 1
        else:
            r = out["r"]
            fo_walls.append((time.monotonic() - t_kill) * 1000)
            rec["failovers"] += r.failovers
            if write_phase:
                got = Counter(_chaos_rows(s2.execute(
                    f"SELECT o_orderkey, o_custkey, o_orderstatus, "
                    f"o_totalprice FROM orc.out.c{seed}").rows))
                if write_baseline is None:
                    write_baseline = Counter(
                        _chaos_rows(s2.execute(write_src).rows))
                rec["lost_rows"] += sum(
                    (write_baseline - got).values())
                rec["dup_rows"] += sum((got - write_baseline).values())
            else:
                if [tuple(x) for x in r.rows] != read_expect:
                    rec["wrong_results"] += 1
            tq = standby.state.tracker.get(r.query_id)
            mode = getattr(tq, "resumed", None) if tq else None
            if mode:
                rec["resumed_by_mode"][mode] = \
                    rec["resumed_by_mode"].get(mode, 0) + 1
        for w in workers:
            w.kill()
        standby.kill()
        for c in (primary, standby):
            c.state.dispatcher.pool.shutdown(wait=False)
        _shutil.rmtree(root, ignore_errors=True)
    if fo_walls:
        ws = sorted(fo_walls)
        rec["failover_to_result_p50_ms"] = round(ws[len(ws) // 2], 1)
        rec["failover_to_result_p99_ms"] = round(
            ws[min(len(ws) - 1, int(len(ws) * 0.99))], 1)
    rec["coordinator_failovers_total"] = COORDINATOR_FAILOVERS.value()
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    rec["passed"] = (rec["wrong_results"] == 0 and rec["lost_rows"] == 0
                     and rec["dup_rows"] == 0
                     and rec["client_errors"] == 0
                     and rec["failovers"] >= rec["seeds"] > 0)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


def memory_pressure_soak(n_queries=None, out_path="BENCH_memory.json"):
    """Memory-pressure soak (round 9 acceptance): >= 20 concurrent
    queries against a 3-worker cluster with every executor pool clamped
    to 25% of the measured working set. Requires 0 wrong answers and 0
    worker crashes — queries must survive by spilling (host-spill
    radix partitioning, revocable partial state) or fail cleanly with
    QUERY_EXCEEDED_MEMORY, never by taking a worker down. Emits
    BENCH_memory.json with spill/backpressure/killer counters."""
    import threading as _th

    from trino_tpu.client.client import Client, QueryError
    from trino_tpu.exec.session import Session
    from trino_tpu.metrics import REGISTRY
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.failuredetector import HeartbeatFailureDetector
    from trino_tpu.server.worker import WorkerServer

    n = n_queries if n_queries is not None else \
        int(os.environ.get("TRINO_TPU_MEMSOAK_QUERIES", 24))
    queries = {
        "join_agg": ("SELECT o_custkey, count(*) AS c, "
                     "sum(o_totalprice) AS s FROM orders JOIN customer "
                     "ON o_custkey = c_custkey WHERE c_acctbal > 0 "
                     "GROUP BY o_custkey ORDER BY s DESC, o_custkey "
                     "LIMIT 50"),
        "wide_agg": ("SELECT l_returnflag, l_linestatus, "
                     "sum(l_quantity) AS q, count(*) AS c, "
                     "min(l_discount) AS mn, max(l_tax) AS mx "
                     "FROM lineitem GROUP BY l_returnflag, l_linestatus "
                     "ORDER BY l_returnflag, l_linestatus"),
        "big_group": ("SELECT l_orderkey, sum(l_quantity) AS q "
                      "FROM lineitem GROUP BY l_orderkey "
                      "ORDER BY q DESC, l_orderkey LIMIT 20"),
        "point": "SELECT count(*) FROM nation",
    }
    # 1) measure the working set at an unconstrained pool (rows
    # normalized like the protocol does — Decimal/date render as text)
    t_start = time.monotonic()
    session = Session(default_schema="tiny")
    baselines = {}
    for name, q in queries.items():
        baselines[name] = _chaos_rows(session.execute(q).rows)
    working_set = session.executor.pool.peak
    limit = max(1 << 20, working_set // 4)

    # 2) cluster with every pool clamped to 25%
    session.properties["query_max_memory_mb"] = max(1, limit >> 20)
    session.executor.pool.set_limit(limit)
    coord = CoordinatorServer(session, max_concurrency=4).start()
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"mem-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    for w in workers:
        w.task_manager._executor.pool.set_limit(limit)
        w.task_manager.max_buffer_bytes = 1 << 20   # exercise backpressure
    detector = HeartbeatFailureDetector(coord.state,
                                        interval_s=0.2).start()
    coord.state.memory_manager.interval_s = 0.2
    coord.state.memory_manager.start()

    reg0 = REGISTRY.snapshot()
    rec = {"metric": "memory_pressure_soak", "queries": 0,
           "wrong_answers": 0, "failed_queries": 0,
           "oom_user_errors": 0, "worker_crashes": 0,
           "concurrent": n, "working_set_bytes": int(working_set),
           "pool_limit_bytes": int(limit)}
    lock = _th.Lock()

    def one(i: int) -> None:
        name = list(queries)[i % len(queries)]
        client = Client(coord.uri, user=f"soak{i}", timeout_s=180)
        try:
            rows = client.execute(queries[name]).rows
        except QueryError as e:
            with lock:
                if e.error_name == "QUERY_EXCEEDED_MEMORY":
                    rec["oom_user_errors"] += 1      # clean user error
                else:
                    rec["failed_queries"] += 1
            return
        except Exception:    # noqa: BLE001 — client-side transport
            with lock:       # failure: count it, never lose the thread
                rec["failed_queries"] += 1
            return
        with lock:
            rec["queries"] += 1
            if _chaos_rows(rows) != baselines[name]:
                rec["wrong_answers"] += 1

    threads = [_th.Thread(target=one, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)

    # 3) no worker crashed: every worker still answers /v1/status ACTIVE
    from urllib.request import urlopen
    for w in workers:
        try:
            with urlopen(f"{w.uri}/v1/status", timeout=5) as resp:
                ok = resp.status == 200
        except Exception:
            ok = False
        if not ok:
            rec["worker_crashes"] += 1

    after = REGISTRY.snapshot()

    def delta(key):
        return int(after.get(key, 0) - reg0.get(key, 0))

    rec["spill_bytes"] = delta(("trino_tpu_spill_bytes_total",))
    rec["spill_partitions"] = delta(("trino_tpu_spill_partitions_total",))
    rec["revocations"] = delta(("trino_tpu_memory_revocations_total",))
    rec["backpressure_waits"] = delta(
        ("trino_tpu_exchange_backpressure_waits_total",))
    rec["queries_killed_oom"] = delta(
        ("trino_tpu_queries_killed_oom_total",))
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    rec["passed"] = (rec["wrong_answers"] == 0 and
                     rec["worker_crashes"] == 0 and
                     rec["failed_queries"] == 0)
    coord.state.memory_manager.stop()
    detector.stop()
    for w in workers:
        w.stop()
    coord.stop()
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def concurrency_soak(n_clients=None, queries_per_client=None,
                     out_path="BENCH_concurrency.json"):
    """High-concurrency serving soak (round-11 acceptance): >= 100 mixed
    clients against one coordinator with the serving layer fully on
    (plan cache, result cache, CPU/TPU cost routing, micro-batching).
    Point/cached/small-aggregate traffic runs host-side WITHOUT the
    device exec lock while scan-heavy plans keep the device, so the mix
    must not serialize. Requires 0 wrong answers vs the uncached oracle
    (every HTTP result — cache hits, micro-batched rows, host-routed
    rows — compared bit-exact against a direct pre-server execution),
    nonzero result-cache/router/micro-batch counters, and a post-write
    rerun proving catalog-version invalidation. Emits
    BENCH_concurrency.json with throughput and p50/p99 per mix."""
    import tempfile
    import threading as _th

    from trino_tpu.client.client import Client, QueryError
    from trino_tpu.exec.session import Session
    from trino_tpu.metrics import REGISTRY
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.resourcegroups import (ResourceGroupConfig,
                                                 ResourceGroupManager)

    n = n_clients if n_clients is not None else \
        int(os.environ.get("TRINO_TPU_CONCURRENCY_CLIENTS", 120))
    per = queries_per_client if queries_per_client is not None else \
        int(os.environ.get("TRINO_TPU_CONCURRENCY_QUERIES", 5))
    t_start = time.monotonic()
    # fresh history file: stale medians from earlier rounds (cold
    # compile walls) would bias the router's baseline input
    hist = tempfile.NamedTemporaryFile(prefix="concurrency_hist_",
                                       suffix=".jsonl", delete=False)
    saved_hist_env = os.environ.get("TRINO_TPU_HISTORY_PATH")
    os.environ["TRINO_TPU_HISTORY_PATH"] = hist.name

    session = Session(default_schema="tiny")
    session.execute("CREATE TABLE memory.s.counters (k bigint, v bigint)")
    session.execute("INSERT INTO memory.s.counters VALUES (1, 10), (2, 20)")

    mixes = {
        "point": [f"SELECT n_name FROM nation WHERE n_nationkey = {k}"
                  for k in range(25)],
        "cached": ["SELECT r_name FROM region ORDER BY r_name",
                   "SELECT count(*) FROM supplier",
                   "SELECT v FROM memory.s.counters WHERE k = 2"],
        "small_agg": ["SELECT min(s_suppkey), max(s_suppkey) "
                      "FROM supplier",
                      "SELECT count(*) FROM customer"],
        "scan_heavy": [
            "SELECT l_returnflag, l_linestatus, sum(l_quantity) AS q, "
            "count(*) AS c FROM lineitem "
            "GROUP BY l_returnflag, l_linestatus "
            "ORDER BY l_returnflag, l_linestatus",
            "SELECT count(*) FROM orders JOIN customer "
            "ON o_custkey = c_custkey WHERE c_acctbal > 0"],
    }
    # uncached oracle: every distinct statement executed directly (no
    # serving layer) BEFORE the server starts — the soak's bit-exact
    # reference for cached/host/micro-batched paths alike
    oracle = {}
    for qs in mixes.values():
        for q in qs:
            oracle[q] = _chaos_rows(session.execute(q).rows)

    session.properties["enable_result_cache"] = True
    session.properties["enable_microbatch"] = True
    session.properties["microbatch_window_ms"] = 4.0
    coord = CoordinatorServer(session, max_concurrency=32).start()
    # the coordinator's history store is bound now: restore the env so
    # later stores in this process keep their configured path
    if saved_hist_env is None:
        os.environ.pop("TRINO_TPU_HISTORY_PATH", None)
    else:
        os.environ["TRINO_TPU_HISTORY_PATH"] = saved_hist_env
    coord.state.dispatcher.resource_groups = ResourceGroupManager(
        ResourceGroupConfig("root", hard_concurrency_limit=32,
                            max_queued=100_000))

    reg0 = REGISTRY.snapshot()
    mix_names = list(mixes)
    lock = _th.Lock()
    latencies = {m: [] for m in mix_names}
    rec = {"metric": "concurrency_soak", "clients": n,
           "queries_per_client": per, "queries": 0, "wrong_answers": 0,
           "failed_queries": 0}

    def one(i: int) -> None:
        mix = mix_names[i % len(mix_names)]
        qs = mixes[mix]
        client = Client(coord.uri, user=f"conc{i}", timeout_s=180,
                        poll_interval_s=0.005)
        for j in range(per):
            q = qs[(i + j) % len(qs)]
            t0 = time.monotonic()
            try:
                rows = client.execute(q).rows
            except Exception:  # noqa: BLE001 — QueryError/transport both
                with lock:     # count as failures; the thread lives on
                    rec["failed_queries"] += 1
                continue
            ms = (time.monotonic() - t0) * 1000
            with lock:
                rec["queries"] += 1
                latencies[mix].append(ms)
                if _chaos_rows(rows) != oracle[q]:
                    rec["wrong_answers"] += 1

    threads = [_th.Thread(target=one, args=(i,), daemon=True)
               for i in range(n)]
    t_soak = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    soak_s = time.monotonic() - t_soak

    # post-write rerun: the cached counter read must reflect the write
    # (catalog-version invalidation), not the cached page
    client = Client(coord.uri, user="writer")
    pre = client.execute("SELECT count(*) FROM memory.s.counters").rows
    again = client.execute("SELECT count(*) FROM memory.s.counters").rows
    client.execute("INSERT INTO memory.s.counters VALUES (3, 30)")
    post = client.execute("SELECT count(*) FROM memory.s.counters").rows
    rec["invalidation_proven"] = (pre == again ==
                                  [[2]]) and post == [[3]]

    after = REGISTRY.snapshot()

    def delta(*key):
        return int(after.get(tuple(key), 0) - reg0.get(tuple(key), 0))

    rec["throughput_qps"] = round(rec["queries"] / max(soak_s, 1e-9), 1)
    rec["soak_seconds"] = round(soak_s, 2)
    rec["mixes"] = {}
    for m in mix_names:
        vals = sorted(latencies[m])
        rec["mixes"][m] = {
            "queries": len(vals),
            "p50_ms": round(_percentile(vals, 0.50), 1),
            "p99_ms": round(_percentile(vals, 0.99), 1)}
    rec["plan_cache_hits"] = delta("trino_tpu_plan_cache_hits_total")
    rec["plan_cache_misses"] = delta("trino_tpu_plan_cache_misses_total")
    rec["result_cache_hits"] = delta("trino_tpu_result_cache_hits_total")
    rec["result_cache_invalidations"] = delta(
        "trino_tpu_result_cache_invalidations_total")
    rec["router_host"] = delta("trino_tpu_router_decisions_total", "host")
    rec["router_device"] = delta("trino_tpu_router_decisions_total",
                                 "device")
    rec["microbatch_queries"] = delta(
        "trino_tpu_microbatch_queries_total")
    rec["microbatch_batches"] = delta(
        "trino_tpu_microbatch_batches_total")
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    rec["passed"] = (rec["wrong_answers"] == 0 and
                     rec["failed_queries"] == 0 and
                     rec["queries"] == n * per and
                     rec["result_cache_hits"] > 0 and
                     rec["plan_cache_hits"] > 0 and
                     rec["router_host"] > 0 and
                     rec["router_device"] > 0 and
                     rec["invalidation_proven"])
    coord.stop()
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


def elastic_soak(duration_s=None, out_path="BENCH_soak.json"):
    """Sustained elastic-membership soak (round-15 acceptance): a
    minutes-long mixed workload — point + cached + scan-heavy + writes
    across >= 3 tenants — with chaos injection, per-tenant soft memory
    limits, and CPU/TPU routing all ON simultaneously, while a worker
    is admin-drained (PUT /v1/info/state) and a fresh worker joins
    mid-run. Gated on: 0 wrong answers (every read bit-exact vs a
    pre-server oracle, every write accounted for in a final count), 0
    failed queries, 0 orphaned splits on the drained worker, the drain
    reaching LEFT, the joiner actually receiving splits, and per-tenant
    p99 SLOs — the fair-share acceptance is that beta (the saturating
    scan tenant) cannot push alpha's point p99 past its SLO, because
    alpha's host-eligible queries overflow to the lock-free host tier
    under device contention. Emits BENCH_soak.json; the smoke path
    (TRINO_TPU_SOAK_DURATION_S of a few seconds) runs in tier-1."""
    import tempfile
    import threading as _th
    from urllib.request import Request as _Req
    from urllib.request import urlopen as _uo

    from trino_tpu.client.client import Client
    from trino_tpu.metrics import REGISTRY, SOAK_SLO_VIOLATIONS
    from trino_tpu.exec.session import Session
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.failuredetector import HeartbeatFailureDetector
    from trino_tpu.server.failureinjector import FailureInjector
    from trino_tpu.server.resourcegroups import tenant_tree
    from trino_tpu.server.security import internal_headers
    from trino_tpu.server.telemetry import (histogram_deltas,
                                            percentile_from_buckets)
    from trino_tpu.server.worker import WorkerServer

    dur = duration_s if duration_s is not None else \
        float(os.environ.get("TRINO_TPU_SOAK_DURATION_S", 180))
    per_tenant = int(os.environ.get("TRINO_TPU_SOAK_CLIENTS", 3))
    # cluster flight recorder cadence: ~20 samples over the soak so the
    # p99-over-time series has real resolution even on the smoke path
    tel_interval = float(os.environ.get("TRINO_TPU_SOAK_TELEMETRY_S",
                                        0)) or max(0.5, dur / 20.0)
    slo_ms = {
        "alpha": float(os.environ.get("TRINO_TPU_SOAK_SLO_ALPHA_MS",
                                      5000)),
        "beta": float(os.environ.get("TRINO_TPU_SOAK_SLO_BETA_MS",
                                     60000)),
        "gamma": float(os.environ.get("TRINO_TPU_SOAK_SLO_GAMMA_MS",
                                      5000)),
    }
    t_start = time.monotonic()
    # fresh history file (same reason as concurrency_soak: stale
    # medians would bias the router baseline)
    hist = tempfile.NamedTemporaryFile(prefix="soak_hist_",
                                       suffix=".jsonl", delete=False)
    saved_hist_env = os.environ.get("TRINO_TPU_HISTORY_PATH")
    os.environ["TRINO_TPU_HISTORY_PATH"] = hist.name

    session = Session(default_schema="tiny")
    session.execute(
        "CREATE TABLE memory.s.soak_log (k bigint, v bigint)")

    # tenant mixes: (sql, unordered, is_write). alpha = interactive
    # point/cached traffic (host tier), beta = scan-heavy distributed
    # saturator (device tier + cluster), gamma = cached reads + writes
    # (writes also bump the catalog version, which keeps invalidating
    # the result cache so beta's scans stay honest distributed work)
    mixes = {
        "alpha": [(f"SELECT n_name FROM nation WHERE n_nationkey = {k}",
                   False, False) for k in range(12)] +
                 [("SELECT r_name FROM region ORDER BY r_name",
                   False, False)],
        "beta": [(q, unordered, False)
                 for q, unordered in CHAOS_QUERIES.values()],
        "gamma": [("INSERT INTO memory.s.soak_log VALUES (1, 1)",
                   False, True),
                  ("SELECT count(*) FROM supplier", False, False),
                  ("SELECT min(s_suppkey), max(s_suppkey) FROM supplier",
                   False, False)],
    }
    oracle = {}
    for qs in mixes.values():
        for q, unordered, is_write in qs:
            if not is_write:
                rows = _chaos_rows(session.execute(q).rows)
                oracle[q] = sorted(rows) if unordered else rows

    session.properties["enable_result_cache"] = True
    session.properties["enable_microbatch"] = True
    # keep the host tier for genuinely small queries only: beta's
    # lineitem scans (~60k rows) must stay device/cluster work so the
    # drain/join path is exercised by real split placement, while
    # alpha's point lookups remain host-eligible for fair-share
    # overflow under contention
    session.properties["router_host_max_rows"] = 4096
    coord = CoordinatorServer(session, max_concurrency=16,
                              retry_policy="QUERY",
                              telemetry_interval_s=tel_interval).start()
    if saved_hist_env is None:
        os.environ.pop("TRINO_TPU_HISTORY_PATH", None)
    else:
        os.environ["TRINO_TPU_HISTORY_PATH"] = saved_hist_env
    # per-tenant isolation: one resource group per tenant with a soft
    # memory limit (round-9 admission gate), fair-share routing reads
    # the tenant off each query
    coord.state.dispatcher.resource_groups = tenant_tree(
        {"alpha": {"hard_concurrency_limit": 8},
         "beta": {"hard_concurrency_limit": 4,
                  "soft_memory_limit_bytes": 1 << 31},
         "gamma": {"hard_concurrency_limit": 4}},
        max_queued=100_000)
    sched = coord.state.scheduler
    sched.split_rows = 8192
    sched.max_task_retries = 8
    sched.hedge_min_s, sched.hedge_multiplier = 0.5, 2.0
    workers = [WorkerServer(f"soak-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            heartbeat_interval_s=0.1,
                            catalog=session.catalog,
                            drain_timeout_s=60.0,
                            telemetry_interval_s=tel_interval).start()
               for i in range(3)]
    detector = HeartbeatFailureDetector(coord.state,
                                        interval_s=0.2).start()
    coord.state.memory_manager.start()

    def wait_active(k, timeout=10.0):
        deadline = time.time() + timeout
        while len(coord.state.active_nodes()) < k and \
                time.time() < deadline:
            time.sleep(0.05)
        return len(coord.state.active_nodes()) >= k

    wait_active(3)
    stats0 = dict(sched.stats)
    reg0 = REGISTRY.snapshot()
    # baseline flight-recorder sample: the first sample of a fresh ring
    # carries counter totals since process start; everything after this
    # timestamp is genuine per-interval soak deltas
    telemetry = coord.state.telemetry
    tel_baseline_ts = telemetry.recorder.sample_once()["ts"]
    lock = _th.Lock()
    latencies = {t: [] for t in mixes}
    rec = {"metric": "soak", "duration_s": dur, "queries": 0,
           "wrong_answers": 0, "failed_queries": 0, "writes_ok": 0,
           "chaos_schedules": 0, "injected_total": 0}
    stop_at = time.monotonic() + dur
    mismatches = []

    def one(tenant: str, i: int) -> None:
        qs = mixes[tenant]
        client = Client(coord.uri, user=f"{tenant}-{i}", timeout_s=180,
                        poll_interval_s=0.005)
        j = 0
        while time.monotonic() < stop_at:
            q, unordered, is_write = qs[(i + j) % len(qs)]
            j += 1
            t0 = time.monotonic()
            try:
                rows = client.execute(q).rows
            except Exception as e:  # noqa: BLE001 — any failure counts
                with lock:
                    rec["failed_queries"] += 1
                    if len(mismatches) < 5:
                        mismatches.append(f"{tenant}: {q[:60]}: {e}")
                continue
            ms = (time.monotonic() - t0) * 1000
            with lock:
                rec["queries"] += 1
                latencies[tenant].append(ms)
                if is_write:
                    rec["writes_ok"] += 1
                else:
                    got = _chaos_rows(rows)
                    if unordered:
                        got = sorted(got)
                    if got != oracle[q]:
                        rec["wrong_answers"] += 1
                        if len(mismatches) < 5:
                            mismatches.append(f"{tenant}: {q[:60]}")

    threads = [_th.Thread(target=one, args=(t, i), daemon=True)
               for t in mixes for i in range(per_tenant)]
    t_soak = time.monotonic()
    for t in threads:
        t.start()

    # --- the orchestrated membership events, chaos rotating throughout
    drain_at = t_soak + dur * 0.30
    join_at = t_soak + dur * 0.45
    next_chaos = t_soak
    w0, w3 = workers[0], None
    drain_requested = False
    seed = 0
    last_inj = None
    while time.monotonic() < stop_at:
        now = time.monotonic()
        if now >= next_chaos:
            inj = FailureInjector.from_seed(seed, max_delay_s=0.5)
            seed += 1
            sched.failure_injector = inj
            detector.injector = inj
            for w in workers:
                w.task_manager.injector = inj
            # drop spooled stage outputs so repeat fingerprints dispatch
            # REAL tasks: the soak must exercise live split placement
            # (and the drain/join membership), not replay the durable
            # spool's dedup of identical (fragment, splits) work
            sched.spool.clear()
            rec["chaos_schedules"] += 1
            if last_inj is not None:
                rec["injected_total"] += last_inj.injected_count
            last_inj = inj
            next_chaos = now + max(2.0, dur / 12.0)
        if not drain_requested and now >= drain_at:
            req = _Req(f"{w0.uri}/v1/info/state",
                       data=json.dumps({"state": "DRAINING"}).encode(),
                       method="PUT",
                       headers={"Content-Type": "application/json",
                                **internal_headers()})
            with _uo(req, timeout=10) as resp:
                assert resp.status == 200, resp.status
            drain_requested = True
        if w3 is None and now >= join_at:
            w3 = WorkerServer("soak-w3", coord.uri,
                              announce_interval_s=0.1,
                              heartbeat_interval_s=0.1,
                              catalog=session.catalog,
                              telemetry_interval_s=tel_interval).start()
            workers.append(w3)
            sched.spool.clear()   # next scans place splits on the joiner
        time.sleep(0.05)
    if last_inj is not None:
        rec["injected_total"] += last_inj.injected_count
    for t in threads:
        t.join(timeout=300)
    soak_s = time.monotonic() - t_soak
    sched.failure_injector = None
    detector.injector = None
    for w in workers:
        w.task_manager.injector = None

    # --- drain postconditions: w0 deregistered with nothing orphaned
    deadline = time.time() + 60
    while not w0.drained() and time.time() < deadline:
        time.sleep(0.05)
    rec["drain_completed"] = w0.drained()
    with coord.state.nodes_lock:
        rec["drained_node_deregistered"] = \
            w0.node_id not in coord.state.nodes
    rec["orphaned_splits"] = len(w0.task_manager.inflight()) + \
        len(w0.task_manager.unflushed())
    rec["join_received_splits"] = any(
        t.get("node") == "soak-w3" for t in sched.task_history)
    # write accounting: every acknowledged INSERT must be visible
    final = Client(coord.uri, user="gamma-audit").execute(
        "SELECT count(*) FROM memory.s.soak_log").rows
    rec["writes_visible"] = int(final[0][0]) == rec["writes_ok"]

    after = REGISTRY.snapshot()

    def delta(*key):
        return int(after.get(tuple(key), 0) - reg0.get(tuple(key), 0))

    rec["throughput_qps"] = round(rec["queries"] / max(soak_s, 1e-9), 1)
    rec["soak_seconds"] = round(soak_s, 2)
    rec["splits_migrated"] = sched.stats["splits_migrated"] - \
        stats0.get("splits_migrated", 0)
    rec["task_retries"] = sched.stats["task_retries"] - \
        stats0["task_retries"]
    rec["hedged_tasks"] = sched.stats["hedged_tasks"] - \
        stats0["hedged_tasks"]
    rec["lifecycle_transitions"] = {
        st: delta("trino_tpu_node_lifecycle_transitions_total", st)
        for st in ("ACTIVE", "DRAINING", "DRAINED", "LEFT", "FAILED")}
    rec["membership_rearbitrations"] = \
        coord.state.memory_manager.membership_rearbitrations
    rec["router_host"] = delta("trino_tpu_router_decisions_total",
                               "host")
    rec["router_device"] = delta("trino_tpu_router_decisions_total",
                                 "device")
    # --- p99-over-time from the cluster flight recorder. The SLO gate
    # reads its per-tenant p99 off the recorder's per-interval histogram
    # deltas of trino_tpu_tenant_query_seconds (the series BENCH_soak
    # emits), with the client-side latency list kept as the summary
    # p50/p99 fields --check-regressions parses.
    telemetry.collect()          # final round: flush the partial interval
    tel_samples = telemetry.recorder.since(tel_baseline_ts)
    tel_rec = {"interval_s": tel_interval,
               "samples": len(tel_samples),
               "ring_bytes": telemetry.recorder.ring_bytes(),
               "nodes": sorted({r[1] for r in telemetry.rows()}),
               "p99_series_ms": {}, "p99_ms": {},
               "interval_slo_violations": {}}
    fam = "trino_tpu_tenant_query_seconds"
    rec["tenants"] = {}
    slo_ok = True
    for tname in mixes:
        deltas = histogram_deltas(tel_samples, fam, labelval=tname)
        series, viol, merged = [], 0, {}
        for d in deltas:
            p = percentile_from_buckets(d["buckets"], 0.99)
            for le, c in d["buckets"]:
                merged[le] = merged.get(le, 0.0) + c
            if p is None:
                continue
            series.append([round(d["ts"], 3), round(p * 1000, 1)])
            if p * 1000 > slo_ms[tname]:
                viol += 1
                SOAK_SLO_VIOLATIONS.inc()
        soak_p99 = percentile_from_buckets(list(merged.items()), 0.99)
        tel_rec["p99_series_ms"][tname] = series
        tel_rec["p99_ms"][tname] = round(soak_p99 * 1000, 1) \
            if soak_p99 is not None else None
        tel_rec["interval_slo_violations"][tname] = viol
        # the gate: the recorder-derived whole-soak p99 within SLO
        ok = soak_p99 is not None and soak_p99 * 1000 <= slo_ms[tname]
        if not ok:
            SOAK_SLO_VIOLATIONS.inc()
            slo_ok = False
        vals = sorted(latencies[tname])
        p99 = round(_percentile(vals, 0.99), 1) if vals else 0.0
        rec["tenants"][tname] = {
            "queries": len(vals),
            "p50_ms": round(_percentile(vals, 0.50), 1) if vals else 0.0,
            "p99_ms": p99, "slo_ms": slo_ms[tname], "slo_ok": ok}
    # --- host/device utilization over the soak (round-21): per-interval
    # deltas of the cumulative busy counter (trino_tpu_node_busy_ms_total)
    # out of the flight-recorder ring, normalized to a fleet-wide busy
    # fraction. The counter form is what works here: the in-process fleet
    # shares one registry, so the instantaneous busy-fraction gauge is
    # last-writer-wins across workers, while counter increments from
    # every worker accumulate — the recorder's delta encoding then yields
    # exactly the busy-ms each interval saw
    fam_busy = "trino_tpu_node_busy_ms_total"
    fleet = max(1, len(workers))
    busy_series = {}
    for tier in ("device", "host"):
        pts = []
        for s in tel_samples:
            iv_ms = s.get("interval_s", 0.0) * 1000
            if iv_ms <= 0:
                continue
            delta = s["values"].get(f"{fam_busy}|{tier}", 0.0)
            pts.append([round(s["ts"], 3),
                        round(min(1.0, delta / (iv_ms * fleet)), 4)])
        busy_series[tier] = pts
    tel_rec["busy_fraction_series"] = busy_series
    tel_rec["busy_fraction_mean"] = {
        tier: (round(sum(v for _, v in pts) / len(pts), 4) if pts
               else None)
        for tier, pts in busy_series.items()}
    rec["telemetry"] = tel_rec
    # live-stats folds landed (heartbeats actually streamed) + the
    # per-node utilization view the folds produced
    rec["live_stats_folds"] = coord.state.livestats.folds
    rec["utilization"] = coord.state.livestats.utilization()
    # the fair-share acceptance, stated explicitly: the saturating scan
    # tenant did not push the point tenant past its SLO
    rec["fair_share_held"] = rec["tenants"]["alpha"]["slo_ok"]
    if mismatches:
        rec["sample_failures"] = mismatches
    rec["elapsed_s"] = round(time.monotonic() - t_start, 1)
    rec["passed"] = (rec["wrong_answers"] == 0 and
                     rec["failed_queries"] == 0 and
                     rec["orphaned_splits"] == 0 and
                     rec["drain_completed"] and
                     rec["drained_node_deregistered"] and
                     rec["join_received_splits"] and
                     rec["writes_visible"] and
                     rec["queries"] > 0 and
                     slo_ok)
    detector.stop()
    coord.state.memory_manager.stop()
    for w in workers:
        w.stop()
    coord.stop()
    if out_path:
        with open(out_path, "w") as f:
            json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


# ---------------------------------------------------------------------------
# --cold-start: fresh-process cold walls vs in-process steady walls
# ---------------------------------------------------------------------------

COLD_QUERIES = {"q3": Q3, "q5": Q5, "q6": Q6}


def _cold_child(query: str) -> int:
    """Child half of --cold-start: one fresh-process execution of the
    named query, timed end to end — everything a cold coordinator pays
    (interpreter start already spent, then imports, planning, ingest,
    and XLA compiles).

    With TRINO_TPU_PREWARM on, the child first runs the AOT warm the
    coordinator would run at boot (PrewarmEngine.warm_fingerprint, off
    the measured path), then times the first query-path execution —
    the cold latency the prewarm subsystem actually delivers. With
    prewarm off it times the raw unwarmed cold path (the baseline the
    parent reports as `seed_ms`). Emits one JSON line and exits."""
    t_start = time.monotonic()
    from trino_tpu.exec.prewarm import (PrewarmEngine,
                                        prewarm_enabled_by_env)
    from trino_tpu.exec.profiler import RECORDER
    from trino_tpu.exec.session import Session
    from trino_tpu.server.history import plan_fingerprint
    schema = os.environ.get("TRINO_TPU_COLD_SCHEMA", "tiny")
    session = Session(default_schema=schema)
    sql = COLD_QUERIES[query]
    prewarmed = False
    if prewarm_enabled_by_env():
        eng = PrewarmEngine(session=session, enabled=True)
        prewarmed = eng.warm_fingerprint(plan_fingerprint(sql), sql)
    before = RECORDER.totals()
    t0 = time.monotonic()
    res = session.execute(sql)
    cold_ms = (time.monotonic() - t0) * 1000
    tot = RECORDER.totals()
    print(json.dumps({
        "metric": "cold_child", "query": query,
        "cold_ms": round(cold_ms, 1),
        "startup_ms": round((t0 - t_start) * 1000, 1),
        "rows": len(res.rows), "prewarmed": prewarmed,
        "fresh_compiles": tot["compiles"] - before["compiles"],
        "prewarm_hits": tot["prewarmHits"],
        "compile_s": tot["compileSeconds"]}), flush=True)
    return 0


def cold_start(queries=None, cold_runs=None, steady_runs=None,
               out_path="BENCH_cold_r01.json", ratio_gate=3.0):
    """Cold-start gate: fresh-process cold walls vs in-process steady
    walls for the headline TPC-H shapes.

    Every cold sample is a subprocess (`bench.py --cold-child q`), so it
    pays real imports, planning, ingest, and XLA compiles — nothing
    in-process trace caches can hide. Per query: one prewarm-OFF child
    measures the raw unwarmed cold wall (reported as `seed_ms`, the
    worst case; it also seeds the shared persistent compile cache),
    then the timed children run the boot-time AOT warm first and
    measure the first query-path execution — the cold start the
    prewarm subsystem actually delivers. A shared compile cache
    defaults ON for all children (override via TRINO_TPU_COMPILE_CACHE).
    Gate: prewarmed cold / steady < ratio_gate for every query."""
    import statistics as _st
    import subprocess
    import sys as _sys
    import tempfile
    queries = queries or list(COLD_QUERIES)
    cold_runs = int(cold_runs or
                    os.environ.get("TRINO_TPU_COLD_RUNS", 2))
    steady_runs = int(steady_runs or 5)
    schema = os.environ.get("TRINO_TPU_COLD_SCHEMA", "tiny")
    env = dict(os.environ)
    env.setdefault("TRINO_TPU_COMPILE_CACHE",
                   os.path.join(tempfile.gettempdir(),
                                "trino_tpu_cold_cache"))

    def child(q, prewarm):
        cenv = dict(env)
        cenv["TRINO_TPU_PREWARM"] = "1" if prewarm else "0"
        p = subprocess.run(
            [_sys.executable, os.path.abspath(__file__),
             "--cold-child", q],
            capture_output=True, text=True, env=cenv,
            cwd=os.path.dirname(os.path.abspath(__file__)), timeout=600)
        rec = None
        for line in p.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
        if rec is None:
            raise RuntimeError(
                f"cold child {q} produced no record (rc={p.returncode}): "
                f"{p.stderr[-500:]}")
        return rec

    from trino_tpu.exec.session import Session
    steady_session = Session(default_schema=schema)
    records, passed = [], True
    for q in queries:
        # unwarmed worst case; also populates the shared XLA cache
        seed = child(q, prewarm=False)
        colds = [child(q, prewarm=True) for _ in range(cold_runs)]
        cold_ms = _st.median(c["cold_ms"] for c in colds)
        steady_session.execute(COLD_QUERIES[q])     # in-process warm
        walls = []
        for _ in range(steady_runs):
            t0 = time.monotonic()
            steady_session.execute(COLD_QUERIES[q])
            walls.append((time.monotonic() - t0) * 1000)
        steady_ms = _st.median(walls)
        ratio = cold_ms / max(steady_ms, 1e-6)
        ok = ratio < ratio_gate
        passed = passed and ok
        records.append({
            "query": q, "cold_ms": round(cold_ms, 1),
            "cold_runs": [c["cold_ms"] for c in colds],
            "seed_ms": seed["cold_ms"],
            "startup_ms": round(_st.median(
                c["startup_ms"] for c in colds), 1),
            "fresh_compiles": colds[-1]["fresh_compiles"],
            "prewarm_hits": colds[-1].get("prewarm_hits", 0),
            "steady_ms": round(steady_ms, 1),
            "ratio": round(ratio, 2), "passed": ok})
        print(json.dumps({"metric": "cold_start_progress", **records[-1]}),
              flush=True)
    rec = {"metric": "cold_start", "schema": schema,
           "ratio_gate": ratio_gate, "cold_runs": cold_runs,
           "steady_runs": steady_runs,
           "compile_cache": env.get("TRINO_TPU_COMPILE_CACHE"),
           "records": records, "passed": passed}
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec), flush=True)
    return rec


# ---------------------------------------------------------------------------
# --check-regressions: history-based latency gate over BENCH_r*.json
# ---------------------------------------------------------------------------

def load_bench_round(path):
    """Extract per-config steady-state walls from one BENCH round file.

    Accepts the driver format ({"n","cmd","rc","tail"} where `tail`
    carries the emitted JSON lines — the LAST parseable line wins, the
    same cumulative-emit contract bench uses) or a raw emitted record.
    Returns {config: tpu_steady_ms} or None when the round produced no
    usable record (e.g. an rc=124 driver kill before the first emit)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict) and "tail" in doc:
        recs = []
        for line in doc["tail"].splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                recs.append(json.loads(line))
            except ValueError:
                continue              # torn tail line
        doc = recs[-1] if recs else None
    if not isinstance(doc, dict):
        return None
    if str(doc.get("metric", "")).startswith("scan_micro"):
        # --scan-micro rounds gate on the pruned-scan walls per
        # selectivity plus the two prefetch-pipeline walls: a slower
        # pruned scan or pipeline in a later round reads as a
        # regressed scan_micro_* config
        out = {}
        for r in doc.get("records", ()):
            ms = r.get("prune_on_ms")
            if ms is not None:
                out[f"scan_micro_sel{r['selectivity']}"] = float(ms)
        for depth, d in (doc.get("prefetch") or {}).items():
            if isinstance(d, dict) and "wall_ms" in d:
                out[f"scan_micro_prefetch_{depth}"] = float(d["wall_ms"])
        return out or None
    if str(doc.get("metric", "")) == "soak":
        # --soak rounds gate on per-tenant p99s (the SLO surface) plus
        # overall throughput inverted into a wall-like number so a
        # throughput collapse reads as a regression under the same
        # bigger-is-worse median+MAD rule
        out = {}
        for tname, d in (doc.get("tenants") or {}).items():
            if isinstance(d, dict) and "p99_ms" in d:
                out[f"soak_{tname}_p99"] = float(d["p99_ms"])
        qps = doc.get("throughput_qps")
        if qps:
            out["soak_ms_per_query"] = 1000.0 / float(qps)
        return out or None
    if str(doc.get("metric", "")) == "write_chaos":
        # --write-chaos rounds gate on the per-chaos-point commit walls:
        # a slower staged-write/commit/publish path in a later round
        # reads as a regressed write_chaos_* config (correctness — lost
        # or duplicate rows, orphans — already hard-fails the soak)
        out = {}
        for point, d in (doc.get("points") or {}).items():
            if isinstance(d, dict) and "p50_ms" in d:
                out[f"write_chaos_{point.lower()}_p50"] = float(d["p50_ms"])
        return out or None
    if str(doc.get("metric", "")) == "overload":
        # --overload rounds gate on the enforcement latencies: a slower
        # cancel-to-terminal fan-out or a bigger deadline overshoot in
        # a later round reads as a regressed overload_* config
        # (correctness — wrong answers, leaked tasks, undrained pools —
        # already hard-fails the soak itself)
        out = {}
        for key, cfg in (("cancel_terminal_p50_ms", "overload_cancel_p50"),
                         ("cancel_terminal_max_ms", "overload_cancel_max"),
                         ("deadline_overshoot_p50_ms",
                          "overload_deadline_overshoot_p50")):
            if doc.get(key) is not None:
                out[cfg] = float(doc[key])
        return out or None
    if str(doc.get("metric", "")) == "coordinator_chaos":
        # --coordinator-chaos rounds gate on the failover-to-first-
        # result walls: a slower promotion/replay/resume path in a
        # later round reads as a regressed coordinator_chaos_* config
        # (correctness — wrong/lost/duplicate rows or client-visible
        # errors — already hard-fails the soak itself)
        out = {}
        for pct in ("p50", "p99"):
            ms = doc.get(f"failover_to_result_{pct}_ms")
            if ms is not None:
                out[f"coordinator_chaos_failover_{pct}"] = float(ms)
        return out or None
    if str(doc.get("metric", "")) == "cold_start":
        # --cold-start rounds gate on the fresh-process cold wall AND
        # the cold/steady ratio per query: a compile-cache or prewarm
        # break in a later round shows as a blown-up cold_q* config
        out = {}
        for r in doc.get("records", ()):
            if r.get("cold_ms") is not None:
                out[f"cold_{r['query']}"] = float(r["cold_ms"])
            if r.get("ratio") is not None:
                out[f"cold_{r['query']}_ratio"] = float(r["ratio"])
        return out or None
    if str(doc.get("metric", "")).startswith("star_micro"):
        # --star-micro rounds gate on BOTH walls per star shape: a
        # slower fused kernel OR a slower pairwise ladder in a later
        # round reads as a regressed star_micro_* config
        out = {}
        for r in doc.get("records", ()):
            tag = f"star_micro_k{r['dims']}_h{r['hit_rate']}"
            if r.get("fused_ms") is not None:
                out[f"{tag}_fused"] = float(r["fused_ms"])
            if r.get("pairwise_ms") is not None:
                out[f"{tag}_pairwise"] = float(r["pairwise_ms"])
        return out or None
    if str(doc.get("metric", "")).startswith("agg_micro"):
        # --agg-micro rounds gate on the strategy the gate would pick
        # (hash where present, else sort): a slower kernel in a later
        # round reads as a regressed agg_micro_g<cardinality> config
        out = {}
        for r in doc.get("records", ()):
            ms = r.get("hash_ms", r.get("sort_ms"))
            if ms is not None:
                out[f"agg_micro_g{r['groups']}"] = float(ms)
        return out or None
    detail = doc.get("detail", doc)
    out = {}
    for cfg, d in detail.items():
        if isinstance(d, dict) and "tpu_steady_ms" in d:
            out[cfg] = float(d["tpu_steady_ms"])
    return out or None


def check_regressions(paths=None, ratio=None, mad_k=None,
                      min_prior=2):
    """Diff the newest BENCH_r*.json round against the prior rounds'
    per-config baselines with the SAME median+MAD rule the query-history
    detector applies (server/history.py): a config regresses when its
    steady wall exceeds median * ratio AND the robust MAD envelope.
    Returns (ok, report); configs with fewer than `min_prior` baseline
    rounds are reported but never judged."""
    import glob as _glob

    from trino_tpu.server.history import (MAD_K, RATIO, is_regressed,
                                          robust_baseline)
    ratio = RATIO if ratio is None else ratio
    mad_k = MAD_K if mad_k is None else mad_k
    if paths is None:
        paths = sorted(_glob.glob("BENCH_r*.json"))
    rounds = [(p, load_bench_round(p)) for p in paths]
    rounds = [(p, r) for p, r in rounds if r]
    report = {"metric": "bench_regression_check", "rounds": len(rounds),
              "configs": {}, "regressions": []}
    if len(rounds) < 2:
        report["note"] = "need at least 2 parseable rounds to compare"
        return True, report
    latest_path, latest = rounds[-1]
    report["latest"] = latest_path
    for cfg, cur in sorted(latest.items()):
        prior = [r[cfg] for _, r in rounds[:-1] if cfg in r]
        entry = {"steady_ms": cur, "baseline_rounds": len(prior)}
        if len(prior) < min_prior:
            entry["status"] = "insufficient-baseline"
        else:
            med, mad = robust_baseline(prior)
            entry["baseline_median_ms"] = round(med, 1)
            entry["baseline_mad_ms"] = round(mad, 1)
            if is_regressed(cur, med, mad, ratio=ratio, mad_k=mad_k):
                entry["status"] = "REGRESSED"
                report["regressions"].append(cfg)
            else:
                entry["status"] = "ok"
        report["configs"][cfg] = entry
    return not report["regressions"], report


# ---------------------------------------------------------------------------

def run_config(session, sql, runs=RUNS, prewarm=PREWARM):
    """End-to-end timings: cold (first exec: compiles + ingest), then
    steady-state median."""
    t0 = time.monotonic()
    result = session.execute(sql)
    cold_ms = (time.monotonic() - t0) * 1000
    for _ in range(max(0, prewarm - 1)):
        session.execute(sql)
    times = []
    for _ in range(runs):
        t0 = time.monotonic()
        result = session.execute(sql)
        times.append((time.monotonic() - t0) * 1000)
    return result, cold_ms, statistics.median(times)


def op_stats(session, reg_before=None):
    """Per-config operator attribution for the BENCH payloads: the
    executor's adaptive-path counters (nonzero only) plus per-operator
    dispatch wall-ms deltas from the metrics registry — so the perf
    trajectory names operators, not just end-to-end walls."""
    import dataclasses
    from trino_tpu.metrics import REGISTRY
    st = {k: v for k, v in
          dataclasses.asdict(session.executor.stats).items() if v}
    out = {"exec": st}
    if reg_before is not None:
        after = REGISTRY.snapshot()
        wall = {}
        for key, v in after.items():
            if key[0] == "trino_tpu_operator_wall_ms_total":
                d = v - reg_before.get(key, 0)
                if d > 0:
                    wall[key[1]] = round(d, 1)
        out["operator_wall_ms"] = wall
        key = ("trino_tpu_task_output_bytes_total",)
        out["bytes_shuffled"] = int(after.get(key, 0) -
                                    reg_before.get(key, 0))
        key = ("trino_tpu_operator_rows_total", "scan")
        out["rows_scanned"] = int(after.get(key, 0) -
                                  reg_before.get(key, 0))
    return out


def reg_snapshot():
    from trino_tpu.metrics import REGISTRY
    return REGISTRY.snapshot()


def budget_left(frac):
    return (time.monotonic() - T0) < BUDGET_S * frac


def cached_baseline(key: str, fn):
    """CPU baselines are deterministic per dataset, so their (result,
    wall) pair is measured once per machine and cached beside the table
    cache — the same once-per-machine treatment as datagen. The cached
    cpu_ms is the wall measured on this host on first computation."""
    import pickle
    from trino_tpu.connectors.diskcache import cache_root
    os.makedirs(cache_root(), exist_ok=True)
    path = os.path.join(cache_root(), f"baseline_{key}.pkl")
    if os.path.exists(path):
        with open(path, "rb") as f:
            rec = pickle.load(f)
        return rec["result"], rec["cpu_ms"], True
    t0 = time.monotonic()
    result = fn()
    cpu_ms = (time.monotonic() - t0) * 1000
    with open(path, "wb") as f:
        pickle.dump({"result": result, "cpu_ms": cpu_ms}, f)
    return result, cpu_ms, False


def build_parser():
    """Flag-style subcommands (each former ad-hoc `"--x" in sys.argv`
    check is now a declared argparse flag, so `--help` documents the
    full surface and typos fail loudly instead of silently running the
    default bench). Exactly one mode runs per invocation; with no mode
    flag the TPC-H e2e bench runs as before."""
    import argparse
    p = argparse.ArgumentParser(
        prog="bench.py",
        description="trino-tpu driver benchmark and operational soaks "
                    "(one JSON line per result)")
    mode = p.add_argument_group("modes (default: TPC-H e2e bench)")
    mode.add_argument("--chaos", action="store_true",
                      help="seeded fault-injection soak -> "
                           "BENCH_chaos.json")
    mode.add_argument("--write-chaos", action="store_true",
                      help="exactly-once write soak: seeded kills at "
                           "WRITE_STAGE/WRITE_COMMIT/WRITE_PUBLISH, "
                           "0 lost/0 dup rows + 0 orphans required -> "
                           "BENCH_write_chaos.json")
    mode.add_argument("--overload", action="store_true",
                      help="deadline/cancellation/admission-control "
                           "soak: saturating load + HANG faults + "
                           "mass-cancel wave -> BENCH_overload.json")
    mode.add_argument("--coordinator-chaos", action="store_true",
                      help="seeded coordinator-kill failover soak "
                           "(primary + warm standby, kill at every "
                           "query phase) -> BENCH_coordinator_chaos"
                           ".json")
    mode.add_argument("--memory-pressure", action="store_true",
                      help="concurrent soak at 25%% pool -> "
                           "BENCH_memory.json")
    mode.add_argument("--gather-micro", action="store_true",
                      help="Pallas tiled-gather microbench -> "
                           "BENCH_gather_micro.json")
    mode.add_argument("--agg-micro", action="store_true",
                      help="hash vs sort vs direct aggregation "
                           "microbench across group cardinalities -> "
                           "BENCH_agg_micro.json")
    mode.add_argument("--star-micro", action="store_true",
                      help="fused multiway star probe vs the pairwise "
                           "join ladder across star widths and probe "
                           "selectivities -> BENCH_star_micro.json")
    mode.add_argument("--scan-micro", action="store_true",
                      help="zone-map pruning + prefetch pipeline "
                           "scan-path microbench across predicate "
                           "selectivities -> BENCH_scan_micro.json")
    mode.add_argument("--cold-start", action="store_true",
                      help="fresh-process cold walls vs in-process "
                           "steady walls for q3/q5/q6 (prewarm + shared "
                           "compile cache on for the children) -> "
                           "BENCH_cold_r01.json; exit 1 when any "
                           "cold/steady ratio >= 3")
    p.add_argument("--cold-child", metavar="QUERY",
                   help=argparse.SUPPRESS)
    mode.add_argument("--check-regressions", action="store_true",
                      help="gate the newest BENCH_r*.json round against "
                           "prior rounds (median+MAD); exit 1 on a "
                           "regression")
    mode.add_argument("--concurrency", action="store_true",
                      help="high-concurrency serving soak (plan/result "
                           "caches, CPU/TPU routing, micro-batching) -> "
                           "BENCH_concurrency.json")
    mode.add_argument("--soak", action="store_true",
                      help="sustained elastic-membership soak: mixed "
                           "multi-tenant load + chaos + drain/join "
                           "mid-run -> BENCH_soak.json")
    soak = p.add_argument_group("--soak options")
    soak.add_argument("--duration", type=float, default=None,
                      help="soak duration seconds (default: 180 or "
                           "TRINO_TPU_SOAK_DURATION_S)")
    conc = p.add_argument_group("--concurrency options")
    conc.add_argument("--clients", type=int, default=None,
                      help="concurrent clients (default: 120 or "
                           "TRINO_TPU_CONCURRENCY_CLIENTS)")
    conc.add_argument("--queries-per-client", type=int, default=None,
                      help="statements each client runs (default: 5)")
    gate = p.add_argument_group("--check-regressions options")
    gate.add_argument("--rounds-glob", default="BENCH_r*.json",
                      help="round files to diff (default: BENCH_r*.json)")
    gate.add_argument("--ratio", type=float, default=None,
                      help="regression ratio gate (default: history "
                           "detector's 2.0)")
    gate.add_argument("--mad-k", type=float, default=None,
                      help="MAD envelope multiplier (default: 6.0)")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.cold_child:
        return _cold_child(args.cold_child)
    if args.cold_start:
        rec = cold_start()
        return 0 if rec["passed"] else 1
    if args.chaos:
        chaos_soak()
        return 0
    if args.write_chaos:
        rec = write_chaos_soak()
        return 0 if rec["passed"] else 1
    if args.overload:
        rec = overload_soak()
        return 0 if rec["passed"] else 1
    if args.coordinator_chaos:
        rec = coordinator_chaos_soak()
        return 0 if rec["passed"] else 1
    if args.memory_pressure:
        memory_pressure_soak()
        return 0
    if args.gather_micro:
        gather_micro()
        return 0
    if args.agg_micro:
        agg_micro()
        return 0
    if args.star_micro:
        star_micro()
        return 0
    if args.scan_micro:
        scan_micro()
        return 0
    if args.concurrency:
        rec = concurrency_soak(n_clients=args.clients,
                               queries_per_client=args.queries_per_client)
        return 0 if rec["passed"] else 1
    if args.soak:
        rec = elastic_soak(duration_s=args.duration)
        return 0 if rec["passed"] else 1
    if args.check_regressions:
        import glob as _glob
        ok, report = check_regressions(
            sorted(_glob.glob(args.rounds_glob)),
            ratio=args.ratio, mad_k=args.mad_k)
        # the aggregation trajectory gates as its own series: later
        # rounds append BENCH_agg_micro_r*.json next to the canonical
        # BENCH_agg_micro.json, and a slower hash kernel fails the gate
        agg_paths = sorted(_glob.glob("BENCH_agg_micro*.json"))
        if agg_paths:
            ok2, report2 = check_regressions(agg_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["agg_micro"] = report2
            ok = ok and ok2
        # the star-join trajectory gates as its own series the same way
        # (BENCH_star_micro.json + later rounds' BENCH_star_micro_r*.json)
        star_paths = sorted(_glob.glob("BENCH_star_micro*.json"))
        if star_paths:
            ok7, report7 = check_regressions(star_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["star_micro"] = report7
            ok = ok and ok7
        # the scan-path trajectory gates as its own series the same way
        # (BENCH_scan_micro.json + later rounds' BENCH_scan_micro_r*.json)
        scan_paths = sorted(_glob.glob("BENCH_scan_micro*.json"))
        if scan_paths:
            ok4, report4 = check_regressions(scan_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["scan_micro"] = report4
            ok = ok and ok4
        # the elastic soak gates as its own series (BENCH_soak.json +
        # later rounds' BENCH_soak_r*.json): a per-tenant p99 SLO
        # blowout or a throughput collapse in a later round fails here
        soak_paths = sorted(_glob.glob("BENCH_soak*.json"))
        if soak_paths:
            ok5, report5 = check_regressions(soak_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["soak"] = report5
            ok = ok and ok5
        # the exactly-once write trajectory gates as its own series
        # (BENCH_write_chaos.json + later rounds'
        # BENCH_write_chaos_r*.json): a slower commit path at any chaos
        # point in a later round fails here
        wc_paths = sorted(_glob.glob("BENCH_write_chaos*.json"))
        if wc_paths:
            ok8, report8 = check_regressions(wc_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["write_chaos"] = report8
            ok = ok and ok8
        # the lifecycle-enforcement trajectory gates as its own series
        # (BENCH_overload.json + later rounds' BENCH_overload_r*.json):
        # a slower cancel fan-out or deadline overshoot fails here
        ovl_paths = sorted(_glob.glob("BENCH_overload*.json"))
        if ovl_paths:
            ok10, report10 = check_regressions(ovl_paths,
                                               ratio=args.ratio,
                                               mad_k=args.mad_k)
            report["overload"] = report10
            ok = ok and ok10
        # the coordinator-failover trajectory gates as its own series
        # (BENCH_coordinator_chaos.json + later rounds'
        # BENCH_coordinator_chaos_r*.json): a slower failover-to-first-
        # result wall in a later round fails here
        cc_paths = sorted(_glob.glob("BENCH_coordinator_chaos*.json"))
        if cc_paths:
            ok9, report9 = check_regressions(cc_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["coordinator_chaos"] = report9
            ok = ok and ok9
        # the cold-start trajectory gates as its own series
        # (BENCH_cold_r*.json): a regressed fresh-process cold wall or
        # cold/steady ratio in a later round fails here
        cold_paths = sorted(_glob.glob("BENCH_cold*.json"))
        if cold_paths:
            ok6, report6 = check_regressions(cold_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["cold_start"] = report6
            ok = ok and ok6
        # the multichip trajectory gates as its own series too: each
        # driver round lands a MULTICHIP_r*.json whose tail carries the
        # dryrun's emitted JSON line (rounds before the partitioned-join
        # step emitted none — they parse to nothing and are skipped)
        mc_paths = sorted(_glob.glob("MULTICHIP_r*.json"))
        if mc_paths:
            ok3, report3 = check_regressions(mc_paths,
                                             ratio=args.ratio,
                                             mad_k=args.mad_k)
            report["multichip"] = report3
            ok = ok and ok3
        print(json.dumps(report), flush=True)
        return 0 if ok else 1
    threading.Thread(target=_watchdog, daemon=True).start()
    import jax
    from trino_tpu.exec.session import Session
    _detail.update({"device": str(jax.devices()[0]),
                    "prewarm": PREWARM, "runs": RUNS,
                    "budget_s": BUDGET_S})
    only = os.environ.get("TRINO_TPU_BENCH_ONLY", "")
    configs = only.split(",") if only else ["q5", "q6", "q3"]

    # ---- config 4 FIRST: q5-shaped SF100, chunked -------------------
    # Emitted first (round-3 verdict: order configs by information
    # value so a driver timeout can't starve the most important one).
    # The fact table's q5 columns live device-resident in narrowed
    # dtypes (7.8 GB in HBM, exec/device_cache.py); the chunked driver
    # slices chunks from HBM, so steady state never crosses the ~30 MB/s
    # tunnel. Cold pays one narrowed ingest + XLA compiles.
    if "q5" in configs and \
            os.environ.get("TRINO_TPU_BENCH_SKIP_SF100") != "1":
        scale = float(os.environ.get("TRINO_TPU_BENCH_SF100_SCALE", 100))
        t0 = time.monotonic()
        tables100 = q5_tables(scale)
        gen_s = time.monotonic() - t0
        from trino_tpu.catalog import Catalog
        cat = Catalog()
        cat.register("bench", BenchConnector(tables100, "q5"))
        s100 = Session(catalog=cat, default_cat="bench",
                       default_schema="q5")
        chunk = int(os.environ.get("TRINO_TPU_BENCH_CHUNK_ROWS",
                                   33_554_432))
        s100.properties["spill_chunk_rows"] = chunk
        s100.executor.spill_chunk_rows = chunk
        cpu_q5, cpu_q5_ms, _ = cached_baseline(
            f"q5_sf{scale:g}", lambda: numpy_q5(tables100))
        reg0 = reg_snapshot()
        res, cold, steady = run_config(s100, Q5, runs=1, prewarm=1)
        got = [(r[0], round(float(r[1]), 2)) for r in res.rows]
        want = [(n, round(v, 2)) for n, v in cpu_q5]
        assert got == want, (got[:3], want[:3])
        st = s100.executor.stats
        _detail["q5_sf100"] = {
            "tpu_cold_ms": round(cold, 1),
            "tpu_steady_ms": round(steady, 1),
            "cpu_ms": round(cpu_q5_ms, 1),
            "speedup": round(cpu_q5_ms / steady, 2),
            "gen_s": round(gen_s, 1), "scale": scale,
            "rows_lineitem": tables100["lineitem"].num_rows,
            "chunked": True, "verified": True,
            "fact_cache_chunks": st.fact_cache_chunks,
            "chunk_lut_joins": st.chunk_lut_joins,
            "operator_stats": op_stats(s100, reg0),
            "note": "steady slices device-resident narrowed columns; "
                    "cold pays one narrowed ingest over the tunnel"}
        emit()
        del s100, tables100, cat

    # ---- config 2: q6 SF1 end-to-end --------------------------------
    if "q6" in configs and budget_left(0.92):
        t0 = time.monotonic()
        session = Session(default_schema="sf1")
        tables = {"lineitem": session.catalog.get_table("tpch", "sf1",
                                                        "lineitem")}
        gen1_s = time.monotonic() - t0
        cpu_q6, cpu_q6_ms, _ = cached_baseline("q6_sf1",
                                               lambda: numpy_q6(tables))
        reg0 = reg_snapshot()
        res, cold, steady = run_config(session, Q6)
        got = float(res.rows[0][0])
        assert abs(got - cpu_q6 / 1e4) < 1e-2, (got, cpu_q6 / 1e4)
        _detail["q6_sf1"] = {
            "tpu_cold_ms": round(cold, 1),
            "tpu_steady_ms": round(steady, 1),
            "cpu_ms": round(cpu_q6_ms, 1), "gen_s": round(gen1_s, 1),
            "speedup": round(cpu_q6_ms / steady, 2), "verified": True,
            "operator_stats": op_stats(session, reg0)}
        emit()

    # ---- config 3: q3 SF10 end-to-end -------------------------------
    # 0.85: with the round-5 caches q3 runs warm in ~60-90 s, so it can
    # still land before the watchdog even after a slow q5 cold
    if "q3" in configs and budget_left(0.85):
        t0 = time.monotonic()
        session10 = Session(default_schema="sf10")
        tables10 = {t: session10.catalog.get_table("tpch", "sf10", t)
                    for t in ["customer", "orders", "lineitem"]}
        gen10_s = time.monotonic() - t0
        cpu_q3, cpu_q3_ms, _ = cached_baseline(
            "q3_sf10", lambda: numpy_q3(tables10))
        reg0 = reg_snapshot()
        res, cold, steady = run_config(session10, Q3)
        got = [(int(r[0]), round(float(r[1]), 2)) for r in res.rows]
        want = [(k, round(v, 2)) for k, v in cpu_q3]
        assert got == want, (got[:3], want[:3])
        _detail["q3_sf10"] = {
            "tpu_cold_ms": round(cold, 1),
            "tpu_steady_ms": round(steady, 1),
            "cpu_ms": round(cpu_q3_ms, 1), "gen_s": round(gen10_s, 1),
            "speedup": round(cpu_q3_ms / steady, 2), "verified": True,
            "operator_stats": op_stats(session10, reg0)}
        emit()
        del session10, tables10

    emit(final=True)
    return 0


if __name__ == "__main__":
    sys.exit(main() or 0)
