"""Driver benchmark: prints ONE JSON line.

Workload: TPC-H q1 at SF1 (~6M lineitem rows) — the reference's benchto
TPC-H methodology (testing/trino-benchto-benchmarks/.../tpch.yaml:1-40:
prewarm runs then measured runs, concurrency 1) applied to the engine's
flagship aggregation pipeline on the real TPU chip.

Baseline: the same computation, single-node CPU, vectorized numpy — the
stand-in for the reference's single-node Java operator pipeline
(BenchmarkHashAndStreamingAggregationOperators.java:75-99 measures the same
shape). vs_baseline = cpu_time / tpu_time (higher is better; >1 = faster
than CPU).

The TPU timing measures the steady-state jitted pipeline on device-resident
columns (scan cache warm, like the reference benchmarks which read from
in-memory pages), excluding one-time XLA compilation — consistent with
JMH average-time methodology.
"""

import json
import statistics
import time

import numpy as np

PREWARM = 2
RUNS = 6
SCALE = 1.0


def numpy_q1(cols, cutoff):
    """Single-node CPU baseline: vectorized numpy q1 (filter + group by
    returnflag x linestatus + 6 aggregates + 3 avgs)."""
    rf, ls, qty, price, disc, tax, ship = cols
    m = ship <= cutoff
    gid = rf[m] * 2 + ls[m]
    qty_m, price_m, disc_m, tax_m = qty[m], price[m], disc[m], tax[m]
    disc_price = price_m * (100 - disc_m)
    charge = disc_price * (100 + tax_m)
    n_groups = 6
    out = {}
    out["sum_qty"] = np.bincount(gid, weights=qty_m, minlength=n_groups)
    out["sum_base"] = np.bincount(gid, weights=price_m, minlength=n_groups)
    out["sum_disc_price"] = np.bincount(gid, weights=disc_price,
                                        minlength=n_groups)
    out["sum_charge"] = np.bincount(gid, weights=charge, minlength=n_groups)
    out["sum_disc"] = np.bincount(gid, weights=disc_m, minlength=n_groups)
    out["count"] = np.bincount(gid, minlength=n_groups)
    c = np.maximum(out["count"], 1)
    out["avg_qty"] = out["sum_qty"] / c
    out["avg_price"] = out["sum_base"] / c
    out["avg_disc"] = out["sum_disc"] / c
    return out


def main():
    import jax

    from trino_tpu import ir
    from trino_tpu.batch import batch_from_numpy
    from trino_tpu.connectors.tpch.connector import TpchConnector
    from trino_tpu.ops.aggregate import AggSpec, direct_group_aggregate
    from trino_tpu.ops.project import apply_filter, project
    from trino_tpu.types import BIGINT, DATE, VARCHAR, decimal

    conn = TpchConnector()
    li = conn.get_table(f"sf{SCALE:g}" if SCALE != 1 else "sf1", "lineitem")
    s = li.schema
    names = ["l_returnflag", "l_linestatus", "l_quantity",
             "l_extendedprice", "l_discount", "l_tax", "l_shipdate"]
    host_cols = [li.columns[s.index_of(n)] for n in names]
    cutoff = 10561  # DATE '1998-12-01' - 90 days

    # ---- CPU baseline -----------------------------------------------------
    cpu_times = []
    for i in range(PREWARM + RUNS):
        t0 = time.perf_counter()
        ref = numpy_q1(host_cols, cutoff)
        dt = time.perf_counter() - t0
        if i >= PREWARM:
            cpu_times.append(dt)
    cpu_t = statistics.median(cpu_times)

    # ---- TPU pipeline -----------------------------------------------------
    batch = batch_from_numpy(host_cols, pad_multiple=8192)
    d122 = decimal(12, 2)
    rf = ir.ColumnRef(0, VARCHAR, "l_returnflag")
    ls = ir.ColumnRef(1, VARCHAR, "l_linestatus")
    qty = ir.ColumnRef(2, d122, "l_quantity")
    price = ir.ColumnRef(3, d122, "l_extendedprice")
    disc = ir.ColumnRef(4, d122, "l_discount")
    tax = ir.ColumnRef(5, d122, "l_tax")
    ship = ir.ColumnRef(6, DATE, "l_shipdate")
    one = ir.Literal(100, d122)
    flt = ir.Compare("<=", ship, ir.Literal(cutoff, DATE))
    disc_price = ir.arith("*", price, ir.arith("-", one, disc))
    charge = ir.arith("*", disc_price, ir.arith("+", one, tax))
    pre = (rf, ls, qty, price, disc_price, charge, disc)
    aggs = (AggSpec("sum", 2), AggSpec("sum", 3), AggSpec("sum", 4),
            AggSpec("sum", 5), AggSpec("sum", 6),
            AggSpec("count_star", None))

    # XLA masked-reduction path: measured faster than the Pallas MXU
    # kernel at this shape (see ops/pallas_agg.py docstring) because the
    # whole filter+project+aggregate stage fuses into one HBM pass
    @jax.jit
    def q1_step(b):
        filtered = apply_filter(b, flt)
        projected = project(filtered, pre)
        return direct_group_aggregate(projected, (0, 1), (3, 2), aggs)

    # Through the axon tunnel block_until_ready returns before remote
    # execution finishes and any host fetch pays ~60ms network RTT, so we
    # time N pipeline iterations inside ONE jitted fori_loop (per-iteration
    # data perturbation defeats CSE/hoisting), fetch a single scalar, and
    # difference two loop lengths so RTT + dispatch cancel exactly.
    from jax import lax

    from trino_tpu.batch import Batch, Column

    import jax.numpy as jnp

    @jax.jit
    def q1_iterated(b, n_iter):
        def body(i, acc):
            # perturb the shipdate column: the filter feeds every
            # aggregate, so no part of the pipeline is loop-invariant and
            # XLA cannot hoist work out of the timing loop
            cols = list(b.columns)
            ship_c = cols[6]
            cols[6] = Column(
                data=ship_c.data + (i % 2).astype(ship_c.data.dtype),
                valid=ship_c.valid)
            bb = Batch(columns=tuple(cols), live=b.live)
            out = q1_step(bb)
            # consume EVERY aggregate output — anything unconsumed is
            # dead-code-eliminated together with its inputs, silently
            # shrinking the measured pipeline
            total = acc
            for c in out.columns[2:]:
                total = total + c.data.sum()
            return total
        return lax.fori_loop(0, n_iter, body,
                             jnp.asarray(0, dtype=jnp.int64))

    # dynamic trip count: one compile, two loop lengths; the long loop is
    # sized so per-iteration time dominates RTT noise (~ms) by >100x
    N_SHORT, N_LONG = 8, 264
    np.asarray(q1_iterated(batch, N_SHORT))   # warm compile

    def timed(n):
        ts = []
        for _ in range(RUNS):
            t0 = time.perf_counter()
            np.asarray(q1_iterated(batch, n))  # forces remote round trip
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    t_short = timed(N_SHORT)
    t_long = timed(N_LONG)
    tpu_t = max((t_long - t_short) / (N_LONG - N_SHORT), 1e-9)

    out = q1_step(batch)

    # ---- correctness gate (verifier-style: identical results) -------------
    got_counts = np.asarray(out.columns[7].data)
    got_sum_qty = np.asarray(out.columns[2].data)
    # engine group id = rf*2+ls, same mixed radix as baseline
    assert int(got_counts.sum()) == int(ref["count"].sum()), "count mismatch"
    np.testing.assert_allclose(
        np.sort(got_sum_qty[got_counts > 0]),
        np.sort(ref["sum_qty"][ref["count"] > 0]), rtol=0, atol=0)

    n_rows = li.num_rows
    print(json.dumps({
        "metric": "tpch_sf1_q1_agg_pipeline_wall_ms",
        "value": round(tpu_t * 1000, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_t / tpu_t, 3),
        "detail": {
            "rows": n_rows,
            "tpu_rows_per_sec": round(n_rows / tpu_t),
            "cpu_baseline_ms": round(cpu_t * 1000, 3),
            "prewarm": PREWARM, "runs": RUNS,
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
