"""UPDATE / DELETE / MERGE on the memory connector, oracle-verified.

Reference pattern: the row-change tests around MergeWriterOperator /
TestMergeBase — the same mutation statements run on an independent engine
(sqlite) over identical data; final table contents must match.
"""

import sqlite3

import pytest

from trino_tpu.exec.session import Session

SETUP = [
    "CREATE TABLE m.s.accounts (id bigint, name varchar, bal bigint)",
    "INSERT INTO m.s.accounts VALUES (1, 'alice', 100), (2, 'bob', 50),"
    " (3, 'carol', 0), (4, 'dan', 75)",
    "CREATE TABLE m.s.feed (id bigint, name varchar, amount bigint)",
    "INSERT INTO m.s.feed VALUES (2, 'bob', 25), (5, 'eve', 10),"
    " (3, 'carol', -5)",
]


@pytest.fixture()
def session():
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    for sql in SETUP:
        s.execute(sql)
    return s


@pytest.fixture()
def oracle():
    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE accounts (id INTEGER, name TEXT,"
                 " bal INTEGER)")
    conn.executemany("INSERT INTO accounts VALUES (?,?,?)",
                     [(1, "alice", 100), (2, "bob", 50), (3, "carol", 0),
                      (4, "dan", 75)])
    conn.execute("CREATE TABLE feed (id INTEGER, name TEXT,"
                 " amount INTEGER)")
    conn.executemany("INSERT INTO feed VALUES (?,?,?)",
                     [(2, "bob", 25), (5, "eve", 10), (3, "carol", -5)])
    return conn


def table_rows(session):
    return session.execute(
        "SELECT id, name, bal FROM accounts ORDER BY id").rows


def oracle_rows(conn):
    return conn.execute(
        "SELECT id, name, bal FROM accounts ORDER BY id").fetchall()


def check(session, conn):
    assert [tuple(r) for r in table_rows(session)] == oracle_rows(conn)


def test_delete_where(session, oracle):
    r = session.execute("DELETE FROM accounts WHERE bal < 60")
    assert r.rows[0][0] == 2
    oracle.execute("DELETE FROM accounts WHERE bal < 60")
    check(session, oracle)


def test_delete_all(session, oracle):
    session.execute("DELETE FROM accounts")
    oracle.execute("DELETE FROM accounts")
    check(session, oracle)


def test_update_expression(session, oracle):
    r = session.execute(
        "UPDATE accounts SET bal = bal * 2 + 1 WHERE bal >= 50")
    assert r.rows[0][0] == 3
    oracle.execute(
        "UPDATE accounts SET bal = bal * 2 + 1 WHERE bal >= 50")
    check(session, oracle)


def test_update_varchar_new_pool_value(session, oracle):
    session.execute(
        "UPDATE accounts SET name = 'zed' WHERE id = 3")
    oracle.execute("UPDATE accounts SET name = 'zed' WHERE id = 3")
    check(session, oracle)


def test_update_multi_assignments(session, oracle):
    session.execute(
        "UPDATE accounts SET bal = bal - 10, name = upper(name)"
        " WHERE id IN (1, 2)")
    oracle.execute(
        "UPDATE accounts SET bal = bal - 10, name = upper(name)"
        " WHERE id IN (1, 2)")
    check(session, oracle)


def test_merge_upsert(session, oracle):
    r = session.execute("""
        MERGE INTO accounts a USING feed f ON a.id = f.id
        WHEN MATCHED THEN UPDATE SET bal = a.bal + f.amount
        WHEN NOT MATCHED THEN INSERT (id, name, bal)
             VALUES (f.id, f.name, f.amount)
    """)
    assert r.rows[0][0] == 3        # 2 updates + 1 insert
    oracle.executescript("""
        UPDATE accounts SET bal = bal +
          (SELECT amount FROM feed WHERE feed.id = accounts.id)
        WHERE id IN (SELECT id FROM feed);
        INSERT INTO accounts
          SELECT id, name, amount FROM feed
          WHERE id NOT IN (SELECT id FROM accounts);
    """)
    check(session, oracle)


def test_merge_conditional_delete(session, oracle):
    session.execute("""
        MERGE INTO accounts a USING feed f ON a.id = f.id
        WHEN MATCHED AND f.amount < 0 THEN DELETE
    """)
    oracle.execute("""
        DELETE FROM accounts WHERE id IN
          (SELECT id FROM feed WHERE amount < 0)
    """)
    check(session, oracle)


def test_merge_insert_only_with_null_padding(session, oracle):
    session.execute("""
        MERGE INTO accounts a USING feed f ON a.id = f.id
        WHEN NOT MATCHED THEN INSERT (id, name) VALUES (f.id, f.name)
    """)
    oracle.execute("""
        INSERT INTO accounts (id, name)
          SELECT id, name FROM feed
          WHERE id NOT IN (SELECT id FROM accounts)
    """)
    check(session, oracle)


def test_merge_duplicate_source_rows_error(session):
    session.execute("INSERT INTO feed VALUES (2, 'bob2', 7)")
    with pytest.raises(Exception, match="more than one source row"):
        session.execute("""
            MERGE INTO accounts a USING feed f ON a.id = f.id
            WHEN MATCHED THEN UPDATE SET bal = f.amount
        """)


def test_update_unseen_varchar_keeps_pool_sorted(session):
    """Regression: UPDATE/INSERT with a varchar value absent from the
    stored pool must keep the pool sorted (code order == string order)
    and renumber existing codes — appending silently corrupts ORDER BY
    and range compares on later queries."""
    session.execute("UPDATE m.s.accounts SET name = 'zed' WHERE id = 1")
    session.execute("UPDATE m.s.accounts SET name = 'amy' WHERE id = 3")
    rows = session.execute(
        "SELECT id, name FROM m.s.accounts ORDER BY name").rows
    assert rows == [(3, "amy"), (2, "bob"), (4, "dan"), (1, "zed")]
    n = session.execute("SELECT count(*) FROM m.s.accounts "
                        "WHERE name < 'dan'").rows
    assert n == [(2,)]
    session.execute("INSERT INTO m.s.accounts VALUES (9, 'cat', 1)")
    rows = session.execute(
        "SELECT id FROM m.s.accounts ORDER BY name DESC").rows
    assert rows == [(1,), (4,), (9,), (2,), (3,)]
