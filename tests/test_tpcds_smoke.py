"""Quick-tier TPC-DS smoke: a handful of representative queries against
the sqlite oracle. The full 99-query sweep lives in test_tpcds.py (slow
tier); this keeps star-schema join/agg coverage in the default gate.
"""

import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from tpcds_queries import ORACLE, QUERIES
from trino_tpu.connectors.tpcds.connector import TABLE_NAMES
from trino_tpu.exec.session import Session

SMOKE = [q for q in (3, 7, 42, 52, 55, 96) if q in QUERIES]


@pytest.fixture(scope="module")
def session():
    return Session(default_cat="tpcds", default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpcds")
    return load_oracle([conn.get_table("tiny", t) for t in TABLE_NAMES])


@pytest.mark.parametrize("qid", SMOKE)
def test_tpcds_smoke(session, oracle, qid):
    sql = QUERIES[qid]
    got = session.execute(sql).rows
    want = oracle_query(oracle, ORACLE.get(qid, sql))
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.02, ordered=True)
