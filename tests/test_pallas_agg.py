"""Pallas MXU aggregation kernel tests (interpret mode on the CPU mesh;
the real-TPU path is exercised by bench.py and the driver's entry())."""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from trino_tpu.batch import batch_from_numpy
from trino_tpu.ops.aggregate import AggSpec, direct_group_aggregate
from trino_tpu.ops.pallas_agg import (direct_group_aggregate_mxu, supports)


def make_batch(n, rng, null_frac=0.1):
    group = rng.integers(0, 3, n).astype(np.int32)
    flag = rng.integers(0, 2, n).astype(np.int32)
    v1 = rng.integers(-2**44, 2**44, n).astype(np.int64)
    v2 = rng.integers(0, 10_000, n).astype(np.int64)
    valids = [None, None, rng.random(n) >= null_frac, None]
    return batch_from_numpy([group, flag, v1, v2], valids=valids)


AGGS = (AggSpec("sum", 2), AggSpec("count", 2), AggSpec("sum", 3),
        AggSpec("count_star", None))


def test_supports():
    assert supports(AGGS, (3, 2))
    assert not supports((AggSpec("min", 2),), (3, 2))
    assert not supports(AGGS, (64, 2))     # beyond MAX_GROUPS


def test_matches_xla_path():
    rng = np.random.default_rng(7)
    batch = make_batch(5000, rng)
    want = direct_group_aggregate(batch, (0, 1), (3, 2), AGGS)
    got = direct_group_aggregate_mxu(batch, (0, 1), (3, 2), AGGS,
                                     interpret=True)
    assert np.array_equal(np.asarray(want.live), np.asarray(got.live))
    for cw, cg in zip(want.columns, got.columns):
        live = np.asarray(want.live)
        assert np.array_equal(np.asarray(cw.valid)[live],
                              np.asarray(cg.valid)[live])
        keep = np.asarray(cw.valid) & live
        assert np.array_equal(np.asarray(cw.data)[keep],
                              np.asarray(cg.data)[keep])


def test_dead_rows_and_null_keys_excluded():
    rng = np.random.default_rng(3)
    batch = make_batch(2000, rng)
    # kill half the rows; NULL some keys
    live = np.asarray(batch.live).copy()
    live[::2] = False
    batch = batch.with_live(jnp.asarray(live))
    want = direct_group_aggregate(batch, (0,), (3,), AGGS)
    got = direct_group_aggregate_mxu(batch, (0,), (3,), AGGS,
                                     interpret=True)
    live_mask = np.asarray(want.live)
    for cw, cg in zip(want.columns, got.columns):
        keep = np.asarray(cw.valid) & live_mask
        assert np.array_equal(np.asarray(cw.data)[keep],
                              np.asarray(cg.data)[keep])


def test_negative_sums_exact():
    rng = np.random.default_rng(11)
    n = 4096 * 8
    group = np.zeros(n, dtype=np.int32)
    vals = np.full(n, -(2**44) + 17, dtype=np.int64)
    batch = batch_from_numpy([group, vals])
    got = direct_group_aggregate_mxu(
        batch, (0,), (1,), (AggSpec("sum", 1),), interpret=True)
    assert int(np.asarray(got.columns[1].data)[0]) == int(vals.sum())
