"""TPC-DS benchmark queries (engine-supported subset).

Written from the TPC-DS specification's query definitions against the
generated schema subset (trino_tpu/connectors/tpcds/datagen.py); where a
spec query touches columns the generator does not produce, the query is
adapted (noted per query). Every query runs against the sqlite oracle on
identical data, so results are verified regardless of adaptation.
"""

QUERIES = {}

# q3: brand revenue for a manufacturer in November
QUERIES[3] = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, brand_id
LIMIT 100
"""

# q7: average store-sales metrics for a demographic slice
QUERIES[7] = """
SELECT i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

# q19: brand revenue where customer and store are in different zip prefixes
QUERIES[19] = """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 8
  AND d_moy = 11
  AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand_id, i_brand, i_manufact_id, i_manufact
ORDER BY ext_price DESC, brand_id, i_manufact_id
LIMIT 100
"""

# q26: catalog-sales averages for a demographic slice (adapted: generated
# catalog_sales has no cs_coupon_amt; uses cs_net_profit for agg4)
QUERIES[26] = """
SELECT i_item_id,
       avg(cs_quantity) agg1,
       avg(cs_list_price) agg2,
       avg(cs_sales_price) agg3,
       avg(cs_net_profit) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk
  AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

# q42: category revenue in a month
QUERIES[42] = """
SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) revenue
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY revenue DESC, d_year, i_category_id, i_category
LIMIT 100
"""

# q52: brand revenue in a month
QUERIES[52] = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, brand_id
LIMIT 100
"""

# q53: quarterly manufacturer sales vs their average (window over agg)
QUERIES[53] = """
SELECT i_manufact_id, d_qoy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_manufact_id) avg_quarterly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 1999
  AND i_category IN ('Books', 'Children', 'Electronics')
GROUP BY i_manufact_id, d_qoy
ORDER BY i_manufact_id, d_qoy
LIMIT 100
"""

# q55: brand revenue for one manager's items in a month
QUERIES[55] = """
SELECT i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, brand_id
LIMIT 100
"""

# q65: items whose store revenue is at most 10% of the store average
QUERIES[65] = """
SELECT s_store_name, sc.sk_item, sc.revenue
FROM store,
     (SELECT ss_store_sk sk_store, ss_item_sk sk_item,
             sum(ss_sales_price) revenue
      FROM store_sales GROUP BY ss_store_sk, ss_item_sk) sc,
     (SELECT ss_store_sk sk_store2, avg(revenue) ave
      FROM (SELECT ss_store_sk, ss_item_sk,
                   sum(ss_sales_price) revenue
            FROM store_sales GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb
WHERE s_store_sk = sc.sk_store
  AND sb.sk_store2 = sc.sk_store
  AND sc.revenue <= 0.1 * sb.ave
ORDER BY s_store_name, sc.revenue, sc.sk_item
LIMIT 100
"""

# q68: customers whose current city differs from the purchase city
QUERIES[68] = """
SELECT c_last_name, c_first_name, bought_city,
       ms.ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) ms,
     customer, customer_address current_addr
WHERE ms.ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ms.ss_ticket_number, extended_price
LIMIT 100
"""

# q73: ticket row counts per customer for a demographic slice
QUERIES[73] = """
SELECT c_last_name, c_first_name, dj.ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND d_dom BETWEEN 1 AND 2
        AND hd_buy_potential = '1001-5000'
        AND hd_vehicle_count > 0
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE dj.ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name, c_first_name, dj.ss_ticket_number
LIMIT 100
"""

# q79: per-ticket coupon amount and profit for a demographic slice
QUERIES[79] = """
SELECT c_last_name, c_first_name, ms.s_city, profit,
       ms.ss_ticket_number, amt
FROM (SELECT ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
WHERE ms.ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, ms.s_city, profit,
         ms.ss_ticket_number
LIMIT 100
"""

# q93: actual sales after returns for one return reason
QUERIES[93] = """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END act_sales
      FROM store_sales
           LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
                AND sr_ticket_number = ss_ticket_number,
           reason
      WHERE sr_reason_sk = r_reason_sk
        AND r_reason_desc = 'Did not fit') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk NULLS FIRST
LIMIT 100
"""

# q96: sales volume in a store/time/demographic window
QUERIES[96] = """
SELECT count(*) cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
  AND s_store_name = 'ese'
"""

# q27: store-sales averages with ROLLUP over state (adapted: the generated
# schema rolls up over s_state only; spec adds i_item_id grouping)
QUERIES[27] = """
SELECT i_item_id, s_state, avg(ss_quantity) agg1,
       avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2002
  AND s_state IN ('TN', 'TX')
  AND i_manufact_id < 30
GROUP BY i_item_id, s_state
ORDER BY i_item_id, s_state
LIMIT 100
"""

# q34: households buying 15-20 items per ticket (count HAVING band)
QUERIES[34] = """
SELECT c_last_name, c_first_name, dn.ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (d_dom BETWEEN 1 AND 3 OR d_dom BETWEEN 25 AND 28)
        AND hd_buy_potential = '>10000'
        AND hd_vehicle_count > 0
        AND d_year IN (1999, 2000, 2001)
      GROUP BY ss_ticket_number, ss_customer_sk
      HAVING count(*) BETWEEN 5 AND 20) dn, customer
WHERE dn.ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, dn.ss_ticket_number DESC, cnt
LIMIT 100
"""

# q37: items with inventory in a quantity band sold through catalog
QUERIES[37] = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 20 AND 50
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-02-01' AND DATE '2000-04-01'
  AND i_manufact_id IN (100, 200, 300, 400)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

# q43: store sales by day of week (CASE pivot)
QUERIES[43] = """
SELECT s_store_name, s_store_id,
       sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE NULL END) sun_sales,
       sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE NULL END) mon_sales,
       sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE NULL END) fri_sales,
       sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -500
  AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id
LIMIT 100
"""

# q46: city mismatch between purchase and residence (like q68 with dow)
QUERIES[46] = """
SELECT c_last_name, c_first_name, ca_city,
       dn.bought_city, dn.ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_dow IN (6, 0)
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE dn.ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> dn.bought_city
ORDER BY c_last_name, c_first_name, ca_city, dn.bought_city,
         dn.ss_ticket_number
LIMIT 100
"""

# q63: monthly manager sales vs their yearly average (window over agg)
QUERIES[63] = """
SELECT i_manager_id, d_moy, sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_manager_id) avg_monthly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 2001
  AND i_category IN ('Books', 'Electronics', 'Sports')
GROUP BY i_manager_id, d_moy
ORDER BY i_manager_id, d_moy
LIMIT 100
"""

# q82: items with store inventory in a band (store-sales twin of q37)
QUERIES[82] = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 30 AND 60
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '1999-05-01' AND DATE '1999-07-01'
  AND i_manufact_id IN (50, 150, 250, 350)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

# q89: weekly category sales vs class average (window over agg)
QUERIES[89] = """
SELECT i_category, i_class, s_store_name, d_moy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_category, i_class,
                 s_store_name) avg_monthly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 2000
  AND i_category IN ('Home', 'Music', 'Shoes')
  AND i_class IN ('accent', 'classical', 'athletic')
GROUP BY i_category, i_class, s_store_name, d_moy
ORDER BY i_category, i_class, s_store_name, d_moy
LIMIT 100
"""

# q98: item revenue share within class (sum over partition of agg)
QUERIES[98] = """
SELECT i_item_id, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100 /
           sum(sum(ss_ext_sales_price))
               OVER (PARTITION BY i_class) revenueratio
FROM store_sales, item, date_dim
WHERE ss_item_sk = i_item_sk
  AND i_category IN ('Books', 'Jewelry', 'Women')
  AND ss_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-03-24'
GROUP BY i_item_id, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, revenueratio
LIMIT 100
"""

# q33: manufacturer revenue across all three channels for one category
QUERIES[33] = """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
"""

# q48: quantity sold under demographic/address OR-band predicates
QUERIES[48] = """
SELECT sum(ss_quantity) q
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'D'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'KS')
        AND ss_net_profit BETWEEN 0 AND 2000)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('CA', 'NY', 'WA')
        AND ss_net_profit BETWEEN 150 AND 3000)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('GA', 'MN', 'NC')
        AND ss_net_profit BETWEEN 50 AND 25000))
"""

# q56: color-item revenue across the three channels
QUERIES[56] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

# q60: category-item revenue across the three channels
QUERIES[60] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

# q13: average store-sales metrics under demographic/address OR bands
QUERIES[13] = """
SELECT avg(ss_quantity) a1, avg(ss_ext_sales_price) a2,
       avg(ss_ext_wholesale_cost) a3, sum(ss_ext_wholesale_cost) a4
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 50.00 AND 100.00
        AND hd_dep_count = 1)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'W'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 150.00 AND 200.00
        AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'KS')
        AND ss_net_profit BETWEEN 100 AND 200)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('CA', 'NY', 'WA')
        AND ss_net_profit BETWEEN 150 AND 300)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('GA', 'MN', 'NC')
        AND ss_net_profit BETWEEN 50 AND 250))
"""

# q45: web sales by zip prefix or flagged items
QUERIES[45] = """
SELECT ca_zip, ca_city, sum(ws_sales_price) total
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (substr(ca_zip, 1, 5) IN
         ('85669', '86197', '88274', '83405', '86475')
    OR i_item_id IN (SELECT i_item_id FROM item
                     WHERE i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19, 23)))
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
LIMIT 100
"""

# q69: demographic profile of store customers absent from other channels
QUERIES[69] = """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) cnt1, cd_purchase_estimate, count(*) cnt2
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('KY', 'GA', 'NM')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT 1 FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2001 AND d_moy BETWEEN 4 AND 6)
  AND (NOT EXISTS (SELECT 1 FROM web_sales, date_dim
                   WHERE c.c_customer_sk = ws_bill_customer_sk
                     AND ws_sold_date_sk = d_date_sk
                     AND d_year = 2001 AND d_moy BETWEEN 4 AND 6))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
LIMIT 100
"""


# ---- round 2 expansion: 31 additional spec queries ----

# Batch A: single-fact aggregations, case buckets, channel unions
# (written from the TPC-DS spec query definitions; adapted where noted)

# q9: CASE bucket picks between avg columns by count thresholds
QUERIES[9] = """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 2000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 3000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 1000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3
FROM reason
WHERE r_reason_sk = 1
"""

# q15: catalog sales by zip for qualifying zips/states/prices
QUERIES[15] = """
SELECT ca_zip, sum(cs_sales_price) total
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348',
                                '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
"""

# q21: inventory before/after a date, ratio-bounded
QUERIES[21] = """
SELECT w_warehouse_name, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
FROM inventory, warehouse, item, date_dim
WHERE i_item_sk = inv_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk
  AND i_current_price BETWEEN 0.99 AND 1.49
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_warehouse_name, i_item_id
HAVING sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) > 0
   AND sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 3 >=
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 2
   AND sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 3 >=
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 2
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
"""

# q25: store/returns/catalog profit by item and store
QUERIES[25] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) AS store_sales_profit,
       sum(sr_net_loss) AS store_returns_loss,
       sum(cs_net_profit) AS catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_year = 2001
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2001
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2001
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

# q29: same join shape, quantities
QUERIES[29] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) AS store_sales_quantity,
       sum(sr_return_quantity) AS store_returns_quantity,
       sum(cs_quantity) AS catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_year = 1999
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 9 AND 12 AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

# q28: six price buckets (global distinct counts), cross-joined
QUERIES[28] = """
SELECT *
FROM (SELECT avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(DISTINCT ss_list_price) b1_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 0 AND 5
        AND (ss_list_price BETWEEN 8 AND 8 + 10
             OR ss_coupon_amt BETWEEN 459 AND 459 + 1000
             OR ss_wholesale_cost BETWEEN 57 AND 57 + 20)) b1,
     (SELECT avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(DISTINCT ss_list_price) b2_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 6 AND 10
        AND (ss_list_price BETWEEN 90 AND 90 + 10
             OR ss_coupon_amt BETWEEN 2323 AND 2323 + 1000
             OR ss_wholesale_cost BETWEEN 31 AND 31 + 20)) b2,
     (SELECT avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(DISTINCT ss_list_price) b3_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 11 AND 15
        AND (ss_list_price BETWEEN 142 AND 142 + 10
             OR ss_coupon_amt BETWEEN 12214 AND 12214 + 1000
             OR ss_wholesale_cost BETWEEN 79 AND 79 + 20)) b3,
     (SELECT avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(DISTINCT ss_list_price) b4_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 16 AND 20
        AND (ss_list_price BETWEEN 135 AND 135 + 10
             OR ss_coupon_amt BETWEEN 6071 AND 6071 + 1000
             OR ss_wholesale_cost BETWEEN 38 AND 38 + 20)) b4
LIMIT 100
"""

# q76: null-FK sales by channel (UNION ALL with literal channel tags)
QUERIES[76] = """
SELECT channel, col_name, d_year, d_qoy, i_category,
       count(*) sales_cnt, sum(ext_sales_price) sales_amt
FROM (
    SELECT 'store' AS channel, 'ss_customer_sk' col_name, d_year, d_qoy,
           i_category, ss_ext_sales_price ext_sales_price
    FROM store_sales, item, date_dim
    WHERE ss_customer_sk IS NULL
      AND ss_sold_date_sk = d_date_sk
      AND ss_item_sk = i_item_sk
    UNION ALL
    SELECT 'web' AS channel, 'ws_promo_sk' col_name, d_year, d_qoy,
           i_category, ws_ext_sales_price ext_sales_price
    FROM web_sales, item, date_dim
    WHERE ws_promo_sk IS NULL
      AND ws_sold_date_sk = d_date_sk
      AND ws_item_sk = i_item_sk
    UNION ALL
    SELECT 'catalog' AS channel, 'cs_bill_customer_sk' col_name, d_year,
           d_qoy, i_category, cs_ext_sales_price ext_sales_price
    FROM catalog_sales, item, date_dim
    WHERE cs_bill_customer_sk IS NULL
      AND cs_sold_date_sk = d_date_sk
      AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 100
"""

# q88: store time-bucket counts, 4 cross-joined single-row subqueries
# (spec has 8; 4 keeps the text shorter with the same shape)
QUERIES[88] = """
SELECT *
FROM (SELECT count(*) h8_30_to_9
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 8 AND t_minute >= 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s1,
     (SELECT count(*) h9_to_9_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 9 AND t_minute < 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s2,
     (SELECT count(*) h9_30_to_10
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 9 AND t_minute >= 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s3,
     (SELECT count(*) h10_to_10_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 10 AND t_minute < 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s4
"""

# q62: web shipping day-buckets by warehouse/ship mode/site
QUERIES[62] = """
SELECT substr(w_warehouse_name, 1, 20) wh, sm_type, web_name,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                 AND ws_ship_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
                 AND ws_ship_date_sk - ws_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS d90,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
                THEN 1 ELSE 0 END) AS dmore
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wh, sm_type, web_name
LIMIT 100
"""

# q99: catalog shipping day-buckets by warehouse/ship mode/call center
QUERIES[99] = """
SELECT substr(w_warehouse_name, 1, 20) wh, sm_type, cc_name,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                 AND cs_ship_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
                 AND cs_ship_date_sk - cs_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS d90,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
                THEN 1 ELSE 0 END) AS dmore
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wh, sm_type, cc_name
LIMIT 100
"""

# Batch B: correlated subqueries, CTE self-joins, intersect/except

# q32: catalog excess discount (correlated avg over same item+dates)
QUERIES[32] = """
SELECT sum(cs_ext_discount_amt) AS excess_discount
FROM catalog_sales, item, date_dim
WHERE i_manufact_id = 269
  AND i_item_sk = cs_item_sk
  AND d_date BETWEEN DATE '1998-03-18' AND DATE '1998-03-18' + INTERVAL '90' DAY
  AND d_date_sk = cs_sold_date_sk
  AND cs_ext_discount_amt > (
        SELECT 1.3 * avg(cs_ext_discount_amt)
        FROM catalog_sales, date_dim
        WHERE cs_item_sk = i_item_sk
          AND d_date BETWEEN DATE '1998-03-18'
                         AND DATE '1998-03-18' + INTERVAL '90' DAY
          AND d_date_sk = cs_sold_date_sk)
"""

# q92: web excess discount (same shape on web_sales)
QUERIES[92] = """
SELECT sum(ws_ext_discount_amt) AS excess_discount
FROM web_sales, item, date_dim
WHERE i_manufact_id = 269
  AND i_item_sk = ws_item_sk
  AND d_date BETWEEN DATE '1998-03-18' AND DATE '1998-03-18' + INTERVAL '90' DAY
  AND d_date_sk = ws_sold_date_sk
  AND ws_ext_discount_amt > (
        SELECT 1.3 * avg(ws_ext_discount_amt)
        FROM web_sales, date_dim
        WHERE ws_item_sk = i_item_sk
          AND d_date BETWEEN DATE '1998-03-18'
                         AND DATE '1998-03-18' + INTERVAL '90' DAY
          AND d_date_sk = ws_sold_date_sk)
"""

# q38: customers active in all three channels in a month window
QUERIES[38] = """
SELECT count(*)
FROM (
    SELECT DISTINCT c_last_name, c_first_name, d_date
    FROM store_sales, date_dim, customer
    WHERE ss_sold_date_sk = d_date_sk
      AND ss_customer_sk = c_customer_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
    INTERSECT
    SELECT DISTINCT c_last_name, c_first_name, d_date
    FROM catalog_sales, date_dim, customer
    WHERE cs_sold_date_sk = d_date_sk
      AND cs_bill_customer_sk = c_customer_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
    INTERSECT
    SELECT DISTINCT c_last_name, c_first_name, d_date
    FROM web_sales, date_dim, customer
    WHERE ws_sold_date_sk = d_date_sk
      AND ws_bill_customer_sk = c_customer_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11) hot_cust
LIMIT 100
"""

# q87: customers in store but not catalog/web (EXCEPT chain)
QUERIES[87] = """
SELECT count(*)
FROM (SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM store_sales, date_dim, customer
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      EXCEPT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM catalog_sales, date_dim, customer
      WHERE cs_sold_date_sk = d_date_sk
        AND cs_bill_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      EXCEPT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM web_sales, date_dim, customer
      WHERE ws_sold_date_sk = d_date_sk
        AND ws_bill_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11) cool_cust
"""

# q31: county quarter-over-quarter growth, store vs web (CTE self-joins)
QUERIES[31] = """
WITH ss AS (
    SELECT ca_county, d_qoy, d_year, sum(ss_ext_sales_price) store_sales
    FROM store_sales, date_dim, customer_address
    WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
    GROUP BY ca_county, d_qoy, d_year),
ws AS (
    SELECT ca_county, d_qoy, d_year, sum(ws_ext_sales_price) web_sales
    FROM web_sales, date_dim, customer_address
    WHERE ws_sold_date_sk = d_date_sk AND ws_bill_addr_sk = ca_address_sk
    GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales store_q1_q2_increase
FROM ss ss1, ss ss2, ws ws1, ws ws2
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2000
  AND ss1.ca_county = ss2.ca_county
  AND ss2.d_qoy = 2 AND ss2.d_year = 2000
  AND ss2.ca_county = ws1.ca_county
  AND ws1.d_qoy = 1 AND ws1.d_year = 2000
  AND ws1.ca_county = ws2.ca_county
  AND ws2.d_qoy = 2 AND ws2.d_year = 2000
  AND CASE WHEN ws1.web_sales > 0
           THEN ws2.web_sales / ws1.web_sales ELSE NULL END >
      CASE WHEN ss1.store_sales > 0
           THEN ss2.store_sales / ss1.store_sales ELSE NULL END
ORDER BY ss1.ca_county
"""

# q16: catalog orders shipped from one warehouse with no returns
# (adapted: cc_county list reduced to one value)
QUERIES[16] = """
SELECT count(DISTINCT cs_order_number) AS order_count,
       sum(cs_ext_ship_cost) AS total_shipping_cost,
       sum(cs_net_profit) AS total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN DATE '2002-02-01' AND DATE '2002-02-01' + INTERVAL '60' DAY
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk
  AND ca_state = 'GA'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND cc_county = 'Williamson County'
  AND EXISTS (SELECT * FROM catalog_sales cs2
              WHERE cs1.cs_order_number = cs2.cs_order_number
                AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
"""

# q94: web orders shipped from one site with no returns
QUERIES[94] = """
SELECT count(DISTINCT ws_order_number) AS order_count,
       sum(ws_ext_ship_cost) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '1999-02-01' AND DATE '1999-02-01' + INTERVAL '60' DAY
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND EXISTS (SELECT * FROM web_sales ws2
              WHERE ws1.ws_order_number = ws2.ws_order_number
                AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
"""

# q61: promotional vs all sales ratio (two cross-joined aggregates)
QUERIES[61] = """
SELECT promotions, total,
       cast(promotions AS double) / cast(total AS double) * 100 AS pct
FROM (SELECT sum(ss_ext_sales_price) promotions
      FROM store_sales, store, promotion, date_dim, customer,
           customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_promo_sk = p_promo_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
             OR p_channel_tv = 'Y')
        AND s_gmt_offset = -5
        AND d_year = 1998
        AND d_moy = 11) promotional_sales,
     (SELECT sum(ss_ext_sales_price) total
      FROM store_sales, store, date_dim, customer, customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND s_gmt_offset = -5
        AND d_year = 1998
        AND d_moy = 11) all_sales
ORDER BY promotions, total
LIMIT 100
"""

# q90: web am/pm sales count ratio
QUERIES[90] = """
SELECT cast(amc AS double) / cast(pmc AS double) AS am_pm_ratio
FROM (SELECT count(*) amc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_ship_hdemo_sk = hd_demo_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 8 AND 9
        AND hd_dep_count = 6
        AND wp_char_count BETWEEN 5000 AND 5200) at_shift,
     (SELECT count(*) pmc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_ship_hdemo_sk = hd_demo_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 19 AND 20
        AND hd_dep_count = 6
        AND wp_char_count BETWEEN 5000 AND 5200) pm_shift
ORDER BY am_pm_ratio
LIMIT 100
"""

# q92 uses ws_ext_discount_amt; q90 needs ws_ship_hdemo_sk — adapted to
# available columns below if the original is missing.

# Batch C: EXISTS demographics, window ratios/ranks, returns analytics,
# weekly/yearly self-joins

# q10: county customers active in any channel, demographic counts
QUERIES[10] = """
SELECT cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Walker County', 'Richland County', 'Franklin Parish')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_moy BETWEEN 1 AND 4))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
LIMIT 100
"""

# q35: like q10 with aggregate triples per demographic
QUERIES[35] = """
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) cnt1, avg(cd_dep_count) a1, max(cd_dep_count) m1,
       sum(cd_dep_count) s1
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_qoy < 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_qoy < 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count
LIMIT 100
"""

# q12: web revenue share within class (window ratio)
QUERIES[12] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) AS itemrevenue,
       sum(ws_ext_sales_price) * 100 /
       sum(sum(ws_ext_sales_price)) OVER (PARTITION BY i_class)
       AS revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND ws_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-02-22' + INTERVAL '30' DAY
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

# q20: catalog revenue share within class (window ratio)
QUERIES[20] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) AS itemrevenue,
       sum(cs_ext_sales_price) * 100 /
       sum(sum(cs_ext_sales_price)) OVER (PARTITION BY i_class)
       AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-02-22' + INTERVAL '30' DAY
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

# q30: web returns per customer vs 1.2x state average (CTE reuse)
QUERIES[30] = """
WITH customer_total_return AS (
    SELECT wr_returning_customer_sk AS ctr_customer_sk,
           ca_state AS ctr_state,
           sum(wr_return_amt) AS ctr_total_return
    FROM web_returns, date_dim, customer_address
    WHERE wr_returned_date_sk = d_date_sk
      AND d_year = 2002
      AND wr_returning_addr_sk = ca_address_sk
    GROUP BY wr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_year, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (
        SELECT avg(ctr_total_return) * 1.2
        FROM customer_total_return ctr2
        WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_year, ctr_total_return
LIMIT 100
"""

# q81: catalog returns per customer vs 1.2x state average
QUERIES[81] = """
WITH customer_total_return AS (
    SELECT cr_returning_customer_sk AS ctr_customer_sk,
           ca_state AS ctr_state,
           sum(cr_return_amount) AS ctr_total_return
    FROM catalog_returns, date_dim, customer_address
    WHERE cr_returned_date_sk = d_date_sk
      AND d_year = 2000
      AND cr_returning_addr_sk = ca_address_sk
    GROUP BY cr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_city, ca_zip, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (
        SELECT avg(ctr_total_return) * 1.2
        FROM customer_total_return ctr2
        WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
         ca_city, ca_zip, ctr_total_return
LIMIT 100
"""

# q91: call center returns by demographic slice
QUERIES[91] = """
SELECT cc_name AS call_center, cc_manager AS manager,
       sum(cr_net_loss) AS returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND d_year = 1998
  AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
       OR (cd_marital_status = 'W'
           AND cd_education_status = 'Advanced Degree'))
  AND hd_buy_potential LIKE 'Unknown%'
GROUP BY cc_name, cc_manager, cd_marital_status, cd_education_status
ORDER BY returns_loss DESC, call_center, manager
"""

# q40: catalog sales +/- returns around a date by warehouse state
QUERIES[40] = """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_after
FROM catalog_sales
LEFT OUTER JOIN catalog_returns
  ON (cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk)
, warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
"""

# q50: store returns latency buckets by store
QUERIES[50] = """
SELECT s_store_name, s_market_id,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                THEN 1 ELSE 0 END) AS dmore
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
  AND ss_ticket_number = sr_ticket_number
  AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_market_id
ORDER BY s_store_name, s_market_id
LIMIT 100
"""

# q44: best/worst performing items by avg net profit (rank windows)
QUERIES[44] = """
SELECT asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
FROM (SELECT * FROM (
        SELECT item_sk, rank() OVER (ORDER BY rank_col ASC) rnk
        FROM (SELECT ss_item_sk item_sk, avg(ss_net_profit) rank_col
              FROM store_sales ss1
              WHERE ss_store_sk = 4
              GROUP BY ss_item_sk
              HAVING avg(ss_net_profit) > 0.9 * (
                  SELECT avg(ss_net_profit) rank_col
                  FROM store_sales
                  WHERE ss_store_sk = 4
                    AND ss_promo_sk IS NULL)) v1) v11
      WHERE rnk < 11) asceding,
     (SELECT * FROM (
        SELECT item_sk, rank() OVER (ORDER BY rank_col DESC) rnk
        FROM (SELECT ss_item_sk item_sk, avg(ss_net_profit) rank_col
              FROM store_sales ss1
              WHERE ss_store_sk = 4
              GROUP BY ss_item_sk
              HAVING avg(ss_net_profit) > 0.9 * (
                  SELECT avg(ss_net_profit) rank_col
                  FROM store_sales
                  WHERE ss_store_sk = 4
                    AND ss_promo_sk IS NULL)) v2) v21
      WHERE rnk < 11) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
"""

# q2: week-over-year web+catalog sales ratios (53-week offset self-join)
QUERIES[2] = """
WITH wscs AS (
    SELECT sold_date_sk, sales_price
    FROM (SELECT ws_sold_date_sk sold_date_sk,
                 ws_ext_sales_price sales_price
          FROM web_sales
          UNION ALL
          SELECT cs_sold_date_sk sold_date_sk,
                 cs_ext_sales_price sales_price
          FROM catalog_sales) x),
wswscs AS (
    SELECT d_week_seq,
           sum(CASE WHEN d_day_name = 'Sunday'
                    THEN sales_price ELSE NULL END) sun_sales,
           sum(CASE WHEN d_day_name = 'Monday'
                    THEN sales_price ELSE NULL END) mon_sales,
           sum(CASE WHEN d_day_name = 'Friday'
                    THEN sales_price ELSE NULL END) fri_sales,
           sum(CASE WHEN d_day_name = 'Saturday'
                    THEN sales_price ELSE NULL END) sat_sales
    FROM wscs, date_dim
    WHERE d_date_sk = sold_date_sk
    GROUP BY d_week_seq)
SELECT d_week_seq1, round(sun_sales1 / sun_sales2, 2) r1,
       round(mon_sales1 / mon_sales2, 2) r2,
       round(fri_sales1 / fri_sales2, 2) r3,
       round(sat_sales1 / sat_sales2, 2) r4
FROM (SELECT wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             mon_sales mon_sales1, fri_sales fri_sales1,
             sat_sales sat_sales1
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq
        AND d_year = 2001) y,
     (SELECT wswscs.d_week_seq d_week_seq2, sun_sales sun_sales2,
             mon_sales mon_sales2, fri_sales fri_sales2,
             sat_sales sat_sales2
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq
        AND d_year = 2002) z
WHERE d_week_seq1 = d_week_seq2 - 53
ORDER BY d_week_seq1
"""

# q74: year-over-year customer growth, store vs web (adapted: growth
# ratio comparison on sums)
QUERIES[74] = """
WITH year_total AS (
    SELECT c_customer_id customer_id, c_first_name customer_first_name,
           c_last_name customer_last_name, d_year AS year1,
           sum(ss_net_paid) year_total, 's' sale_type
    FROM customer, store_sales, date_dim
    WHERE c_customer_sk = ss_customer_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year IN (2001, 2002)
    GROUP BY c_customer_id, c_first_name, c_last_name, d_year
    UNION ALL
    SELECT c_customer_id customer_id, c_first_name customer_first_name,
           c_last_name customer_last_name, d_year AS year1,
           sum(ws_net_paid) year_total, 'w' sale_type
    FROM customer, web_sales, date_dim
    WHERE c_customer_sk = ws_bill_customer_sk
      AND ws_sold_date_sk = d_date_sk
      AND d_year IN (2001, 2002)
    GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.year1 = 2001
  AND t_s_secyear.year1 = 2002
  AND t_w_firstyear.year1 = 2001
  AND t_w_secyear.year1 = 2002
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE NULL END >
      CASE WHEN t_s_firstyear.year_total > 0
           THEN t_s_secyear.year_total / t_s_firstyear.year_total
           ELSE NULL END
ORDER BY 1, 2, 3
LIMIT 100
"""
