"""TPC-DS benchmark queries (engine-supported subset).

Written from the TPC-DS specification's query definitions against the
generated schema subset (trino_tpu/connectors/tpcds/datagen.py); where a
spec query touches columns the generator does not produce, the query is
adapted (noted per query). Every query runs against the sqlite oracle on
identical data, so results are verified regardless of adaptation.
"""

QUERIES = {}

# q3: brand revenue for a manufacturer in November
QUERIES[3] = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, brand_id
LIMIT 100
"""

# q7: average store-sales metrics for a demographic slice
QUERIES[7] = """
SELECT i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

# q19: brand revenue where customer and store are in different zip prefixes
QUERIES[19] = """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 8
  AND d_moy = 11
  AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand_id, i_brand, i_manufact_id, i_manufact
ORDER BY ext_price DESC, brand_id, i_manufact_id
LIMIT 100
"""

# q26: catalog-sales averages for a demographic slice (adapted: generated
# catalog_sales has no cs_coupon_amt; uses cs_net_profit for agg4)
QUERIES[26] = """
SELECT i_item_id,
       avg(cs_quantity) agg1,
       avg(cs_list_price) agg2,
       avg(cs_sales_price) agg3,
       avg(cs_net_profit) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk
  AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

# q42: category revenue in a month
QUERIES[42] = """
SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) revenue
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY revenue DESC, d_year, i_category_id, i_category
LIMIT 100
"""

# q52: brand revenue in a month
QUERIES[52] = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, brand_id
LIMIT 100
"""

# q53: quarterly manufacturer sales vs their average (window over agg)
QUERIES[53] = """
SELECT i_manufact_id, d_qoy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_manufact_id) avg_quarterly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 1999
  AND i_category IN ('Books', 'Children', 'Electronics')
GROUP BY i_manufact_id, d_qoy
ORDER BY i_manufact_id, d_qoy
LIMIT 100
"""

# q55: brand revenue for one manager's items in a month
QUERIES[55] = """
SELECT i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, brand_id
LIMIT 100
"""

# q65: items whose store revenue is at most 10% of the store average
QUERIES[65] = """
SELECT s_store_name, sc.sk_item, sc.revenue
FROM store,
     (SELECT ss_store_sk sk_store, ss_item_sk sk_item,
             sum(ss_sales_price) revenue
      FROM store_sales GROUP BY ss_store_sk, ss_item_sk) sc,
     (SELECT ss_store_sk sk_store2, avg(revenue) ave
      FROM (SELECT ss_store_sk, ss_item_sk,
                   sum(ss_sales_price) revenue
            FROM store_sales GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb
WHERE s_store_sk = sc.sk_store
  AND sb.sk_store2 = sc.sk_store
  AND sc.revenue <= 0.1 * sb.ave
ORDER BY s_store_name, sc.revenue, sc.sk_item
LIMIT 100
"""

# q68: customers whose current city differs from the purchase city
QUERIES[68] = """
SELECT c_last_name, c_first_name, bought_city,
       ms.ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) ms,
     customer, customer_address current_addr
WHERE ms.ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ms.ss_ticket_number, extended_price
LIMIT 100
"""

# q73: ticket row counts per customer for a demographic slice
QUERIES[73] = """
SELECT c_last_name, c_first_name, dj.ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND d_dom BETWEEN 1 AND 2
        AND hd_buy_potential = '1001-5000'
        AND hd_vehicle_count > 0
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE dj.ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name, c_first_name, dj.ss_ticket_number
LIMIT 100
"""

# q79: per-ticket coupon amount and profit for a demographic slice
QUERIES[79] = """
SELECT c_last_name, c_first_name, ms.s_city, profit,
       ms.ss_ticket_number, amt
FROM (SELECT ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
WHERE ms.ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, ms.s_city, profit,
         ms.ss_ticket_number
LIMIT 100
"""

# q93: actual sales after returns for one return reason
QUERIES[93] = """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END act_sales
      FROM store_sales
           LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
                AND sr_ticket_number = ss_ticket_number,
           reason
      WHERE sr_reason_sk = r_reason_sk
        AND r_reason_desc = 'Did not fit') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk NULLS FIRST
LIMIT 100
"""

# q96: sales volume in a store/time/demographic window
QUERIES[96] = """
SELECT count(*) cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
  AND s_store_name = 'ese'
"""

# q27: store-sales averages with ROLLUP over state (adapted: the generated
# schema rolls up over s_state only; spec adds i_item_id grouping)
QUERIES[27] = """
SELECT i_item_id, s_state, avg(ss_quantity) agg1,
       avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2002
  AND s_state IN ('TN', 'TX')
  AND i_manufact_id < 30
GROUP BY i_item_id, s_state
ORDER BY i_item_id, s_state
LIMIT 100
"""

# q34: households buying 15-20 items per ticket (count HAVING band)
QUERIES[34] = """
SELECT c_last_name, c_first_name, dn.ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (d_dom BETWEEN 1 AND 3 OR d_dom BETWEEN 25 AND 28)
        AND hd_buy_potential = '>10000'
        AND hd_vehicle_count > 0
        AND d_year IN (1999, 2000, 2001)
      GROUP BY ss_ticket_number, ss_customer_sk
      HAVING count(*) BETWEEN 5 AND 20) dn, customer
WHERE dn.ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, dn.ss_ticket_number DESC, cnt
LIMIT 100
"""

# q37: items with inventory in a quantity band sold through catalog
QUERIES[37] = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 20 AND 50
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-02-01' AND DATE '2000-04-01'
  AND i_manufact_id IN (100, 200, 300, 400)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

# q43: store sales by day of week (CASE pivot)
QUERIES[43] = """
SELECT s_store_name, s_store_id,
       sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE NULL END) sun_sales,
       sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE NULL END) mon_sales,
       sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE NULL END) fri_sales,
       sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -500
  AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id
LIMIT 100
"""

# q46: city mismatch between purchase and residence (like q68 with dow)
QUERIES[46] = """
SELECT c_last_name, c_first_name, ca_city,
       dn.bought_city, dn.ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_dow IN (6, 0)
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE dn.ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> dn.bought_city
ORDER BY c_last_name, c_first_name, ca_city, dn.bought_city,
         dn.ss_ticket_number
LIMIT 100
"""

# q63: monthly manager sales vs their yearly average (window over agg)
QUERIES[63] = """
SELECT i_manager_id, d_moy, sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_manager_id) avg_monthly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 2001
  AND i_category IN ('Books', 'Electronics', 'Sports')
GROUP BY i_manager_id, d_moy
ORDER BY i_manager_id, d_moy
LIMIT 100
"""

# q82: items with store inventory in a band (store-sales twin of q37)
QUERIES[82] = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 30 AND 60
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '1999-05-01' AND DATE '1999-07-01'
  AND i_manufact_id IN (50, 150, 250, 350)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

# q89: weekly category sales vs class average (window over agg)
QUERIES[89] = """
SELECT i_category, i_class, s_store_name, d_moy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_category, i_class,
                 s_store_name) avg_monthly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 2000
  AND i_category IN ('Home', 'Music', 'Shoes')
  AND i_class IN ('accent', 'classical', 'athletic')
GROUP BY i_category, i_class, s_store_name, d_moy
ORDER BY i_category, i_class, s_store_name, d_moy
LIMIT 100
"""

# q98: item revenue share within class (sum over partition of agg)
QUERIES[98] = """
SELECT i_item_id, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100 /
           sum(sum(ss_ext_sales_price))
               OVER (PARTITION BY i_class) revenueratio
FROM store_sales, item, date_dim
WHERE ss_item_sk = i_item_sk
  AND i_category IN ('Books', 'Jewelry', 'Women')
  AND ss_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-03-24'
GROUP BY i_item_id, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, revenueratio
LIMIT 100
"""

# q33: manufacturer revenue across all three channels for one category
QUERIES[33] = """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
"""

# q48: quantity sold under demographic/address OR-band predicates
QUERIES[48] = """
SELECT sum(ss_quantity) q
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'D'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'KS')
        AND ss_net_profit BETWEEN 0 AND 2000)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('CA', 'NY', 'WA')
        AND ss_net_profit BETWEEN 150 AND 3000)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('GA', 'MN', 'NC')
        AND ss_net_profit BETWEEN 50 AND 25000))
"""

# q56: color-item revenue across the three channels
QUERIES[56] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

# q60: category-item revenue across the three channels
QUERIES[60] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

# q13: average store-sales metrics under demographic/address OR bands
QUERIES[13] = """
SELECT avg(ss_quantity) a1, avg(ss_ext_sales_price) a2,
       avg(ss_ext_wholesale_cost) a3, sum(ss_ext_wholesale_cost) a4
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 50.00 AND 100.00
        AND hd_dep_count = 1)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'W'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 150.00 AND 200.00
        AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'KS')
        AND ss_net_profit BETWEEN 100 AND 200)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('CA', 'NY', 'WA')
        AND ss_net_profit BETWEEN 150 AND 300)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('GA', 'MN', 'NC')
        AND ss_net_profit BETWEEN 50 AND 250))
"""

# q45: web sales by zip prefix or flagged items
QUERIES[45] = """
SELECT ca_zip, ca_city, sum(ws_sales_price) total
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (substr(ca_zip, 1, 5) IN
         ('85669', '86197', '88274', '83405', '86475')
    OR i_item_id IN (SELECT i_item_id FROM item
                     WHERE i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19, 23)))
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
LIMIT 100
"""

# q69: demographic profile of store customers absent from other channels
QUERIES[69] = """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) cnt1, cd_purchase_estimate, count(*) cnt2
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('KY', 'GA', 'NM')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT 1 FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2001 AND d_moy BETWEEN 4 AND 6)
  AND (NOT EXISTS (SELECT 1 FROM web_sales, date_dim
                   WHERE c.c_customer_sk = ws_bill_customer_sk
                     AND ws_sold_date_sk = d_date_sk
                     AND d_year = 2001 AND d_moy BETWEEN 4 AND 6))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
LIMIT 100
"""
