"""TPC-DS benchmark queries (engine-supported subset).

Written from the TPC-DS specification's query definitions against the
generated schema subset (trino_tpu/connectors/tpcds/datagen.py); where a
spec query touches columns the generator does not produce, the query is
adapted (noted per query). Every query runs against the sqlite oracle on
identical data, so results are verified regardless of adaptation.
"""

QUERIES = {}

# q3: brand revenue for a manufacturer in November
QUERIES[3] = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, brand_id
LIMIT 100
"""

# q7: average store-sales metrics for a demographic slice
QUERIES[7] = """
SELECT i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

# q19: brand revenue where customer and store are in different zip prefixes
QUERIES[19] = """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 8
  AND d_moy = 11
  AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand_id, i_brand, i_manufact_id, i_manufact
ORDER BY ext_price DESC, brand_id, i_manufact_id
LIMIT 100
"""

# q26: catalog-sales averages for a demographic slice (adapted: generated
# catalog_sales has no cs_coupon_amt; uses cs_net_profit for agg4)
QUERIES[26] = """
SELECT i_item_id,
       avg(cs_quantity) agg1,
       avg(cs_list_price) agg2,
       avg(cs_sales_price) agg3,
       avg(cs_net_profit) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk
  AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
"""

# q42: category revenue in a month
QUERIES[42] = """
SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) revenue
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY revenue DESC, d_year, i_category_id, i_category
LIMIT 100
"""

# q52: brand revenue in a month
QUERIES[52] = """
SELECT d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, brand_id
LIMIT 100
"""

# q53: quarterly manufacturer sales vs their average (window over agg)
QUERIES[53] = """
SELECT i_manufact_id, d_qoy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_manufact_id) avg_quarterly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 1999
  AND i_category IN ('Books', 'Children', 'Electronics')
GROUP BY i_manufact_id, d_qoy
ORDER BY i_manufact_id, d_qoy
LIMIT 100
"""

# q55: brand revenue for one manager's items in a month
QUERIES[55] = """
SELECT i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, brand_id
LIMIT 100
"""

# q65: items whose store revenue is at most 10% of the store average
QUERIES[65] = """
SELECT s_store_name, sc.sk_item, sc.revenue
FROM store,
     (SELECT ss_store_sk sk_store, ss_item_sk sk_item,
             sum(ss_sales_price) revenue
      FROM store_sales GROUP BY ss_store_sk, ss_item_sk) sc,
     (SELECT ss_store_sk sk_store2, avg(revenue) ave
      FROM (SELECT ss_store_sk, ss_item_sk,
                   sum(ss_sales_price) revenue
            FROM store_sales GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb
WHERE s_store_sk = sc.sk_store
  AND sb.sk_store2 = sc.sk_store
  AND sc.revenue <= 0.1 * sb.ave
ORDER BY s_store_name, sc.revenue, sc.sk_item
LIMIT 100
"""

# q68: customers whose current city differs from the purchase city
QUERIES[68] = """
SELECT c_last_name, c_first_name, bought_city,
       ms.ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) ms,
     customer, customer_address current_addr
WHERE ms.ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ms.ss_ticket_number, extended_price
LIMIT 100
"""

# q73: ticket row counts per customer for a demographic slice
QUERIES[73] = """
SELECT c_last_name, c_first_name, dj.ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND d_dom BETWEEN 1 AND 2
        AND hd_buy_potential = '1001-5000'
        AND hd_vehicle_count > 0
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE dj.ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name, c_first_name, dj.ss_ticket_number
LIMIT 100
"""

# q79: per-ticket coupon amount and profit for a demographic slice
QUERIES[79] = """
SELECT c_last_name, c_first_name, ms.s_city, profit,
       ms.ss_ticket_number, amt
FROM (SELECT ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
WHERE ms.ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, ms.s_city, profit,
         ms.ss_ticket_number
LIMIT 100
"""

# q93: actual sales after returns for one return reason
QUERIES[93] = """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END act_sales
      FROM store_sales
           LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
                AND sr_ticket_number = ss_ticket_number,
           reason
      WHERE sr_reason_sk = r_reason_sk
        AND r_reason_desc = 'Did not fit') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk NULLS FIRST
LIMIT 100
"""

# q96: sales volume in a store/time/demographic window
QUERIES[96] = """
SELECT count(*) cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
  AND s_store_name = 'ese'
"""

# q27: store-sales averages with ROLLUP over state (adapted: the generated
# schema rolls up over s_state only; spec adds i_item_id grouping)
QUERIES[27] = """
SELECT i_item_id, s_state, avg(ss_quantity) agg1,
       avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2002
  AND s_state IN ('TN', 'TX')
  AND i_manufact_id < 30
GROUP BY i_item_id, s_state
ORDER BY i_item_id, s_state
LIMIT 100
"""

# q34: households buying 15-20 items per ticket (count HAVING band)
QUERIES[34] = """
SELECT c_last_name, c_first_name, dn.ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (d_dom BETWEEN 1 AND 3 OR d_dom BETWEEN 25 AND 28)
        AND hd_buy_potential = '>10000'
        AND hd_vehicle_count > 0
        AND d_year IN (1999, 2000, 2001)
      GROUP BY ss_ticket_number, ss_customer_sk
      HAVING count(*) BETWEEN 5 AND 20) dn, customer
WHERE dn.ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, dn.ss_ticket_number DESC, cnt
LIMIT 100
"""

# q37: items with inventory in a quantity band sold through catalog
QUERIES[37] = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 20 AND 50
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-02-01' AND DATE '2000-04-01'
  AND i_manufact_id IN (100, 200, 300, 400)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

# q43: store sales by day of week (CASE pivot)
QUERIES[43] = """
SELECT s_store_name, s_store_id,
       sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE NULL END) sun_sales,
       sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE NULL END) mon_sales,
       sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE NULL END) fri_sales,
       sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -500
  AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id
LIMIT 100
"""

# q46: city mismatch between purchase and residence (like q68 with dow)
QUERIES[46] = """
SELECT c_last_name, c_first_name, ca_city,
       dn.bought_city, dn.ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_dow IN (6, 0)
        AND d_year = 1999
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE dn.ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> dn.bought_city
ORDER BY c_last_name, c_first_name, ca_city, dn.bought_city,
         dn.ss_ticket_number
LIMIT 100
"""

# q63: monthly manager sales vs their yearly average (window over agg)
QUERIES[63] = """
SELECT i_manager_id, d_moy, sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_manager_id) avg_monthly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 2001
  AND i_category IN ('Books', 'Electronics', 'Sports')
GROUP BY i_manager_id, d_moy
ORDER BY i_manager_id, d_moy
LIMIT 100
"""

# q82: items with store inventory in a band (store-sales twin of q37)
QUERIES[82] = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 30 AND 60
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '1999-05-01' AND DATE '1999-07-01'
  AND i_manufact_id IN (50, 150, 250, 350)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
LIMIT 100
"""

# q89: weekly category sales vs class average (window over agg)
QUERIES[89] = """
SELECT i_category, i_class, s_store_name, d_moy,
       sum(ss_sales_price) sum_sales,
       avg(sum(ss_sales_price))
           OVER (PARTITION BY i_category, i_class,
                 s_store_name) avg_monthly_sales
FROM item, store_sales, date_dim, store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_year = 2000
  AND i_category IN ('Home', 'Music', 'Shoes')
  AND i_class IN ('accent', 'classical', 'athletic')
GROUP BY i_category, i_class, s_store_name, d_moy
ORDER BY i_category, i_class, s_store_name, d_moy
LIMIT 100
"""

# q98: item revenue share within class (sum over partition of agg)
QUERIES[98] = """
SELECT i_item_id, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100 /
           sum(sum(ss_ext_sales_price))
               OVER (PARTITION BY i_class) revenueratio
FROM store_sales, item, date_dim
WHERE ss_item_sk = i_item_sk
  AND i_category IN ('Books', 'Jewelry', 'Women')
  AND ss_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-03-24'
GROUP BY i_item_id, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, revenueratio
LIMIT 100
"""

# q33: manufacturer revenue across all three channels for one category
QUERIES[33] = """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
"""

# q48: quantity sold under demographic/address OR-band predicates
QUERIES[48] = """
SELECT sum(ss_quantity) q
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'D'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'KS')
        AND ss_net_profit BETWEEN 0 AND 2000)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('CA', 'NY', 'WA')
        AND ss_net_profit BETWEEN 150 AND 3000)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('GA', 'MN', 'NC')
        AND ss_net_profit BETWEEN 50 AND 25000))
"""

# q56: color-item revenue across the three channels
QUERIES[56] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('azure', 'burlywood', 'chiffon'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

# q60: category-item revenue across the three channels
QUERIES[60] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 9
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -600
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

# q13: average store-sales metrics under demographic/address OR bands
QUERIES[13] = """
SELECT avg(ss_quantity) a1, avg(ss_ext_sales_price) a2,
       avg(ss_ext_wholesale_cost) a3, sum(ss_ext_wholesale_cost) a4
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 50.00 AND 100.00
        AND hd_dep_count = 1)
    OR (ss_hdemo_sk = hd_demo_sk
        AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'W'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 150.00 AND 200.00
        AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'KS')
        AND ss_net_profit BETWEEN 100 AND 200)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('CA', 'NY', 'WA')
        AND ss_net_profit BETWEEN 150 AND 300)
    OR (ss_addr_sk = ca_address_sk
        AND ca_country = 'United States'
        AND ca_state IN ('GA', 'MN', 'NC')
        AND ss_net_profit BETWEEN 50 AND 250))
"""

# q45: web sales by zip prefix or flagged items
QUERIES[45] = """
SELECT ca_zip, ca_city, sum(ws_sales_price) total
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (substr(ca_zip, 1, 5) IN
         ('85669', '86197', '88274', '83405', '86475')
    OR i_item_id IN (SELECT i_item_id FROM item
                     WHERE i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19, 23)))
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
LIMIT 100
"""

# q69: demographic profile of store customers absent from other channels
QUERIES[69] = """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) cnt1, cd_purchase_estimate, count(*) cnt2
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('KY', 'GA', 'NM')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT 1 FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2001 AND d_moy BETWEEN 4 AND 6)
  AND (NOT EXISTS (SELECT 1 FROM web_sales, date_dim
                   WHERE c.c_customer_sk = ws_bill_customer_sk
                     AND ws_sold_date_sk = d_date_sk
                     AND d_year = 2001 AND d_moy BETWEEN 4 AND 6))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
LIMIT 100
"""


# ---- round 2 expansion: 31 additional spec queries ----

# Batch A: single-fact aggregations, case buckets, channel unions
# (written from the TPC-DS spec query definitions; adapted where noted)

# q9: CASE bucket picks between avg columns by count thresholds
QUERIES[9] = """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 2000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 3000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 1000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3
FROM reason
WHERE r_reason_sk = 1
"""

# q15: catalog sales by zip for qualifying zips/states/prices
QUERIES[15] = """
SELECT ca_zip, sum(cs_sales_price) total
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348',
                                '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
"""

# q21: inventory before/after a date, ratio-bounded
QUERIES[21] = """
SELECT w_warehouse_name, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
FROM inventory, warehouse, item, date_dim
WHERE i_item_sk = inv_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk
  AND i_current_price BETWEEN 0.99 AND 1.49
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_warehouse_name, i_item_id
HAVING sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) > 0
   AND sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 3 >=
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 2
   AND sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 3 >=
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) * 2
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
"""

# q25: store/returns/catalog profit by item and store
QUERIES[25] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) AS store_sales_profit,
       sum(sr_net_loss) AS store_returns_loss,
       sum(cs_net_profit) AS catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_year = 2001
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2001
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2001
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

# q29: same join shape, quantities
QUERIES[29] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) AS store_sales_quantity,
       sum(sr_return_quantity) AS store_returns_quantity,
       sum(cs_quantity) AS catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_year = 1999
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 9 AND 12 AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

# q28: six price buckets (global distinct counts), cross-joined
QUERIES[28] = """
SELECT *
FROM (SELECT avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(DISTINCT ss_list_price) b1_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 0 AND 5
        AND (ss_list_price BETWEEN 8 AND 8 + 10
             OR ss_coupon_amt BETWEEN 459 AND 459 + 1000
             OR ss_wholesale_cost BETWEEN 57 AND 57 + 20)) b1,
     (SELECT avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(DISTINCT ss_list_price) b2_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 6 AND 10
        AND (ss_list_price BETWEEN 90 AND 90 + 10
             OR ss_coupon_amt BETWEEN 2323 AND 2323 + 1000
             OR ss_wholesale_cost BETWEEN 31 AND 31 + 20)) b2,
     (SELECT avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(DISTINCT ss_list_price) b3_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 11 AND 15
        AND (ss_list_price BETWEEN 142 AND 142 + 10
             OR ss_coupon_amt BETWEEN 12214 AND 12214 + 1000
             OR ss_wholesale_cost BETWEEN 79 AND 79 + 20)) b3,
     (SELECT avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(DISTINCT ss_list_price) b4_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 16 AND 20
        AND (ss_list_price BETWEEN 135 AND 135 + 10
             OR ss_coupon_amt BETWEEN 6071 AND 6071 + 1000
             OR ss_wholesale_cost BETWEEN 38 AND 38 + 20)) b4
LIMIT 100
"""

# q76: null-FK sales by channel (UNION ALL with literal channel tags)
QUERIES[76] = """
SELECT channel, col_name, d_year, d_qoy, i_category,
       count(*) sales_cnt, sum(ext_sales_price) sales_amt
FROM (
    SELECT 'store' AS channel, 'ss_customer_sk' col_name, d_year, d_qoy,
           i_category, ss_ext_sales_price ext_sales_price
    FROM store_sales, item, date_dim
    WHERE ss_customer_sk IS NULL
      AND ss_sold_date_sk = d_date_sk
      AND ss_item_sk = i_item_sk
    UNION ALL
    SELECT 'web' AS channel, 'ws_promo_sk' col_name, d_year, d_qoy,
           i_category, ws_ext_sales_price ext_sales_price
    FROM web_sales, item, date_dim
    WHERE ws_promo_sk IS NULL
      AND ws_sold_date_sk = d_date_sk
      AND ws_item_sk = i_item_sk
    UNION ALL
    SELECT 'catalog' AS channel, 'cs_bill_customer_sk' col_name, d_year,
           d_qoy, i_category, cs_ext_sales_price ext_sales_price
    FROM catalog_sales, item, date_dim
    WHERE cs_bill_customer_sk IS NULL
      AND cs_sold_date_sk = d_date_sk
      AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 100
"""

# q88: store time-bucket counts, 4 cross-joined single-row subqueries
# (spec has 8; 4 keeps the text shorter with the same shape)
QUERIES[88] = """
SELECT *
FROM (SELECT count(*) h8_30_to_9
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 8 AND t_minute >= 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s1,
     (SELECT count(*) h9_to_9_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 9 AND t_minute < 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s2,
     (SELECT count(*) h9_30_to_10
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 9 AND t_minute >= 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s3,
     (SELECT count(*) h10_to_10_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = t_time_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND t_hour = 10 AND t_minute < 30
        AND ((hd_dep_count = 4 AND hd_vehicle_count <= 6)
             OR (hd_dep_count = 2 AND hd_vehicle_count <= 4)
             OR (hd_dep_count = 0 AND hd_vehicle_count <= 2))
        AND s_store_name = 'ese') s4
"""

# q62: web shipping day-buckets by warehouse/ship mode/site
QUERIES[62] = """
SELECT substr(w_warehouse_name, 1, 20) wh, sm_type, web_name,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                 AND ws_ship_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
                 AND ws_ship_date_sk - ws_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS d90,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
                THEN 1 ELSE 0 END) AS dmore
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wh, sm_type, web_name
LIMIT 100
"""

# q99: catalog shipping day-buckets by warehouse/ship mode/call center
QUERIES[99] = """
SELECT substr(w_warehouse_name, 1, 20) wh, sm_type, cc_name,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                 AND cs_ship_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
                 AND cs_ship_date_sk - cs_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS d90,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
                THEN 1 ELSE 0 END) AS dmore
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wh, sm_type, cc_name
LIMIT 100
"""

# Batch B: correlated subqueries, CTE self-joins, intersect/except

# q32: catalog excess discount (correlated avg over same item+dates)
QUERIES[32] = """
SELECT sum(cs_ext_discount_amt) AS excess_discount
FROM catalog_sales, item, date_dim
WHERE i_manufact_id = 269
  AND i_item_sk = cs_item_sk
  AND d_date BETWEEN DATE '1998-03-18' AND DATE '1998-03-18' + INTERVAL '90' DAY
  AND d_date_sk = cs_sold_date_sk
  AND cs_ext_discount_amt > (
        SELECT 1.3 * avg(cs_ext_discount_amt)
        FROM catalog_sales, date_dim
        WHERE cs_item_sk = i_item_sk
          AND d_date BETWEEN DATE '1998-03-18'
                         AND DATE '1998-03-18' + INTERVAL '90' DAY
          AND d_date_sk = cs_sold_date_sk)
"""

# q92: web excess discount (same shape on web_sales)
QUERIES[92] = """
SELECT sum(ws_ext_discount_amt) AS excess_discount
FROM web_sales, item, date_dim
WHERE i_manufact_id = 269
  AND i_item_sk = ws_item_sk
  AND d_date BETWEEN DATE '1998-03-18' AND DATE '1998-03-18' + INTERVAL '90' DAY
  AND d_date_sk = ws_sold_date_sk
  AND ws_ext_discount_amt > (
        SELECT 1.3 * avg(ws_ext_discount_amt)
        FROM web_sales, date_dim
        WHERE ws_item_sk = i_item_sk
          AND d_date BETWEEN DATE '1998-03-18'
                         AND DATE '1998-03-18' + INTERVAL '90' DAY
          AND d_date_sk = ws_sold_date_sk)
"""

# q38: customers active in all three channels in a month window
QUERIES[38] = """
SELECT count(*)
FROM (
    SELECT DISTINCT c_last_name, c_first_name, d_date
    FROM store_sales, date_dim, customer
    WHERE ss_sold_date_sk = d_date_sk
      AND ss_customer_sk = c_customer_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
    INTERSECT
    SELECT DISTINCT c_last_name, c_first_name, d_date
    FROM catalog_sales, date_dim, customer
    WHERE cs_sold_date_sk = d_date_sk
      AND cs_bill_customer_sk = c_customer_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11
    INTERSECT
    SELECT DISTINCT c_last_name, c_first_name, d_date
    FROM web_sales, date_dim, customer
    WHERE ws_sold_date_sk = d_date_sk
      AND ws_bill_customer_sk = c_customer_sk
      AND d_month_seq BETWEEN 1200 AND 1200 + 11) hot_cust
LIMIT 100
"""

# q87: customers in store but not catalog/web (EXCEPT chain)
QUERIES[87] = """
SELECT count(*)
FROM (SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM store_sales, date_dim, customer
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      EXCEPT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM catalog_sales, date_dim, customer
      WHERE cs_sold_date_sk = d_date_sk
        AND cs_bill_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11
      EXCEPT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM web_sales, date_dim, customer
      WHERE ws_sold_date_sk = d_date_sk
        AND ws_bill_customer_sk = c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11) cool_cust
"""

# q31: county quarter-over-quarter growth, store vs web (CTE self-joins)
QUERIES[31] = """
WITH ss AS (
    SELECT ca_county, d_qoy, d_year, sum(ss_ext_sales_price) store_sales
    FROM store_sales, date_dim, customer_address
    WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
    GROUP BY ca_county, d_qoy, d_year),
ws AS (
    SELECT ca_county, d_qoy, d_year, sum(ws_ext_sales_price) web_sales
    FROM web_sales, date_dim, customer_address
    WHERE ws_sold_date_sk = d_date_sk AND ws_bill_addr_sk = ca_address_sk
    GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales store_q1_q2_increase
FROM ss ss1, ss ss2, ws ws1, ws ws2
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2000
  AND ss1.ca_county = ss2.ca_county
  AND ss2.d_qoy = 2 AND ss2.d_year = 2000
  AND ss2.ca_county = ws1.ca_county
  AND ws1.d_qoy = 1 AND ws1.d_year = 2000
  AND ws1.ca_county = ws2.ca_county
  AND ws2.d_qoy = 2 AND ws2.d_year = 2000
  AND CASE WHEN ws1.web_sales > 0
           THEN ws2.web_sales / ws1.web_sales ELSE NULL END >
      CASE WHEN ss1.store_sales > 0
           THEN ss2.store_sales / ss1.store_sales ELSE NULL END
ORDER BY ss1.ca_county
"""

# q16: catalog orders shipped from one warehouse with no returns
# (adapted: cc_county list reduced to one value)
QUERIES[16] = """
SELECT count(DISTINCT cs_order_number) AS order_count,
       sum(cs_ext_ship_cost) AS total_shipping_cost,
       sum(cs_net_profit) AS total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN DATE '2002-02-01' AND DATE '2002-02-01' + INTERVAL '60' DAY
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk
  AND ca_state = 'GA'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND cc_county = 'Williamson County'
  AND EXISTS (SELECT * FROM catalog_sales cs2
              WHERE cs1.cs_order_number = cs2.cs_order_number
                AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
"""

# q94: web orders shipped from one site with no returns
QUERIES[94] = """
SELECT count(DISTINCT ws_order_number) AS order_count,
       sum(ws_ext_ship_cost) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '1999-02-01' AND DATE '1999-02-01' + INTERVAL '60' DAY
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND EXISTS (SELECT * FROM web_sales ws2
              WHERE ws1.ws_order_number = ws2.ws_order_number
                AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
"""

# q61: promotional vs all sales ratio (two cross-joined aggregates)
QUERIES[61] = """
SELECT promotions, total,
       cast(promotions AS double) / cast(total AS double) * 100 AS pct
FROM (SELECT sum(ss_ext_sales_price) promotions
      FROM store_sales, store, promotion, date_dim, customer,
           customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_promo_sk = p_promo_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
             OR p_channel_tv = 'Y')
        AND s_gmt_offset = -5
        AND d_year = 1998
        AND d_moy = 11) promotional_sales,
     (SELECT sum(ss_ext_sales_price) total
      FROM store_sales, store, date_dim, customer, customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND s_gmt_offset = -5
        AND d_year = 1998
        AND d_moy = 11) all_sales
ORDER BY promotions, total
LIMIT 100
"""

# q90: web am/pm sales count ratio
QUERIES[90] = """
SELECT cast(amc AS double) / cast(pmc AS double) AS am_pm_ratio
FROM (SELECT count(*) amc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_ship_hdemo_sk = hd_demo_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 8 AND 9
        AND hd_dep_count = 6
        AND wp_char_count BETWEEN 5000 AND 5200) at_shift,
     (SELECT count(*) pmc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_ship_hdemo_sk = hd_demo_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 19 AND 20
        AND hd_dep_count = 6
        AND wp_char_count BETWEEN 5000 AND 5200) pm_shift
ORDER BY am_pm_ratio
LIMIT 100
"""

# q92 uses ws_ext_discount_amt; q90 needs ws_ship_hdemo_sk — adapted to
# available columns below if the original is missing.

# Batch C: EXISTS demographics, window ratios/ranks, returns analytics,
# weekly/yearly self-joins

# q10: county customers active in any channel, demographic counts
QUERIES[10] = """
SELECT cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Walker County', 'Richland County', 'Franklin Parish')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_moy BETWEEN 1 AND 4))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
LIMIT 100
"""

# q35: like q10 with aggregate triples per demographic
QUERIES[35] = """
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) cnt1, avg(cd_dep_count) a1, max(cd_dep_count) m1,
       sum(cd_dep_count) s1
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_qoy < 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_qoy < 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count
LIMIT 100
"""

# q12: web revenue share within class (window ratio)
QUERIES[12] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) AS itemrevenue,
       sum(ws_ext_sales_price) * 100 /
       sum(sum(ws_ext_sales_price)) OVER (PARTITION BY i_class)
       AS revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND ws_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-02-22' + INTERVAL '30' DAY
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

# q20: catalog revenue share within class (window ratio)
QUERIES[20] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) AS itemrevenue,
       sum(cs_ext_sales_price) * 100 /
       sum(sum(cs_ext_sales_price)) OVER (PARTITION BY i_class)
       AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-02-22' + INTERVAL '30' DAY
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

# q30: web returns per customer vs 1.2x state average (CTE reuse)
QUERIES[30] = """
WITH customer_total_return AS (
    SELECT wr_returning_customer_sk AS ctr_customer_sk,
           ca_state AS ctr_state,
           sum(wr_return_amt) AS ctr_total_return
    FROM web_returns, date_dim, customer_address
    WHERE wr_returned_date_sk = d_date_sk
      AND d_year = 2002
      AND wr_returning_addr_sk = ca_address_sk
    GROUP BY wr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_year, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (
        SELECT avg(ctr_total_return) * 1.2
        FROM customer_total_return ctr2
        WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_year, ctr_total_return
LIMIT 100
"""

# q81: catalog returns per customer vs 1.2x state average
QUERIES[81] = """
WITH customer_total_return AS (
    SELECT cr_returning_customer_sk AS ctr_customer_sk,
           ca_state AS ctr_state,
           sum(cr_return_amount) AS ctr_total_return
    FROM catalog_returns, date_dim, customer_address
    WHERE cr_returned_date_sk = d_date_sk
      AND d_year = 2000
      AND cr_returning_addr_sk = ca_address_sk
    GROUP BY cr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_city, ca_zip, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (
        SELECT avg(ctr_total_return) * 1.2
        FROM customer_total_return ctr2
        WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
         ca_city, ca_zip, ctr_total_return
LIMIT 100
"""

# q91: call center returns by demographic slice
QUERIES[91] = """
SELECT cc_name AS call_center, cc_manager AS manager,
       sum(cr_net_loss) AS returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND d_year = 1998
  AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
       OR (cd_marital_status = 'W'
           AND cd_education_status = 'Advanced Degree'))
  AND hd_buy_potential LIKE 'Unknown%'
GROUP BY cc_name, cc_manager, cd_marital_status, cd_education_status
ORDER BY returns_loss DESC, call_center, manager
"""

# q40: catalog sales +/- returns around a date by warehouse state
QUERIES[40] = """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) AS sales_after
FROM catalog_sales
LEFT OUTER JOIN catalog_returns
  ON (cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk)
, warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
"""

# q50: store returns latency buckets by store
QUERIES[50] = """
SELECT s_store_name, s_market_id,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS d30,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS d60,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                THEN 1 ELSE 0 END) AS dmore
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
  AND ss_ticket_number = sr_ticket_number
  AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_market_id
ORDER BY s_store_name, s_market_id
LIMIT 100
"""

# q44: best/worst performing items by avg net profit (rank windows)
QUERIES[44] = """
SELECT asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
FROM (SELECT * FROM (
        SELECT item_sk, rank() OVER (ORDER BY rank_col ASC) rnk
        FROM (SELECT ss_item_sk item_sk, avg(ss_net_profit) rank_col
              FROM store_sales ss1
              WHERE ss_store_sk = 4
              GROUP BY ss_item_sk
              HAVING avg(ss_net_profit) > 0.9 * (
                  SELECT avg(ss_net_profit) rank_col
                  FROM store_sales
                  WHERE ss_store_sk = 4
                    AND ss_promo_sk IS NULL)) v1) v11
      WHERE rnk < 11) asceding,
     (SELECT * FROM (
        SELECT item_sk, rank() OVER (ORDER BY rank_col DESC) rnk
        FROM (SELECT ss_item_sk item_sk, avg(ss_net_profit) rank_col
              FROM store_sales ss1
              WHERE ss_store_sk = 4
              GROUP BY ss_item_sk
              HAVING avg(ss_net_profit) > 0.9 * (
                  SELECT avg(ss_net_profit) rank_col
                  FROM store_sales
                  WHERE ss_store_sk = 4
                    AND ss_promo_sk IS NULL)) v2) v21
      WHERE rnk < 11) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
"""

# q2: week-over-year web+catalog sales ratios (53-week offset self-join)
QUERIES[2] = """
WITH wscs AS (
    SELECT sold_date_sk, sales_price
    FROM (SELECT ws_sold_date_sk sold_date_sk,
                 ws_ext_sales_price sales_price
          FROM web_sales
          UNION ALL
          SELECT cs_sold_date_sk sold_date_sk,
                 cs_ext_sales_price sales_price
          FROM catalog_sales) x),
wswscs AS (
    SELECT d_week_seq,
           sum(CASE WHEN d_day_name = 'Sunday'
                    THEN sales_price ELSE NULL END) sun_sales,
           sum(CASE WHEN d_day_name = 'Monday'
                    THEN sales_price ELSE NULL END) mon_sales,
           sum(CASE WHEN d_day_name = 'Friday'
                    THEN sales_price ELSE NULL END) fri_sales,
           sum(CASE WHEN d_day_name = 'Saturday'
                    THEN sales_price ELSE NULL END) sat_sales
    FROM wscs, date_dim
    WHERE d_date_sk = sold_date_sk
    GROUP BY d_week_seq)
SELECT d_week_seq1, round(sun_sales1 / sun_sales2, 2) r1,
       round(mon_sales1 / mon_sales2, 2) r2,
       round(fri_sales1 / fri_sales2, 2) r3,
       round(sat_sales1 / sat_sales2, 2) r4
FROM (SELECT wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             mon_sales mon_sales1, fri_sales fri_sales1,
             sat_sales sat_sales1
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq
        AND d_year = 2001) y,
     (SELECT wswscs.d_week_seq d_week_seq2, sun_sales sun_sales2,
             mon_sales mon_sales2, fri_sales fri_sales2,
             sat_sales sat_sales2
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq
        AND d_year = 2002) z
WHERE d_week_seq1 = d_week_seq2 - 53
ORDER BY d_week_seq1
"""

# q74: year-over-year customer growth, store vs web (adapted: growth
# ratio comparison on sums)
QUERIES[74] = """
WITH year_total AS (
    SELECT c_customer_id customer_id, c_first_name customer_first_name,
           c_last_name customer_last_name, d_year AS year1,
           sum(ss_net_paid) year_total, 's' sale_type
    FROM customer, store_sales, date_dim
    WHERE c_customer_sk = ss_customer_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year IN (2001, 2002)
    GROUP BY c_customer_id, c_first_name, c_last_name, d_year
    UNION ALL
    SELECT c_customer_id customer_id, c_first_name customer_first_name,
           c_last_name customer_last_name, d_year AS year1,
           sum(ws_net_paid) year_total, 'w' sale_type
    FROM customer, web_sales, date_dim
    WHERE c_customer_sk = ws_bill_customer_sk
      AND ws_sold_date_sk = d_date_sk
      AND d_year IN (2001, 2002)
    GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.year1 = 2001
  AND t_s_secyear.year1 = 2002
  AND t_w_firstyear.year1 = 2001
  AND t_w_secyear.year1 = 2002
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE NULL END >
      CASE WHEN t_s_firstyear.year_total > 0
           THEN t_s_secyear.year_total / t_s_firstyear.year_total
           ELSE NULL END
ORDER BY 1, 2, 3
LIMIT 100
"""

# ---------------------------------------------------------------------------
# round-3 additions: the remaining spec queries, adapted (noted per query)
# to the generated schema subset. Oracle-verified like the rest.
# ---------------------------------------------------------------------------

# q1: customers returning more than 1.2x their store's average
QUERIES[1] = """
WITH customer_total_return AS (
  SELECT sr_customer_sk ctr_customer_sk, sr_store_sk ctr_store_sk,
         sum(sr_return_amt) ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return >
      (SELECT avg(ctr_total_return) * 1.2 FROM customer_total_return ctr2
       WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
"""

# q4: year-over-year growth, store vs catalog vs web (3-channel year_total)
QUERIES[4] = """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(((ss_ext_list_price - ss_ext_wholesale_cost
               - ss_ext_discount_amt) + ss_ext_sales_price) / 2) year_total,
         's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(((cs_ext_list_price - cs_ext_wholesale_cost
               - cs_ext_discount_amt) + cs_ext_sales_price) / 2),
         'c' sale_type
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk AND cs_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(((ws_ext_list_price - ws_ext_wholesale_cost
               - ws_ext_discount_amt) + ws_ext_sales_price) / 2),
         'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w' AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2002
  AND t_c_firstyear.dyear = 2001 AND t_c_secyear.dyear = 2002
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2002
  AND t_s_firstyear.year_total > 0 AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END >
      CASE WHEN t_s_firstyear.year_total > 0
           THEN t_s_secyear.year_total / t_s_firstyear.year_total
           ELSE NULL END
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END >
      CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE NULL END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name
LIMIT 100
"""

# q5: sales + returns per channel with ROLLUP(channel, id)
QUERIES[5] = """
WITH ssr AS (
  SELECT s_store_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_amt, sum(net_loss) profit_loss
  FROM (SELECT ss_store_sk store_sk, ss_sold_date_sk date_sk,
               ss_ext_sales_price sales_price, ss_net_profit profit,
               cast(0 AS decimal(7,2)) return_amt,
               cast(0 AS decimal(7,2)) net_loss
        FROM store_sales
        UNION ALL
        SELECT sr_store_sk store_sk, sr_returned_date_sk date_sk,
               cast(0 AS decimal(7,2)) sales_price,
               cast(0 AS decimal(7,2)) profit,
               sr_return_amt return_amt, sr_net_loss net_loss
        FROM store_returns) salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '14' DAY
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
 csr AS (
  SELECT cp_catalog_page_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_amt, sum(net_loss) profit_loss
  FROM (SELECT cs_catalog_page_sk page_sk, cs_sold_date_sk date_sk,
               cs_ext_sales_price sales_price, cs_net_profit profit,
               cast(0 AS decimal(7,2)) return_amt,
               cast(0 AS decimal(7,2)) net_loss
        FROM catalog_sales
        UNION ALL
        SELECT cr_catalog_page_sk page_sk, cr_returned_date_sk date_sk,
               cast(0 AS decimal(7,2)) sales_price,
               cast(0 AS decimal(7,2)) profit,
               cr_return_amount return_amt, cr_net_loss net_loss
        FROM catalog_returns) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '14' DAY
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id),
 wsr AS (
  SELECT web_name,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_amt, sum(net_loss) profit_loss
  FROM (SELECT ws_web_site_sk wsr_web_site_sk, ws_sold_date_sk date_sk,
               ws_ext_sales_price sales_price, ws_net_profit profit,
               cast(0 AS decimal(7,2)) return_amt,
               cast(0 AS decimal(7,2)) net_loss
        FROM web_sales
        UNION ALL
        SELECT ws.ws_web_site_sk wsr_web_site_sk,
               wr.wr_returned_date_sk date_sk,
               cast(0 AS decimal(7,2)) sales_price,
               cast(0 AS decimal(7,2)) profit,
               wr.wr_return_amt return_amt, wr.wr_net_loss net_loss
        FROM web_returns wr
        LEFT JOIN web_sales ws
          ON wr.wr_item_sk = ws.ws_item_sk
         AND wr.wr_order_number = ws.ws_order_number) salesreturns,
       date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '14' DAY
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_name)
SELECT channel, id, sum(sales) sales, sum(returns_amt) returns_amt,
       sum(profit - profit_loss) profit
FROM (SELECT 'store channel' channel, s_store_id id, sales,
             returns_amt, profit, profit_loss
      FROM ssr
      UNION ALL
      SELECT 'catalog channel' channel, cp_catalog_page_id id, sales,
             returns_amt, profit, profit_loss
      FROM csr
      UNION ALL
      SELECT 'web channel' channel, web_name id, sales, returns_amt,
             profit, profit_loss
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
"""

# q6: states whose customers buy items priced over 1.2x category average
QUERIES[6] = """
SELECT a.ca_state state, count(*) cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq =
      (SELECT DISTINCT d_month_seq FROM date_dim
       WHERE d_year = 2001 AND d_moy = 1)
  AND i.i_current_price > 1.2 *
      (SELECT avg(j.i_current_price) FROM item j
       WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING count(*) >= 10
ORDER BY cnt, a.ca_state
LIMIT 100
"""

# q8: store sales uplift in zips with concentrated preferred customers
# (adapted: 2-digit zip prefixes instead of the spec's 400-entry 5-digit
# list — the generated zip pool is synthetic)
QUERIES[8] = """
SELECT s_store_name, sum(ss_net_profit)
FROM store_sales, date_dim, store,
     (SELECT ca_zip
      FROM (SELECT substr(ca_zip, 1, 2) ca_zip, count(*) cnt
            FROM customer_address, customer
            WHERE ca_address_sk = c_current_addr_sk
              AND c_preferred_cust_flag = 'Y'
            GROUP BY substr(ca_zip, 1, 2)
            HAVING count(*) > 10) a1) v1
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 1998
  AND substr(s_zip, 1, 2) = v1.ca_zip
GROUP BY s_store_name
ORDER BY s_store_name
LIMIT 100
"""

# q14 (first variant): cross-channel items, ROLLUP over channel/brand/class
# (adapted: the spec's second AVG-gated half is represented by the
# avg_sales HAVING gate; d_moy window per spec)
QUERIES[14] = """
WITH cross_items AS (
  SELECT i_item_sk ss_item_sk
  FROM item,
       (SELECT iss.i_brand_id brand_id, iss.i_class_id class_id,
               iss.i_category_id category_id
        FROM store_sales, item iss, date_dim d1
        WHERE ss_item_sk = iss.i_item_sk
          AND ss_sold_date_sk = d1.d_date_sk
          AND d1.d_year BETWEEN 1999 AND 2001
        INTERSECT
        SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
        FROM catalog_sales, item ics, date_dim d2
        WHERE cs_item_sk = ics.i_item_sk
          AND cs_sold_date_sk = d2.d_date_sk
          AND d2.d_year BETWEEN 1999 AND 2001
        INTERSECT
        SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
        FROM web_sales, item iws, date_dim d3
        WHERE ws_item_sk = iws.i_item_sk
          AND ws_sold_date_sk = d3.d_date_sk
          AND d3.d_year BETWEEN 1999 AND 2001) x
  WHERE i_brand_id = brand_id AND i_class_id = class_id
    AND i_category_id = category_id),
 avg_sales AS (
  SELECT avg(quantity * list_price) average_sales
  FROM (SELECT ss_quantity quantity, ss_list_price list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT cs_quantity, cs_list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT ws_quantity, ws_list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001) x)
SELECT channel, i_brand_id, i_class_id, i_category_id,
       sum(sales) sum_sales, sum(number_sales) number_sales
FROM (SELECT 'store' channel, i_brand_id, i_class_id, i_category_id,
             sum(ss_quantity * ss_list_price) sales, count(*) number_sales
      FROM store_sales, item, date_dim
      WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND d_year = 2001 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(ss_quantity * ss_list_price) >
             (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'catalog' channel, i_brand_id, i_class_id, i_category_id,
             sum(cs_quantity * cs_list_price) sales, count(*) number_sales
      FROM catalog_sales, item, date_dim
      WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
        AND d_year = 2001 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(cs_quantity * cs_list_price) >
             (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'web' channel, i_brand_id, i_class_id, i_category_id,
             sum(ws_quantity * ws_list_price) sales, count(*) number_sales
      FROM web_sales, item, date_dim
      WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
        AND d_year = 2001 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(ws_quantity * ws_list_price) >
             (SELECT average_sales FROM avg_sales)) y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id
LIMIT 100
"""

# q17: quantity statistics across the sale->return->re-purchase chain
# (adapted: d_quarter_name -> d_year/d_qoy; the generator has no
# quarter-name column)
QUERIES[17] = """
SELECT i_item_id, i_item_desc, s_state,
       count(ss_quantity) store_sales_quantitycount,
       avg(ss_quantity) store_sales_quantityave,
       stddev_samp(ss_quantity) store_sales_quantitystdev,
       count(sr_return_quantity) store_returns_quantitycount,
       avg(sr_return_quantity) store_returns_quantityave,
       stddev_samp(sr_return_quantity) store_returns_quantitystdev,
       count(cs_quantity) catalog_sales_quantitycount,
       avg(cs_quantity) catalog_sales_quantityave,
       stddev_samp(cs_quantity) catalog_sales_quantitystdev
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
WHERE d1.d_year = 2001 AND d1.d_qoy = 1
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_year = 2001 AND d2.d_qoy IN (1, 2, 3)
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year = 2001 AND d3.d_qoy IN (1, 2, 3)
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state
LIMIT 100
"""

# q18: catalog sales demographics with ROLLUP over geography
QUERIES[18] = """
SELECT i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity AS decimal(12,2))) agg1,
       avg(cast(cs_list_price AS decimal(12,2))) agg2,
       avg(cast(cs_coupon_amt AS decimal(12,2))) agg3,
       avg(cast(cs_sales_price AS decimal(12,2))) agg4,
       avg(cast(cs_net_profit AS decimal(12,2))) agg5,
       avg(cast(c_birth_year AS decimal(12,2))) agg6,
       avg(cast(cd1.cd_dep_count AS decimal(12,2))) agg7
FROM catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
  AND d_year = 1998
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id
LIMIT 100
"""

# q22: inventory quantity-on-hand averages, 4-level ROLLUP
QUERIES[22] = """
SELECT i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 1200 AND 1211
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category
LIMIT 100
"""

# q23 (first variant): frequent cross-channel shoppers' catalog+web sales
# (adapted: substr(i_item_desc,1,30) grouping kept; best customers are
# those above 50% of max store spend — tiny scale makes 95% empty)
QUERIES[23] = """
WITH frequent_ss_items AS (
  SELECT substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,
         d_date solddate, count(*) cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
    AND d_year IN (2000, 2001, 2002, 2003)
  GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING count(*) > 4),
 max_store_sales AS (
  SELECT max(csales) tpcds_cmax
  FROM (SELECT c_customer_sk, sum(ss_quantity * ss_sales_price) csales
        FROM store_sales, customer, date_dim
        WHERE ss_customer_sk = c_customer_sk
          AND ss_sold_date_sk = d_date_sk
          AND d_year IN (2000, 2001, 2002, 2003)
        GROUP BY c_customer_sk) x),
 best_ss_customer AS (
  SELECT c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING sum(ss_quantity * ss_sales_price) >
         0.5 * (SELECT tpcds_cmax FROM max_store_sales))
SELECT sum(sales)
FROM (SELECT cs_quantity * cs_list_price sales
      FROM catalog_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 2 AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND cs_bill_customer_sk IN
            (SELECT c_customer_sk FROM best_ss_customer)
      UNION ALL
      SELECT ws_quantity * ws_list_price sales
      FROM web_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 2 AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND ws_bill_customer_sk IN
            (SELECT c_customer_sk FROM best_ss_customer)) y
"""

# q24 (first variant): store-channel sales by customer/color where the
# customer's birth country differs from their address country
QUERIES[24] = """
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         sum(ss_net_paid) netpaid
  FROM store_sales, store_returns, store, item, customer,
       customer_address
  WHERE ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk
    AND c_current_addr_sk = ca_address_sk
    AND c_birth_country <> upper(ca_country)
    AND s_zip = ca_zip
    AND s_market_id = 8
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size)
SELECT c_last_name, c_first_name, s_store_name, sum(netpaid) paid
FROM ssales
WHERE i_color = 'pale'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING sum(netpaid) > (SELECT 0.05 * avg(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
"""

# q36: gross-margin ranking with grouping()-keyed partitions
QUERIES[36] = """
SELECT sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() OVER (
         PARTITION BY grouping(i_category) + grouping(i_class),
                      CASE WHEN grouping(i_class) = 0
                           THEN i_category END
         ORDER BY sum(ss_net_profit) / sum(ss_ext_sales_price) ASC)
         rank_within_parent
FROM store_sales, date_dim d1, item, store
WHERE d1.d_year = 2001
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND s_state = 'TN'
GROUP BY ROLLUP (i_category, i_class)
ORDER BY lochierarchy DESC,
         CASE WHEN grouping(i_category) + grouping(i_class) = 0
              THEN i_category END,
         rank_within_parent
LIMIT 100
"""

# q39 (first variant): inventory coefficient-of-variation pairs across
# consecutive months
QUERIES[39] = """
WITH inv AS (
  SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         CASE mean WHEN 0 THEN NULL ELSE stdev / mean END cov
  FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        FROM inventory, item, warehouse, date_dim
        WHERE inv_item_sk = i_item_sk
          AND inv_warehouse_sk = w_warehouse_sk
          AND inv_date_sk = d_date_sk
          AND d_year = 2001
        GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  WHERE CASE mean WHEN 0 THEN 0 ELSE stdev / mean END > 1)
SELECT inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
       inv1.cov, inv2.w_warehouse_sk w2, inv2.i_item_sk i2,
       inv2.d_moy moy2, inv2.mean mean2, inv2.cov cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = 1 AND inv2.d_moy = 2
ORDER BY inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
         inv1.cov, inv2.d_moy, inv2.mean, inv2.cov
"""

# q41: distinct product names of items whose manufacturer also makes
# items in specific color/unit/size combinations
QUERIES[41] = """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 738 AND 778
  AND (SELECT count(*) FROM item
       WHERE i_manufact = i1.i_manufact
         AND ((i_category = 'Women'
               AND i_color IN ('powder', 'khaki')
               AND i_units IN ('Ounce', 'Oz')
               AND i_size IN ('medium', 'extra large'))
           OR (i_category = 'Women'
               AND i_color IN ('brown', 'honeydew')
               AND i_units IN ('Bunch', 'Ton')
               AND i_size IN ('N/A', 'small'))
           OR (i_category = 'Men'
               AND i_color IN ('floral', 'deep')
               AND i_units IN ('N/A', 'Dozen')
               AND i_size IN ('petite', 'petite'))
           OR (i_category = 'Men'
               AND i_color IN ('light', 'cornflower')
               AND i_units IN ('Box', 'Pound')
               AND i_size IN ('medium', 'extra large')))) > 0
ORDER BY i_product_name
LIMIT 100
"""

# q47: monthly brand sales vs yearly average, with the neighbouring
# months joined through rank self-joins
QUERIES[47] = """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, d_year, d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER (
           PARTITION BY i_category, i_brand, s_store_name,
                        d_year) avg_monthly_sales,
         rank() OVER (
           PARTITION BY i_category, i_brand, s_store_name
           ORDER BY d_year, d_moy) rn
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 2000
         OR (d_year = 1999 AND d_moy = 12)
         OR (d_year = 2001 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, d_year,
           d_moy),
 v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.s_store_name,
         v1.d_year, v1.d_moy, v1.avg_monthly_sales, v1.sum_sales,
         v1_lag.sum_sales psum, v1_lead.sum_sales nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.s_store_name = v1_lag.s_store_name
    AND v1.s_store_name = v1_lead.s_store_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1)
SELECT v2.i_category, v2.i_brand, v2.d_year, v2.d_moy, v2.avg_monthly_sales,
       v2.sum_sales, v2.psum, v2.nsum
FROM v2
WHERE v2.d_year = 2000
  AND v2.avg_monthly_sales > 0
  AND CASE WHEN v2.avg_monthly_sales > 0
           THEN abs(v2.sum_sales - v2.avg_monthly_sales)
                / v2.avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY v2.sum_sales - v2.avg_monthly_sales, v2.i_category, v2.i_brand,
         v2.d_year, v2.d_moy
LIMIT 100
"""

# q49: worst return ratios per channel, rank()-windowed, unioned
QUERIES[49] = """
SELECT channel, item, return_ratio, return_rank, currency_rank
FROM (SELECT 'web' channel, web.item, web.return_ratio,
             web.return_rank, web.currency_rank
      FROM (SELECT item, return_ratio, currency_ratio,
                   rank() OVER (ORDER BY return_ratio) return_rank,
                   rank() OVER (ORDER BY currency_ratio) currency_rank
            FROM (SELECT ws.ws_item_sk item,
                         cast(sum(coalesce(wr.wr_return_quantity, 0))
                              AS double) /
                         cast(sum(coalesce(ws.ws_quantity, 0))
                              AS double) return_ratio,
                         cast(sum(coalesce(wr.wr_return_amt, 0))
                              AS double) /
                         cast(sum(coalesce(ws.ws_net_paid, 0))
                              AS double) currency_ratio
                  FROM web_sales ws
                  LEFT JOIN web_returns wr
                    ON ws.ws_order_number = wr.wr_order_number
                   AND ws.ws_item_sk = wr.wr_item_sk,
                       date_dim
                  WHERE wr.wr_return_amt > 100
                    AND ws.ws_net_profit > 1
                    AND ws.ws_net_paid > 0
                    AND ws.ws_quantity > 0
                    AND ws_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy = 12
                  GROUP BY ws.ws_item_sk) in_web) web
      WHERE web.return_rank <= 10 OR web.currency_rank <= 10
      UNION
      SELECT 'catalog' channel, catalog.item, catalog.return_ratio,
             catalog.return_rank, catalog.currency_rank
      FROM (SELECT item, return_ratio, currency_ratio,
                   rank() OVER (ORDER BY return_ratio) return_rank,
                   rank() OVER (ORDER BY currency_ratio) currency_rank
            FROM (SELECT cs.cs_item_sk item,
                         cast(sum(coalesce(cr.cr_return_quantity, 0))
                              AS double) /
                         cast(sum(coalesce(cs.cs_quantity, 0))
                              AS double) return_ratio,
                         cast(sum(coalesce(cr.cr_return_amount, 0))
                              AS double) /
                         cast(sum(coalesce(cs.cs_net_paid, 0))
                              AS double) currency_ratio
                  FROM catalog_sales cs
                  LEFT JOIN catalog_returns cr
                    ON cs.cs_order_number = cr.cr_order_number
                   AND cs.cs_item_sk = cr.cr_item_sk,
                       date_dim
                  WHERE cr.cr_return_amount > 100
                    AND cs.cs_net_profit > 1
                    AND cs.cs_net_paid > 0
                    AND cs.cs_quantity > 0
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy = 12
                  GROUP BY cs.cs_item_sk) in_cat) catalog
      WHERE catalog.return_rank <= 10 OR catalog.currency_rank <= 10
      UNION
      SELECT 'store' channel, store.item, store.return_ratio,
             store.return_rank, store.currency_rank
      FROM (SELECT item, return_ratio, currency_ratio,
                   rank() OVER (ORDER BY return_ratio) return_rank,
                   rank() OVER (ORDER BY currency_ratio) currency_rank
            FROM (SELECT sts.ss_item_sk item,
                         cast(sum(coalesce(sr.sr_return_quantity, 0))
                              AS double) /
                         cast(sum(coalesce(sts.ss_quantity, 0))
                              AS double) return_ratio,
                         cast(sum(coalesce(sr.sr_return_amt, 0))
                              AS double) /
                         cast(sum(coalesce(sts.ss_net_paid, 0))
                              AS double) currency_ratio
                  FROM store_sales sts
                  LEFT JOIN store_returns sr
                    ON sts.ss_ticket_number = sr.sr_ticket_number
                   AND sts.ss_item_sk = sr.sr_item_sk,
                       date_dim
                  WHERE sr.sr_return_amt > 100
                    AND sts.ss_net_profit > 1
                    AND sts.ss_net_paid > 0
                    AND sts.ss_quantity > 0
                    AND ss_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy = 12
                  GROUP BY sts.ss_item_sk) in_store) store
      WHERE store.return_rank <= 10 OR store.currency_rank <= 10) x
ORDER BY 1, 4, 5, 2
LIMIT 100
"""

# q51: cumulative web vs store sales crossover (FULL OUTER JOIN of two
# running-window aggregates)
QUERIES[51] = """
WITH web_v1 AS (
  SELECT ws_item_sk item_sk, d_date,
         sum(sum(ws_sales_price)) OVER (
           PARTITION BY ws_item_sk ORDER BY d_date
           ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume_sales
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
    AND ws_item_sk IS NOT NULL
  GROUP BY ws_item_sk, d_date),
 store_v1 AS (
  SELECT ss_item_sk item_sk, d_date,
         sum(sum(ss_sales_price)) OVER (
           PARTITION BY ss_item_sk ORDER BY d_date
           ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume_sales
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
    AND ss_item_sk IS NOT NULL
  GROUP BY ss_item_sk, d_date)
SELECT *
FROM (SELECT item_sk, d_date, web_sales, store_sales,
             max(web_sales) OVER (
               PARTITION BY item_sk ORDER BY d_date
               ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
               web_cumulative,
             max(store_sales) OVER (
               PARTITION BY item_sk ORDER BY d_date
               ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
               store_cumulative
      FROM (SELECT CASE WHEN web.item_sk IS NOT NULL
                        THEN web.item_sk ELSE store.item_sk END item_sk,
                   CASE WHEN web.d_date IS NOT NULL
                        THEN web.d_date ELSE store.d_date END d_date,
                   web.cume_sales web_sales,
                   store.cume_sales store_sales
            FROM web_v1 web
            FULL OUTER JOIN store_v1 store
              ON web.item_sk = store.item_sk
             AND web.d_date = store.d_date) x) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date
LIMIT 100
"""

# q54: revenue segments of cross-channel customers buying from stores in
# the following quarter
QUERIES[54] = """
WITH my_customers AS (
  SELECT DISTINCT c_customer_sk, c_current_addr_sk
  FROM (SELECT cs_sold_date_sk sold_date_sk,
               cs_bill_customer_sk customer_sk, cs_item_sk item_sk
        FROM catalog_sales
        UNION ALL
        SELECT ws_sold_date_sk sold_date_sk,
               ws_bill_customer_sk customer_sk, ws_item_sk item_sk
        FROM web_sales) cs_or_ws_sales, item, date_dim, customer
  WHERE sold_date_sk = d_date_sk
    AND item_sk = i_item_sk
    AND i_category = 'Women'
    AND i_class = 'maternity'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_moy = 12 AND d_year = 1998),
 my_revenue AS (
  SELECT c_customer_sk, sum(ss_ext_sales_price) revenue
  FROM my_customers, store_sales, customer_address, store, date_dim
  WHERE c_current_addr_sk = ca_address_sk
    AND ca_county = s_county AND ca_state = s_state
    AND ss_customer_sk = c_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_month_seq BETWEEN
        (SELECT DISTINCT d_month_seq + 1 FROM date_dim
         WHERE d_year = 1998 AND d_moy = 12)
        AND
        (SELECT DISTINCT d_month_seq + 3 FROM date_dim
         WHERE d_year = 1998 AND d_moy = 12)
  GROUP BY c_customer_sk),
 segments AS (
  SELECT cast((revenue / 50) AS bigint) segment FROM my_revenue)
SELECT segment, count(*) num_customers, segment * 50 segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers
LIMIT 100
"""

# q57: like q47 for the catalog channel (call centers)
QUERIES[57] = """
WITH v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy,
         sum(cs_sales_price) sum_sales,
         avg(sum(cs_sales_price)) OVER (
           PARTITION BY i_category, i_brand, cc_name, d_year)
           avg_monthly_sales,
         rank() OVER (
           PARTITION BY i_category, i_brand, cc_name
           ORDER BY d_year, d_moy) rn
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND cc_call_center_sk = cs_call_center_sk
    AND (d_year = 2000
         OR (d_year = 1999 AND d_moy = 12)
         OR (d_year = 2001 AND d_moy = 1))
  GROUP BY i_category, i_brand, cc_name, d_year, d_moy),
 v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
         v1.avg_monthly_sales, v1.sum_sales, v1_lag.sum_sales psum,
         v1_lead.sum_sales nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.cc_name = v1_lag.cc_name
    AND v1.cc_name = v1_lead.cc_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1)
SELECT v2.i_category, v2.i_brand, v2.d_year, v2.d_moy,
       v2.avg_monthly_sales, v2.sum_sales, v2.psum, v2.nsum
FROM v2
WHERE v2.d_year = 2000
  AND v2.avg_monthly_sales > 0
  AND CASE WHEN v2.avg_monthly_sales > 0
           THEN abs(v2.sum_sales - v2.avg_monthly_sales)
                / v2.avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY v2.sum_sales - v2.avg_monthly_sales, v2.i_category, v2.i_brand,
         v2.d_year, v2.d_moy
LIMIT 100
"""

# q58: items selling comparably across all 3 channels in one week
QUERIES[58] = """
WITH ss_items AS (
  SELECT i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = DATE '2000-01-03'))
    AND ss_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
 cs_items AS (
  SELECT i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = DATE '2000-01-03'))
    AND cs_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
 ws_items AS (
  SELECT i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = DATE '2000-01-03'))
    AND ws_sold_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT ss_items.item_id, ss_item_rev,
       ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
         * 100 ss_dev,
       cs_item_rev,
       cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
         * 100 cs_dev,
       ws_item_rev,
       ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
         * 100 ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
  AND ss_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND cs_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND cs_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND ws_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND ws_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
ORDER BY ss_items.item_id, ss_item_rev
LIMIT 100
"""

# q59: week-over-week store sales by day of week (year vs year+1)
QUERIES[59] = """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         sum(CASE WHEN d_day_name = 'Sunday'
                  THEN ss_sales_price ELSE NULL END) sun_sales,
         sum(CASE WHEN d_day_name = 'Monday'
                  THEN ss_sales_price ELSE NULL END) mon_sales,
         sum(CASE WHEN d_day_name = 'Tuesday'
                  THEN ss_sales_price ELSE NULL END) tue_sales,
         sum(CASE WHEN d_day_name = 'Wednesday'
                  THEN ss_sales_price ELSE NULL END) wed_sales,
         sum(CASE WHEN d_day_name = 'Thursday'
                  THEN ss_sales_price ELSE NULL END) thu_sales,
         sum(CASE WHEN d_day_name = 'Friday'
                  THEN ss_sales_price ELSE NULL END) fri_sales,
         sum(CASE WHEN d_day_name = 'Saturday'
                  THEN ss_sales_price ELSE NULL END) sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk)
SELECT y.s_store_name1, y.s_store_id1, y.d_week_seq1,
       y.sun_sales1 / x.sun_sales2,
       y.mon_sales1 / x.mon_sales2,
       y.tue_sales1 / x.tue_sales2,
       y.wed_sales1 / x.wed_sales2,
       y.thu_sales1 / x.thu_sales2,
       y.fri_sales1 / x.fri_sales2,
       y.sat_sales1 / x.sat_sales2
FROM (SELECT s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1,
             wed_sales wed_sales1, thu_sales thu_sales1,
             fri_sales fri_sales1, sat_sales sat_sales1
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1185 AND 1196) y,
     (SELECT s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2,
             wed_sales wed_sales2, thu_sales thu_sales2,
             fri_sales fri_sales2, sat_sales sat_sales2
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1197 AND 1208) x
WHERE y.s_store_id1 = x.s_store_id2
  AND y.d_week_seq1 = x.d_week_seq2 - 52
ORDER BY y.s_store_name1, y.s_store_id1, y.d_week_seq1
LIMIT 100
"""

# q64: items sold twice (store then again) across demographic transitions
# (adapted: the generator has no c_first_sales_date_sk/c_first_shipto_
# date_sk, so the d2/d3 date roles are dropped; income bands join through
# hd as in spec)
QUERIES[64] = """
WITH cs_ui AS (
  SELECT cs_item_sk,
         sum(cs_ext_list_price) sale,
         sum(cr_refunded_cash + cr_net_loss) refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk
    AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) >
         2 * sum(cr_refunded_cash + cr_net_loss)),
 cross_sales AS (
  SELECT i_product_name product_name, i_item_sk item_sk,
         s_store_name store_name, s_zip store_zip,
         ad1.ca_city b_city, ad1.ca_zip b_zip,
         ad2.ca_city c_city, ad2.ca_zip c_zip,
         d1.d_year syear,
         count(*) cnt,
         sum(ss_wholesale_cost) s1, sum(ss_list_price) s2,
         sum(ss_coupon_amt) s3
  FROM store_sales, store_returns, cs_ui, date_dim d1, store, customer,
       customer_demographics cd1, customer_demographics cd2,
       household_demographics hd1, household_demographics hd2,
       customer_address ad1, customer_address ad2, income_band ib1,
       income_band ib2, item
  WHERE ss_store_sk = s_store_sk
    AND ss_sold_date_sk = d1.d_date_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_cdemo_sk = cd1.cd_demo_sk
    AND ss_hdemo_sk = hd1.hd_demo_sk
    AND ss_addr_sk = ad1.ca_address_sk
    AND ss_item_sk = i_item_sk
    AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND ss_item_sk = cs_ui.cs_item_sk
    AND c_current_cdemo_sk = cd2.cd_demo_sk
    AND c_current_hdemo_sk = hd2.hd_demo_sk
    AND c_current_addr_sk = ad2.ca_address_sk
    AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
    AND hd2.hd_income_band_sk = ib2.ib_income_band_sk
    AND cd1.cd_marital_status <> cd2.cd_marital_status
    AND i_color IN ('purple', 'burlywood', 'indian', 'spring',
                    'floral', 'medium')
    AND i_current_price BETWEEN 64 AND 74
  GROUP BY i_product_name, i_item_sk, s_store_name, s_zip, ad1.ca_city,
           ad1.ca_zip, ad2.ca_city, ad2.ca_zip, d1.d_year)
SELECT cs1.product_name, cs1.store_name, cs1.store_zip, cs1.b_city,
       cs1.b_zip, cs1.c_city, cs1.c_zip, cs1.syear, cs1.cnt, cs1.s1,
       cs1.s2, cs1.s3, cs2.s1 s1_2, cs2.s2 s2_2, cs2.s3 s3_2, cs2.syear
         syear_2, cs2.cnt cnt_2
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk
  AND cs1.syear = 1999
  AND cs2.syear = 2000
  AND cs2.cnt <= cs1.cnt
  AND cs1.store_name = cs2.store_name
  AND cs1.store_zip = cs2.store_zip
ORDER BY cs1.product_name, cs1.store_name, cnt_2, cs1.s1, s1_2
"""

# q66: warehouse shipping pivot by month (adapted: catalog_sales has no
# sold-time column in the generator, so the time_dim filter applies to
# the web channel only; the catalog branch filters by ship mode + year)
QUERIES[66] = """
SELECT w_warehouse_name, w_warehouse_sq_ft, w_state, ship_carriers, year1,
       sum(jan_sales) jan_sales, sum(feb_sales) feb_sales,
       sum(mar_sales) mar_sales, sum(apr_sales) apr_sales,
       sum(may_sales) may_sales, sum(jun_sales) jun_sales,
       sum(jul_sales) jul_sales, sum(aug_sales) aug_sales,
       sum(sep_sales) sep_sales, sum(oct_sales) oct_sales,
       sum(nov_sales) nov_sales, sum(dec_sales) dec_sales,
       sum(jan_net) jan_net, sum(feb_net) feb_net, sum(mar_net) mar_net
FROM (SELECT w_warehouse_name, w_warehouse_sq_ft, w_state,
             'DHL,BARIAN' ship_carriers, d_year year1,
             sum(CASE WHEN d_moy = 1
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) jan_sales,
             sum(CASE WHEN d_moy = 2
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) feb_sales,
             sum(CASE WHEN d_moy = 3
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) mar_sales,
             sum(CASE WHEN d_moy = 4
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) apr_sales,
             sum(CASE WHEN d_moy = 5
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) may_sales,
             sum(CASE WHEN d_moy = 6
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) jun_sales,
             sum(CASE WHEN d_moy = 7
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) jul_sales,
             sum(CASE WHEN d_moy = 8
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) aug_sales,
             sum(CASE WHEN d_moy = 9
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) sep_sales,
             sum(CASE WHEN d_moy = 10
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) oct_sales,
             sum(CASE WHEN d_moy = 11
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) nov_sales,
             sum(CASE WHEN d_moy = 12
                      THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) dec_sales,
             sum(CASE WHEN d_moy = 1
                      THEN ws_net_paid * ws_quantity ELSE 0 END) jan_net,
             sum(CASE WHEN d_moy = 2
                      THEN ws_net_paid * ws_quantity ELSE 0 END) feb_net,
             sum(CASE WHEN d_moy = 3
                      THEN ws_net_paid * ws_quantity ELSE 0 END) mar_net
      FROM web_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE ws_warehouse_sk = w_warehouse_sk
        AND ws_sold_date_sk = d_date_sk
        AND ws_sold_time_sk = t_time_sk
        AND ws_ship_mode_sk = sm_ship_mode_sk
        AND d_year = 2001
        AND t_hour BETWEEN 8 AND 17
        AND sm_carrier IN ('DHL', 'BARIAN')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_state, d_year
      UNION ALL
      SELECT w_warehouse_name, w_warehouse_sq_ft, w_state,
             'DHL,BARIAN' ship_carriers, d_year year1,
             sum(CASE WHEN d_moy = 1
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) jan_sales,
             sum(CASE WHEN d_moy = 2
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) feb_sales,
             sum(CASE WHEN d_moy = 3
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) mar_sales,
             sum(CASE WHEN d_moy = 4
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) apr_sales,
             sum(CASE WHEN d_moy = 5
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) may_sales,
             sum(CASE WHEN d_moy = 6
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) jun_sales,
             sum(CASE WHEN d_moy = 7
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) jul_sales,
             sum(CASE WHEN d_moy = 8
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) aug_sales,
             sum(CASE WHEN d_moy = 9
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) sep_sales,
             sum(CASE WHEN d_moy = 10
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) oct_sales,
             sum(CASE WHEN d_moy = 11
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) nov_sales,
             sum(CASE WHEN d_moy = 12
                      THEN cs_sales_price * cs_quantity
                      ELSE 0 END) dec_sales,
             sum(CASE WHEN d_moy = 1
                      THEN cs_net_paid_inc_tax * cs_quantity
                      ELSE 0 END) jan_net,
             sum(CASE WHEN d_moy = 2
                      THEN cs_net_paid_inc_tax * cs_quantity
                      ELSE 0 END) feb_net,
             sum(CASE WHEN d_moy = 3
                      THEN cs_net_paid_inc_tax * cs_quantity
                      ELSE 0 END) mar_net
      FROM catalog_sales, warehouse, date_dim, ship_mode
      WHERE cs_warehouse_sk = w_warehouse_sk
        AND cs_sold_date_sk = d_date_sk
        AND cs_ship_mode_sk = sm_ship_mode_sk
        AND d_year = 2001
        AND sm_carrier IN ('DHL', 'BARIAN')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_state, d_year) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_state, ship_carriers,
         year1
ORDER BY w_warehouse_name
LIMIT 100
"""

# q67: 8-level ROLLUP with per-category rank
QUERIES[67] = """
SELECT *
FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
             d_moy, s_store_id, sumsales,
             rank() OVER (PARTITION BY i_category
                          ORDER BY sumsales DESC) rk
      FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
                   d_qoy, d_moy, s_store_id,
                   sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales
            FROM store_sales, date_dim, store, item
            WHERE ss_sold_date_sk = d_date_sk
              AND ss_item_sk = i_item_sk
              AND ss_store_sk = s_store_sk
              AND d_month_seq BETWEEN 1200 AND 1211
            GROUP BY ROLLUP (i_category, i_class, i_brand,
                             i_product_name, d_year, d_qoy, d_moy,
                             s_store_id)) dw1) dw2
WHERE rk <= 100
ORDER BY i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales, rk
LIMIT 100
"""

# q70: profitable states/counties with grouping()-ranked hierarchy and a
# windowed top-5-state subquery
QUERIES[70] = """
SELECT sum(ss_net_profit) total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) lochierarchy,
       rank() OVER (
         PARTITION BY grouping(s_state) + grouping(s_county),
                      CASE WHEN grouping(s_county) = 0
                           THEN s_state END
         ORDER BY sum(ss_net_profit) DESC) rank_within_parent
FROM store_sales, date_dim d1, store
WHERE d1.d_month_seq BETWEEN 1200 AND 1211
  AND d1.d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_state IN
      (SELECT s_state
       FROM (SELECT s_state s_state,
                    rank() OVER (PARTITION BY s_state
                                 ORDER BY sum(ss_net_profit) DESC)
                      ranking
             FROM store_sales, store, date_dim
             WHERE d_month_seq BETWEEN 1200 AND 1211
               AND d_date_sk = ss_sold_date_sk
               AND s_store_sk = ss_store_sk
             GROUP BY s_state) tmp1
       WHERE ranking <= 5)
GROUP BY ROLLUP (s_state, s_county)
ORDER BY lochierarchy DESC,
         CASE WHEN grouping(s_state) + grouping(s_county) = 0
              THEN s_state END,
         rank_within_parent
LIMIT 100
"""

# q71: brand revenue by hour across channels (adapted: catalog_sales has
# no sold-time column in the generator, so the union covers the web and
# store channels)
QUERIES[71] = """
SELECT i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
FROM item,
     (SELECT ws_ext_sales_price ext_price, ws_sold_date_sk sold_date_sk,
             ws_item_sk sold_item_sk, ws_sold_time_sk time_sk
      FROM web_sales, date_dim
      WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 1999
      UNION ALL
      SELECT ss_ext_sales_price ext_price, ss_sold_date_sk sold_date_sk,
             ss_item_sk sold_item_sk, ss_sold_time_sk time_sk
      FROM store_sales, date_dim
      WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11 AND d_year = 1999)
     tmp, time_dim
WHERE sold_item_sk = i_item_sk
  AND i_manager_id = 1
  AND time_sk = t_time_sk
  AND (t_hour IN (8, 9, 19, 20))
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id, t_hour, t_minute
"""

# q72: the deep join tree — catalog sales vs inventory with promotions and
# returns (BASELINE config 5's query shape)
QUERIES[72] = """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) no_promo,
       sum(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) promo,
       count(*) total_cnt
FROM catalog_sales
JOIN inventory ON (cs_item_sk = inv_item_sk)
JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
JOIN item ON (i_item_sk = cs_item_sk)
JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
LEFT JOIN promotion ON (cs_promo_sk = p_promo_sk)
LEFT JOIN catalog_returns ON (cr_item_sk = cs_item_sk
                              AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + INTERVAL '5' DAY
  AND hd_buy_potential = '>10000'
  AND d1.d_year = 1999
  AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100
"""

# q75: year-over-year sales quantity decline by brand/class/category
QUERIES[75] = """
WITH all_sales AS (
  SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
         sum(sales_cnt) sales_cnt, sum(sales_amt) sales_amt
  FROM (SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0) sales_cnt,
               cs_ext_sales_price
                 - coalesce(cr_return_amount, 0.0) sales_amt
        FROM catalog_sales
        JOIN item ON i_item_sk = cs_item_sk
        JOIN date_dim ON d_date_sk = cs_sold_date_sk
        LEFT JOIN catalog_returns
          ON cs_order_number = cr_order_number
         AND cs_item_sk = cr_item_sk
        WHERE i_category = 'Books'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0) sales_cnt,
               ss_ext_sales_price
                 - coalesce(sr_return_amt, 0.0) sales_amt
        FROM store_sales
        JOIN item ON i_item_sk = ss_item_sk
        JOIN date_dim ON d_date_sk = ss_sold_date_sk
        LEFT JOIN store_returns
          ON ss_ticket_number = sr_ticket_number
         AND ss_item_sk = sr_item_sk
        WHERE i_category = 'Books'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0) sales_cnt,
               ws_ext_sales_price
                 - coalesce(wr_return_amt, 0.0) sales_amt
        FROM web_sales
        JOIN item ON i_item_sk = ws_item_sk
        JOIN date_dim ON d_date_sk = ws_sold_date_sk
        LEFT JOIN web_returns
          ON ws_order_number = wr_order_number
         AND ws_item_sk = wr_item_sk
        WHERE i_category = 'Books') sales_detail
  GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
SELECT prev_yr.d_year prev_year, curr_yr.d_year year1,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id, prev_yr.sales_cnt prev_yr_cnt,
       curr_yr.sales_cnt curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2002
  AND prev_yr.d_year = 2001
  AND cast(curr_yr.sales_cnt AS decimal(17,2))
      / cast(prev_yr.sales_cnt AS decimal(17,2)) < 0.9
ORDER BY sales_cnt_diff, sales_amt_diff
LIMIT 100
"""

# q77: per-channel sales/returns/profit with ROLLUP(channel, id)
QUERIES[77] = """
WITH ss AS (
  SELECT s_store_sk, sum(ss_ext_sales_price) sales,
         sum(ss_net_profit) profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
 sr AS (
  SELECT s_store_sk, sum(sr_return_amt) returns_amt,
         sum(sr_net_loss) profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
 cs AS (
  SELECT cs_call_center_sk, sum(cs_ext_sales_price) sales,
         sum(cs_net_profit) profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
  GROUP BY cs_call_center_sk),
 cr AS (
  SELECT cr_call_center_sk, sum(cr_return_amount) returns_amt,
         sum(cr_net_loss) profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
  GROUP BY cr_call_center_sk),
 ws AS (
  SELECT wp_web_page_sk, sum(ws_ext_sales_price) sales,
         sum(ws_net_profit) profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
 wr AS (
  SELECT wr_web_page_sk, sum(wr_return_amt) returns_amt,
         sum(wr_net_loss) profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wr_web_page_sk)
SELECT channel, id, sum(sales) sales, sum(returns_amt) returns_amt,
       sum(profit) profit
FROM (SELECT 'store channel' channel, ss.s_store_sk id, sales,
             coalesce(returns_amt, 0) returns_amt,
             profit - coalesce(profit_loss, 0) profit
      FROM ss
      LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
      UNION ALL
      SELECT 'catalog channel' channel, cs_call_center_sk id, sales,
             coalesce(returns_amt, 0) returns_amt,
             profit - coalesce(profit_loss, 0) profit
      FROM cs
      LEFT JOIN cr ON cs.cs_call_center_sk = cr.cr_call_center_sk
      UNION ALL
      SELECT 'web channel' channel, ws.wp_web_page_sk id, sales,
             coalesce(returns_amt, 0) returns_amt,
             profit - coalesce(profit_loss, 0) profit
      FROM ws
      LEFT JOIN wr ON ws.wp_web_page_sk = wr.wr_web_page_sk) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
"""

# q78: customers buying through one channel only (returnless sales ratios)
QUERIES[78] = """
WITH ws AS (
  SELECT d_year ws_sold_year, ws_item_sk,
         ws_bill_customer_sk ws_customer_sk,
         sum(ws_quantity) ws_qty, sum(ws_wholesale_cost) ws_wc,
         sum(ws_sales_price) ws_sp
  FROM web_sales
  LEFT JOIN web_returns ON wr_order_number = ws_order_number
                       AND ws_item_sk = wr_item_sk
  JOIN date_dim ON ws_sold_date_sk = d_date_sk
  WHERE wr_order_number IS NULL
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk),
 cs AS (
  SELECT d_year cs_sold_year, cs_item_sk,
         cs_bill_customer_sk cs_customer_sk,
         sum(cs_quantity) cs_qty, sum(cs_wholesale_cost) cs_wc,
         sum(cs_sales_price) cs_sp
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
                           AND cs_item_sk = cr_item_sk
  JOIN date_dim ON cs_sold_date_sk = d_date_sk
  WHERE cr_order_number IS NULL
  GROUP BY d_year, cs_item_sk, cs_bill_customer_sk),
 ss AS (
  SELECT d_year ss_sold_year, ss_item_sk,
         ss_customer_sk,
         sum(ss_quantity) ss_qty, sum(ss_wholesale_cost) ss_wc,
         sum(ss_sales_price) ss_sp
  FROM store_sales
  LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number
                         AND ss_item_sk = sr_item_sk
  JOIN date_dim ON ss_sold_date_sk = d_date_sk
  WHERE sr_ticket_number IS NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk)
SELECT ss_sold_year, ss_item_sk, ss_customer_sk,
       round(cast(ss_qty AS double) /
             (coalesce(ws_qty, 0) + coalesce(cs_qty, 0) + 1), 2) ratio,
       ss_qty store_qty, ss_wc store_wholesale_cost,
       ss_sp store_sales_price,
       coalesce(ws_qty, 0) + coalesce(cs_qty, 0) other_chan_qty,
       coalesce(ws_wc, 0) + coalesce(cs_wc, 0)
         other_chan_wholesale_cost,
       coalesce(ws_sp, 0) + coalesce(cs_sp, 0) other_chan_sales_price
FROM ss
LEFT JOIN ws ON ws_sold_year = ss_sold_year
            AND ws_item_sk = ss_item_sk
            AND ws_customer_sk = ss_customer_sk
LEFT JOIN cs ON cs_sold_year = ss_sold_year
            AND cs_item_sk = ss_item_sk
            AND cs_customer_sk = ss_customer_sk
WHERE (coalesce(ws_qty, 0) > 0 OR coalesce(cs_qty, 0) > 0)
  AND ss_sold_year = 2000
ORDER BY ss_sold_year, ss_item_sk, ss_customer_sk, ss_qty DESC,
         ss_wc DESC, ss_sp DESC, other_chan_qty,
         other_chan_wholesale_cost, other_chan_sales_price, ratio
LIMIT 100
"""

# q80: 30-day sales minus returns per channel, ROLLUP(channel, id)
QUERIES[80] = """
WITH ssr AS (
  SELECT s_store_id,
         sum(ss_ext_sales_price) sales,
         sum(coalesce(sr_return_amt, 0)) returns_amt,
         sum(ss_net_profit - coalesce(sr_net_loss, 0)) profit
  FROM store_sales
  LEFT JOIN store_returns ON ss_item_sk = sr_item_sk
                         AND ss_ticket_number = sr_ticket_number,
       date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ss_store_sk = s_store_sk
    AND ss_item_sk = i_item_sk
    AND i_current_price > 50
    AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id),
 csr AS (
  SELECT cp_catalog_page_id,
         sum(cs_ext_sales_price) sales,
         sum(coalesce(cr_return_amount, 0)) returns_amt,
         sum(cs_net_profit - coalesce(cr_net_loss, 0)) profit
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cs_item_sk = cr_item_sk
                           AND cs_order_number = cr_order_number,
       date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk
    AND i_current_price > 50
    AND cs_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id),
 wsr AS (
  SELECT web_name,
         sum(ws_ext_sales_price) sales,
         sum(coalesce(wr_return_amt, 0)) returns_amt,
         sum(ws_net_profit - coalesce(wr_net_loss, 0)) profit
  FROM web_sales
  LEFT JOIN web_returns ON ws_item_sk = wr_item_sk
                       AND ws_order_number = wr_order_number,
       date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23'
                   AND DATE '2000-08-23' + INTERVAL '30' DAY
    AND ws_web_site_sk = web_site_sk
    AND ws_item_sk = i_item_sk
    AND i_current_price > 50
    AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_name)
SELECT channel, id, sum(sales) sales, sum(returns_amt) returns_amt,
       sum(profit) profit
FROM (SELECT 'store channel' channel, s_store_id id, sales, returns_amt,
             profit
      FROM ssr
      UNION ALL
      SELECT 'catalog channel' channel, cp_catalog_page_id id, sales,
             returns_amt, profit
      FROM csr
      UNION ALL
      SELECT 'web channel' channel, web_name id, sales, returns_amt,
             profit
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
"""

# q83: returned items compared across the three return channels for
# matched weeks
QUERIES[83] = """
WITH sr_items AS (
  SELECT i_item_id item_id, sum(sr_return_quantity) sr_item_qty
  FROM store_returns, item, date_dim
  WHERE sr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN
                         (SELECT d_week_seq FROM date_dim
                          WHERE d_date IN (DATE '2000-06-30',
                                           DATE '2000-09-27',
                                           DATE '2000-11-17')))
    AND sr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
 cr_items AS (
  SELECT i_item_id item_id, sum(cr_return_quantity) cr_item_qty
  FROM catalog_returns, item, date_dim
  WHERE cr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN
                         (SELECT d_week_seq FROM date_dim
                          WHERE d_date IN (DATE '2000-06-30',
                                           DATE '2000-09-27',
                                           DATE '2000-11-17')))
    AND cr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
 wr_items AS (
  SELECT i_item_id item_id, sum(wr_return_quantity) wr_item_qty
  FROM web_returns, item, date_dim
  WHERE wr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN
                         (SELECT d_week_seq FROM date_dim
                          WHERE d_date IN (DATE '2000-06-30',
                                           DATE '2000-09-27',
                                           DATE '2000-11-17')))
    AND wr_returned_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT sr_items.item_id, sr_item_qty,
       sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
         * 100 sr_dev,
       cr_item_qty,
       cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
         * 100 cr_dev,
       wr_item_qty,
       wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
         * 100 wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id, sr_item_qty
LIMIT 100
"""

# q84: customers in a city within an income band, through returns
# (adapted: store_returns has no sr_cdemo_sk in the generator; the
# returns linkage goes through sr_customer_sk instead)
QUERIES[84] = """
SELECT c_customer_id customer_id,
       coalesce(c_last_name, '') customer_last_name,
       coalesce(c_first_name, '') customer_first_name
FROM customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
WHERE ca_city = 'Edgewood'
  AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 38128
  AND ib_upper_bound <= 38128 + 50000
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND sr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
"""

# q85: web return reasons by demographic/geographic slices (adapted: the
# generator's web_returns has no refunded-cdemo column; demographics
# join through the refunded customer's current cdemo)
QUERIES[85] = """
SELECT substr(r_reason_desc, 1, 20),
       avg(ws_quantity), avg(wr_refunded_cash), avg(wr_net_loss)
FROM web_sales, web_returns, web_page, customer_demographics cd1,
     customer, customer_address, date_dim, reason
WHERE ws_web_page_sk = wp_web_page_sk
  AND ws_item_sk = wr_item_sk
  AND ws_order_number = wr_order_number
  AND ws_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND wr_refunded_customer_sk = c_customer_sk
  AND cd1.cd_demo_sk = c_current_cdemo_sk
  AND c_current_addr_sk = ca_address_sk
  AND wr_reason_sk = r_reason_sk
  AND ((cd1.cd_marital_status = 'M'
        AND cd1.cd_education_status = 'Advanced Degree'
        AND ws_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd1.cd_marital_status = 'S'
        AND cd1.cd_education_status = 'College'
        AND ws_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd1.cd_marital_status = 'W'
        AND cd1.cd_education_status = '2 yr Degree'
        AND ws_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ca_country = 'United States'
        AND ca_state IN ('IN', 'OH', 'NJ')
        AND ws_net_profit BETWEEN 100 AND 200)
    OR (ca_country = 'United States'
        AND ca_state IN ('WI', 'CT', 'KY')
        AND ws_net_profit BETWEEN 150 AND 300)
    OR (ca_country = 'United States'
        AND ca_state IN ('LA', 'IA', 'AR')
        AND ws_net_profit BETWEEN 50 AND 250))
GROUP BY r_reason_desc
ORDER BY substr(r_reason_desc, 1, 20), avg(ws_quantity),
         avg(wr_refunded_cash), avg(wr_net_loss)
LIMIT 100
"""

# q86: web sales margin hierarchy with grouping() rank
QUERIES[86] = """
SELECT sum(ws_net_paid) total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() OVER (
         PARTITION BY grouping(i_category) + grouping(i_class),
                      CASE WHEN grouping(i_class) = 0
                           THEN i_category END
         ORDER BY sum(ws_net_paid) DESC) rank_within_parent
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN 1200 AND 1211
  AND d1.d_date_sk = ws_sold_date_sk
  AND i_item_sk = ws_item_sk
GROUP BY ROLLUP (i_category, i_class)
ORDER BY lochierarchy DESC,
         CASE WHEN grouping(i_category) + grouping(i_class) = 0
              THEN i_category END,
         rank_within_parent
LIMIT 100
"""

# q95: web orders shipped from multiple warehouses with returns
QUERIES[95] = """
WITH ws_wh AS (
  SELECT ws1.ws_order_number, ws1.ws_warehouse_sk wh1,
         ws2.ws_warehouse_sk wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT count(DISTINCT ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '1999-02-01'
                 AND DATE '1999-02-01' + INTERVAL '60' DAY
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number =
                                    ws_wh.ws_order_number)
"""

# q97: store/catalog purchase overlap by customer-item pairs
# (FULL OUTER JOIN counting)
QUERIES[97] = """
WITH ssci AS (
  SELECT ss_customer_sk customer_sk, ss_item_sk item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY ss_customer_sk, ss_item_sk),
 csci AS (
  SELECT cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL
                THEN 1 ELSE 0 END) store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL
                THEN 1 ELSE 0 END) catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL
                THEN 1 ELSE 0 END) store_and_catalog
FROM ssci
FULL OUTER JOIN csci ON ssci.customer_sk = csci.customer_sk
                    AND ssci.item_sk = csci.item_sk
LIMIT 100
"""

# q11: year-over-year growth, store vs web, reporting preferred flag
QUERIES[11] = """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name,
         c_preferred_cust_flag customer_preferred_cust_flag,
         d_year dyear,
         sum(ss_ext_list_price - ss_ext_discount_amt) year_total,
         's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name,
         c_preferred_cust_flag, d_year,
         sum(ws_ext_list_price - ws_ext_discount_amt),
         'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2002
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2002
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE 0.0 END >
      CASE WHEN t_s_firstyear.year_total > 0
           THEN t_s_secyear.year_total / t_s_firstyear.year_total
           ELSE 0.0 END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_preferred_cust_flag
LIMIT 100
"""

# ---------------------------------------------------------------------------
# Oracle overrides: sqlite has no ROLLUP/grouping(), so these queries get a
# hand-expanded UNION ALL equivalent (same technique as
# test_grouping_sets.py). `c IS NULL, c` in ORDER BY emulates Trino's
# NULLS LAST default for rollup NULL rows.
# ---------------------------------------------------------------------------

ORACLE = {}

ORACLE[22] = """
WITH base AS (
  SELECT i_product_name, i_brand, i_class, i_category,
         inv_quantity_on_hand q
  FROM inventory, date_dim, item
  WHERE inv_date_sk = d_date_sk
    AND inv_item_sk = i_item_sk
    AND d_month_seq BETWEEN 1200 AND 1211)
SELECT * FROM (
  SELECT i_product_name, i_brand, i_class, i_category, avg(q) qoh
  FROM base GROUP BY i_product_name, i_brand, i_class, i_category
  UNION ALL
  SELECT i_product_name, i_brand, i_class, NULL, avg(q)
  FROM base GROUP BY i_product_name, i_brand, i_class
  UNION ALL
  SELECT i_product_name, i_brand, NULL, NULL, avg(q)
  FROM base GROUP BY i_product_name, i_brand
  UNION ALL
  SELECT i_product_name, NULL, NULL, NULL, avg(q)
  FROM base GROUP BY i_product_name
  UNION ALL
  SELECT NULL, NULL, NULL, NULL, avg(q) FROM base)
ORDER BY qoh, i_product_name IS NULL, i_product_name,
         i_brand IS NULL, i_brand, i_class IS NULL, i_class,
         i_category IS NULL, i_category
LIMIT 100
"""

ORACLE[18] = """
WITH base AS (
  SELECT i_item_id, ca_country, ca_state, ca_county,
         CAST(cs_quantity AS REAL) q, CAST(cs_list_price AS REAL) lp,
         CAST(cs_coupon_amt AS REAL) ca, CAST(cs_sales_price AS REAL) sp,
         CAST(cs_net_profit AS REAL) np, CAST(c_birth_year AS REAL) by2,
         CAST(cd1.cd_dep_count AS REAL) dc
  FROM catalog_sales, customer_demographics cd1,
       customer_demographics cd2, customer, customer_address, date_dim,
       item
  WHERE cs_sold_date_sk = d_date_sk
    AND cs_item_sk = i_item_sk
    AND cs_bill_cdemo_sk = cd1.cd_demo_sk
    AND cs_bill_customer_sk = c_customer_sk
    AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
    AND c_current_cdemo_sk = cd2.cd_demo_sk
    AND c_current_addr_sk = ca_address_sk
    AND c_birth_month IN (1, 6, 8, 9, 12, 2)
    AND d_year = 1998)
SELECT * FROM (
  SELECT i_item_id, ca_country, ca_state, ca_county, avg(q), avg(lp),
         avg(ca), avg(sp), avg(np), avg(by2), avg(dc)
  FROM base GROUP BY i_item_id, ca_country, ca_state, ca_county
  UNION ALL
  SELECT i_item_id, ca_country, ca_state, NULL, avg(q), avg(lp),
         avg(ca), avg(sp), avg(np), avg(by2), avg(dc)
  FROM base GROUP BY i_item_id, ca_country, ca_state
  UNION ALL
  SELECT i_item_id, ca_country, NULL, NULL, avg(q), avg(lp), avg(ca),
         avg(sp), avg(np), avg(by2), avg(dc)
  FROM base GROUP BY i_item_id, ca_country
  UNION ALL
  SELECT i_item_id, NULL, NULL, NULL, avg(q), avg(lp), avg(ca),
         avg(sp), avg(np), avg(by2), avg(dc)
  FROM base GROUP BY i_item_id
  UNION ALL
  SELECT NULL, NULL, NULL, NULL, avg(q), avg(lp), avg(ca), avg(sp),
         avg(np), avg(by2), avg(dc)
  FROM base)
ORDER BY ca_country IS NULL, ca_country, ca_state IS NULL, ca_state,
         ca_county IS NULL, ca_county, i_item_id IS NULL, i_item_id
LIMIT 100
"""

ORACLE[5] = """
WITH ssr AS (
  SELECT s_store_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_amt, sum(net_loss) profit_loss
  FROM (SELECT ss_store_sk store_sk, ss_sold_date_sk date_sk,
               ss_ext_sales_price sales_price, ss_net_profit profit,
               0 return_amt, 0 net_loss
        FROM store_sales
        UNION ALL
        SELECT sr_store_sk, sr_returned_date_sk, 0, 0, sr_return_amt,
               sr_net_loss
        FROM store_returns) salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-06'
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
 csr AS (
  SELECT cp_catalog_page_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_amt, sum(net_loss) profit_loss
  FROM (SELECT cs_catalog_page_sk page_sk, cs_sold_date_sk date_sk,
               cs_ext_sales_price sales_price, cs_net_profit profit,
               0 return_amt, 0 net_loss
        FROM catalog_sales
        UNION ALL
        SELECT cr_catalog_page_sk, cr_returned_date_sk, 0, 0,
               cr_return_amount, cr_net_loss
        FROM catalog_returns) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-06'
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id),
 wsr AS (
  SELECT web_name,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_amt, sum(net_loss) profit_loss
  FROM (SELECT ws_web_site_sk wsr_web_site_sk, ws_sold_date_sk date_sk,
               ws_ext_sales_price sales_price, ws_net_profit profit,
               0 return_amt, 0 net_loss
        FROM web_sales
        UNION ALL
        SELECT ws.ws_web_site_sk, wr.wr_returned_date_sk, 0, 0,
               wr.wr_return_amt, wr.wr_net_loss
        FROM web_returns wr
        LEFT JOIN web_sales ws
          ON wr.wr_item_sk = ws.ws_item_sk
         AND wr.wr_order_number = ws.ws_order_number) salesreturns,
       date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-06'
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_name),
 x AS (
  SELECT 'store channel' channel, s_store_id id, sales, returns_amt,
         profit, profit_loss
  FROM ssr
  UNION ALL
  SELECT 'catalog channel', cp_catalog_page_id, sales, returns_amt,
         profit, profit_loss
  FROM csr
  UNION ALL
  SELECT 'web channel', web_name, sales, returns_amt, profit,
         profit_loss
  FROM wsr)
SELECT * FROM (
  SELECT channel, id, sum(sales) sales, sum(returns_amt) returns_amt,
         sum(profit - profit_loss) profit
  FROM x GROUP BY channel, id
  UNION ALL
  SELECT channel, NULL, sum(sales), sum(returns_amt),
         sum(profit - profit_loss)
  FROM x GROUP BY channel
  UNION ALL
  SELECT NULL, NULL, sum(sales), sum(returns_amt),
         sum(profit - profit_loss)
  FROM x)
ORDER BY channel IS NULL, channel, id IS NULL, id
LIMIT 100
"""

ORACLE[77] = """
WITH ss AS (
  SELECT s_store_sk, sum(ss_ext_sales_price) sales,
         sum(ss_net_profit) profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
 sr AS (
  SELECT s_store_sk, sum(sr_return_amt) returns_amt,
         sum(sr_net_loss) profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
 cs AS (
  SELECT cs_call_center_sk, sum(cs_ext_sales_price) sales,
         sum(cs_net_profit) profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
  GROUP BY cs_call_center_sk),
 cr AS (
  SELECT cr_call_center_sk, sum(cr_return_amount) returns_amt,
         sum(cr_net_loss) profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
  GROUP BY cr_call_center_sk),
 ws AS (
  SELECT wp_web_page_sk, sum(ws_ext_sales_price) sales,
         sum(ws_net_profit) profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
 wr AS (
  SELECT wr_web_page_sk, sum(wr_return_amt) returns_amt,
         sum(wr_net_loss) profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wr_web_page_sk),
 x AS (
  SELECT 'store channel' channel, ss.s_store_sk id, sales,
         COALESCE(returns_amt, 0) returns_amt,
         profit - COALESCE(profit_loss, 0) profit
  FROM ss LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
  UNION ALL
  SELECT 'catalog channel', cs_call_center_sk, sales,
         COALESCE(returns_amt, 0), profit - COALESCE(profit_loss, 0)
  FROM cs LEFT JOIN cr ON cs.cs_call_center_sk = cr.cr_call_center_sk
  UNION ALL
  SELECT 'web channel', ws.wp_web_page_sk, sales,
         COALESCE(returns_amt, 0), profit - COALESCE(profit_loss, 0)
  FROM ws LEFT JOIN wr ON ws.wp_web_page_sk = wr.wr_web_page_sk)
SELECT * FROM (
  SELECT channel, id, sum(sales) sales, sum(returns_amt) returns_amt,
         sum(profit) profit
  FROM x GROUP BY channel, id
  UNION ALL
  SELECT channel, NULL, sum(sales), sum(returns_amt), sum(profit)
  FROM x GROUP BY channel
  UNION ALL
  SELECT NULL, NULL, sum(sales), sum(returns_amt), sum(profit) FROM x)
ORDER BY channel IS NULL, channel, id IS NULL, id
LIMIT 100
"""

ORACLE[80] = """
WITH ssr AS (
  SELECT s_store_id,
         sum(ss_ext_sales_price) sales,
         sum(COALESCE(sr_return_amt, 0)) returns_amt,
         sum(ss_net_profit - COALESCE(sr_net_loss, 0)) profit
  FROM store_sales
  LEFT JOIN store_returns ON ss_item_sk = sr_item_sk
                         AND ss_ticket_number = sr_ticket_number,
       date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND ss_store_sk = s_store_sk
    AND ss_item_sk = i_item_sk
    AND i_current_price > 50
    AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id),
 csr AS (
  SELECT cp_catalog_page_id,
         sum(cs_ext_sales_price) sales,
         sum(COALESCE(cr_return_amount, 0)) returns_amt,
         sum(cs_net_profit - COALESCE(cr_net_loss, 0)) profit
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cs_item_sk = cr_item_sk
                           AND cs_order_number = cr_order_number,
       date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk
    AND i_current_price > 50
    AND cs_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id),
 wsr AS (
  SELECT web_name,
         sum(ws_ext_sales_price) sales,
         sum(COALESCE(wr_return_amt, 0)) returns_amt,
         sum(ws_net_profit - COALESCE(wr_net_loss, 0)) profit
  FROM web_sales
  LEFT JOIN web_returns ON ws_item_sk = wr_item_sk
                       AND ws_order_number = wr_order_number,
       date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN '2000-08-23' AND '2000-09-22'
    AND ws_web_site_sk = web_site_sk
    AND ws_item_sk = i_item_sk
    AND i_current_price > 50
    AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_name),
 x AS (
  SELECT 'store channel' channel, s_store_id id, sales, returns_amt,
         profit
  FROM ssr
  UNION ALL
  SELECT 'catalog channel', cp_catalog_page_id, sales, returns_amt,
         profit
  FROM csr
  UNION ALL
  SELECT 'web channel', web_name, sales, returns_amt, profit
  FROM wsr)
SELECT * FROM (
  SELECT channel, id, sum(sales) sales, sum(returns_amt) returns_amt,
         sum(profit) profit
  FROM x GROUP BY channel, id
  UNION ALL
  SELECT channel, NULL, sum(sales), sum(returns_amt), sum(profit)
  FROM x GROUP BY channel
  UNION ALL
  SELECT NULL, NULL, sum(sales), sum(returns_amt), sum(profit) FROM x)
ORDER BY channel IS NULL, channel, id IS NULL, id
LIMIT 100
"""

ORACLE[36] = """
WITH base AS (
  SELECT i_category, i_class, ss_net_profit np, ss_ext_sales_price sp
  FROM store_sales, date_dim d1, item, store
  WHERE d1.d_year = 2001
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND s_state = 'TN'),
 g AS (
  SELECT sum(np) / sum(sp) gross_margin, i_category, i_class,
         0 lochierarchy
  FROM base GROUP BY i_category, i_class
  UNION ALL
  SELECT sum(np) / sum(sp), i_category, NULL, 1
  FROM base GROUP BY i_category
  UNION ALL
  SELECT sum(np) / sum(sp), NULL, NULL, 2 FROM base)
SELECT gross_margin, i_category, i_class, lochierarchy,
       rank() OVER (
         PARTITION BY lochierarchy,
                      CASE WHEN lochierarchy = 0 THEN i_category END
         ORDER BY gross_margin ASC) rank_within_parent
FROM g
ORDER BY lochierarchy DESC,
         (CASE WHEN lochierarchy = 0 THEN i_category END) IS NULL,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
"""

ORACLE[86] = """
WITH base AS (
  SELECT i_category, i_class, ws_net_paid np
  FROM web_sales, date_dim d1, item
  WHERE d1.d_month_seq BETWEEN 1200 AND 1211
    AND d1.d_date_sk = ws_sold_date_sk
    AND i_item_sk = ws_item_sk),
 g AS (
  SELECT sum(np) total_sum, i_category, i_class, 0 lochierarchy
  FROM base GROUP BY i_category, i_class
  UNION ALL
  SELECT sum(np), i_category, NULL, 1 FROM base GROUP BY i_category
  UNION ALL
  SELECT sum(np), NULL, NULL, 2 FROM base)
SELECT total_sum, i_category, i_class, lochierarchy,
       rank() OVER (
         PARTITION BY lochierarchy,
                      CASE WHEN lochierarchy = 0 THEN i_category END
         ORDER BY total_sum DESC) rank_within_parent
FROM g
ORDER BY lochierarchy DESC,
         (CASE WHEN lochierarchy = 0 THEN i_category END) IS NULL,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
"""

ORACLE[70] = """
WITH base AS (
  SELECT s_state, s_county, ss_net_profit np
  FROM store_sales, date_dim d1, store
  WHERE d1.d_month_seq BETWEEN 1200 AND 1211
    AND d1.d_date_sk = ss_sold_date_sk
    AND s_store_sk = ss_store_sk
    AND s_state IN
        (SELECT s_state
         FROM (SELECT s_state,
                      rank() OVER (PARTITION BY s_state
                                   ORDER BY sum(ss_net_profit) DESC)
                        ranking
               FROM store_sales, store, date_dim
               WHERE d_month_seq BETWEEN 1200 AND 1211
                 AND d_date_sk = ss_sold_date_sk
                 AND s_store_sk = ss_store_sk
               GROUP BY s_state) tmp1
         WHERE ranking <= 5)),
 g AS (
  SELECT sum(np) total_sum, s_state, s_county, 0 lochierarchy
  FROM base GROUP BY s_state, s_county
  UNION ALL
  SELECT sum(np), s_state, NULL, 1 FROM base GROUP BY s_state
  UNION ALL
  SELECT sum(np), NULL, NULL, 2 FROM base)
SELECT total_sum, s_state, s_county, lochierarchy,
       rank() OVER (
         PARTITION BY lochierarchy,
                      CASE WHEN lochierarchy = 0 THEN s_state END
         ORDER BY total_sum DESC) rank_within_parent
FROM g
ORDER BY lochierarchy DESC,
         (CASE WHEN lochierarchy = 0 THEN s_state END) IS NULL,
         CASE WHEN lochierarchy = 0 THEN s_state END,
         rank_within_parent
LIMIT 100
"""

ORACLE[14] = """
WITH cross_items AS (
  SELECT i_item_sk ss_item_sk
  FROM item,
       (SELECT iss.i_brand_id brand_id, iss.i_class_id class_id,
               iss.i_category_id category_id
        FROM store_sales, item iss, date_dim d1
        WHERE ss_item_sk = iss.i_item_sk
          AND ss_sold_date_sk = d1.d_date_sk
          AND d1.d_year BETWEEN 1999 AND 2001
        INTERSECT
        SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
        FROM catalog_sales, item ics, date_dim d2
        WHERE cs_item_sk = ics.i_item_sk
          AND cs_sold_date_sk = d2.d_date_sk
          AND d2.d_year BETWEEN 1999 AND 2001
        INTERSECT
        SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
        FROM web_sales, item iws, date_dim d3
        WHERE ws_item_sk = iws.i_item_sk
          AND ws_sold_date_sk = d3.d_date_sk
          AND d3.d_year BETWEEN 1999 AND 2001) x
  WHERE i_brand_id = brand_id AND i_class_id = class_id
    AND i_category_id = category_id),
 avg_sales AS (
  SELECT avg(quantity * list_price) average_sales
  FROM (SELECT ss_quantity quantity, ss_list_price list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT cs_quantity, cs_list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT ws_quantity, ws_list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001) x),
 y AS (
  SELECT 'store' channel, i_brand_id, i_class_id, i_category_id,
         sum(ss_quantity * ss_list_price) sales, count(*) number_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 11
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(ss_quantity * ss_list_price) >
         (SELECT average_sales FROM avg_sales)
  UNION ALL
  SELECT 'catalog', i_brand_id, i_class_id, i_category_id,
         sum(cs_quantity * cs_list_price), count(*)
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 11
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(cs_quantity * cs_list_price) >
         (SELECT average_sales FROM avg_sales)
  UNION ALL
  SELECT 'web', i_brand_id, i_class_id, i_category_id,
         sum(ws_quantity * ws_list_price), count(*)
  FROM web_sales, item, date_dim
  WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 11
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(ws_quantity * ws_list_price) >
         (SELECT average_sales FROM avg_sales))
SELECT * FROM (
  SELECT channel, i_brand_id, i_class_id, i_category_id,
         sum(sales) sum_sales, sum(number_sales) number_sales
  FROM y GROUP BY channel, i_brand_id, i_class_id, i_category_id
  UNION ALL
  SELECT channel, i_brand_id, i_class_id, NULL, sum(sales),
         sum(number_sales)
  FROM y GROUP BY channel, i_brand_id, i_class_id
  UNION ALL
  SELECT channel, i_brand_id, NULL, NULL, sum(sales),
         sum(number_sales)
  FROM y GROUP BY channel, i_brand_id
  UNION ALL
  SELECT channel, NULL, NULL, NULL, sum(sales), sum(number_sales)
  FROM y GROUP BY channel
  UNION ALL
  SELECT NULL, NULL, NULL, NULL, sum(sales), sum(number_sales) FROM y)
ORDER BY channel IS NULL, channel, i_brand_id IS NULL, i_brand_id,
         i_class_id IS NULL, i_class_id, i_category_id IS NULL,
         i_category_id
LIMIT 100
"""

ORACLE[67] = """
WITH base AS (
  SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id,
         COALESCE(ss_sales_price * ss_quantity, 0) sp
  FROM store_sales, date_dim, store, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk
    AND d_month_seq BETWEEN 1200 AND 1211),
 dw1 AS (
  SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sum(sp) sumsales
  FROM base GROUP BY i_category, i_class, i_brand, i_product_name,
                     d_year, d_qoy, d_moy, s_store_id
  UNION ALL
  SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, NULL, sum(sp)
  FROM base GROUP BY i_category, i_class, i_brand, i_product_name,
                     d_year, d_qoy, d_moy
  UNION ALL
  SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         NULL, NULL, sum(sp)
  FROM base GROUP BY i_category, i_class, i_brand, i_product_name,
                     d_year, d_qoy
  UNION ALL
  SELECT i_category, i_class, i_brand, i_product_name, d_year, NULL,
         NULL, NULL, sum(sp)
  FROM base GROUP BY i_category, i_class, i_brand, i_product_name,
                     d_year
  UNION ALL
  SELECT i_category, i_class, i_brand, i_product_name, NULL, NULL,
         NULL, NULL, sum(sp)
  FROM base GROUP BY i_category, i_class, i_brand, i_product_name
  UNION ALL
  SELECT i_category, i_class, i_brand, NULL, NULL, NULL, NULL, NULL,
         sum(sp)
  FROM base GROUP BY i_category, i_class, i_brand
  UNION ALL
  SELECT i_category, i_class, NULL, NULL, NULL, NULL, NULL, NULL,
         sum(sp)
  FROM base GROUP BY i_category, i_class
  UNION ALL
  SELECT i_category, NULL, NULL, NULL, NULL, NULL, NULL, NULL, sum(sp)
  FROM base GROUP BY i_category
  UNION ALL
  SELECT NULL, NULL, NULL, NULL, NULL, NULL, NULL, NULL, sum(sp)
  FROM base)
SELECT * FROM (
  SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales,
         rank() OVER (PARTITION BY i_category
                      ORDER BY sumsales DESC) rk
  FROM dw1) dw2
WHERE rk <= 100
ORDER BY i_category IS NULL, i_category, i_class IS NULL, i_class,
         i_brand IS NULL, i_brand, i_product_name IS NULL,
         i_product_name, d_year IS NULL, d_year, d_qoy IS NULL, d_qoy,
         d_moy IS NULL, d_moy, s_store_id IS NULL, s_store_id,
         sumsales, rk
LIMIT 100
"""

# q49's oracle: sqlite CAST(... AS decimal) keeps INTEGER affinity, so the
# ratio divisions must cast to REAL explicitly or they integer-divide into
# a sea of rank ties.
ORACLE[49] = QUERIES[49].replace("AS double)", "AS REAL)")

# q72's oracle: sqlite can't add INTERVAL to a date column
ORACLE[72] = QUERIES[72].replace(
    "d3.d_date > d1.d_date + INTERVAL '5' DAY",
    "d3.d_date > date(d1.d_date, '+5 day')")

ORACLE[75] = QUERIES[75].replace(
    "cs_ext_sales_price\n                 - coalesce(cr_return_amount, 0.0) sales_amt",
    "(CAST(ROUND(cs_ext_sales_price * 100) AS INTEGER)\n"
    "                 - CAST(ROUND(coalesce(cr_return_amount, 0) * 100)"
    " AS INTEGER)) / 100.0 sales_amt").replace(
    "ss_ext_sales_price\n                 - coalesce(sr_return_amt, 0.0) sales_amt",
    "(CAST(ROUND(ss_ext_sales_price * 100) AS INTEGER)\n"
    "                 - CAST(ROUND(coalesce(sr_return_amt, 0) * 100)"
    " AS INTEGER)) / 100.0 sales_amt").replace(
    "ws_ext_sales_price\n                 - coalesce(wr_return_amt, 0.0) sales_amt",
    "(CAST(ROUND(ws_ext_sales_price * 100) AS INTEGER)\n"
    "                 - CAST(ROUND(coalesce(wr_return_amt, 0) * 100)"
    " AS INTEGER)) / 100.0 sales_amt")


ORACLE[57] = """
WITH v0 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy,
         sum(CAST(ROUND(cs_sales_price * 100) AS INTEGER)) cents
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND cc_call_center_sk = cs_call_center_sk
    AND (d_year = 2000
         OR (d_year = 1999 AND d_moy = 12)
         OR (d_year = 2001 AND d_moy = 1))
  GROUP BY i_category, i_brand, cc_name, d_year, d_moy),
 v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy, cents,
         CAST(ROUND(CAST(sum(cents) OVER (PARTITION BY i_category,
                i_brand, cc_name, d_year) AS REAL)
              / count(*) OVER (PARTITION BY i_category, i_brand,
                cc_name, d_year)) AS INTEGER) rcents,
         rank() OVER (PARTITION BY i_category, i_brand, cc_name
                      ORDER BY d_year, d_moy) rn
  FROM v0),
 v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
         v1.cents, v1.rcents, v1_lag.cents pcents, v1_lead.cents ncents
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.cc_name = v1_lag.cc_name
    AND v1.cc_name = v1_lead.cc_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1)
SELECT i_category, i_brand, d_year, d_moy, rcents / 100.0,
       cents / 100.0, pcents / 100.0, ncents / 100.0
FROM v2
WHERE d_year = 2000
  AND rcents > 0
  AND CASE WHEN rcents > 0
           THEN CAST(abs(cents - rcents) AS REAL) / CAST(rcents AS REAL)
           ELSE NULL END > 0.1
ORDER BY cents - rcents, i_category, i_brand, d_year, d_moy
LIMIT 100
"""

ORACLE[47] = """
WITH v0 AS (
  SELECT i_category, i_brand, s_store_name, d_year, d_moy,
         sum(CAST(ROUND(ss_sales_price * 100) AS INTEGER)) cents
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 2000
         OR (d_year = 1999 AND d_moy = 12)
         OR (d_year = 2001 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, d_year, d_moy),
 v1 AS (
  SELECT i_category, i_brand, s_store_name, d_year, d_moy, cents,
         CAST(ROUND(CAST(sum(cents) OVER (PARTITION BY i_category,
                i_brand, s_store_name, d_year) AS REAL)
              / count(*) OVER (PARTITION BY i_category, i_brand,
                s_store_name, d_year)) AS INTEGER) rcents,
         rank() OVER (PARTITION BY i_category, i_brand, s_store_name
                      ORDER BY d_year, d_moy) rn
  FROM v0),
 v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.s_store_name, v1.d_year,
         v1.d_moy, v1.cents, v1.rcents,
         v1_lag.cents pcents, v1_lead.cents ncents
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.s_store_name = v1_lag.s_store_name
    AND v1.s_store_name = v1_lead.s_store_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1)
SELECT i_category, i_brand, d_year, d_moy, rcents / 100.0,
       cents / 100.0, pcents / 100.0, ncents / 100.0
FROM v2
WHERE d_year = 2000
  AND rcents > 0
  AND CASE WHEN rcents > 0
           THEN CAST(abs(cents - rcents) AS REAL) / CAST(rcents AS REAL)
           ELSE NULL END > 0.1
ORDER BY cents - rcents, i_category, i_brand, d_year, d_moy
LIMIT 100
"""

# q49's oracle: divide SCALED CENTS directly (the engine divides scaled
# decimals with the scales cancelling, which rounds differently at the
# ULP than dividing two post-scaled doubles — enough to flip rank ties)
ORACLE[49] = QUERIES[49].replace("AS double)", "AS REAL)")
for _old, _new in [
    ("""cast(sum(coalesce(wr.wr_return_amt, 0))
                              AS REAL) /
                         cast(sum(coalesce(ws.ws_net_paid, 0))
                              AS REAL) currency_ratio""",
     """CAST(sum(CAST(ROUND(coalesce(wr.wr_return_amt, 0) * 100)
                              AS INTEGER)) AS REAL) /
                         CAST(sum(CAST(ROUND(coalesce(ws.ws_net_paid, 0)
                              * 100) AS INTEGER)) AS REAL)
                         currency_ratio"""),
    ("""cast(sum(coalesce(cr.cr_return_amount, 0))
                              AS REAL) /
                         cast(sum(coalesce(cs.cs_net_paid, 0))
                              AS REAL) currency_ratio""",
     """CAST(sum(CAST(ROUND(coalesce(cr.cr_return_amount, 0) * 100)
                              AS INTEGER)) AS REAL) /
                         CAST(sum(CAST(ROUND(coalesce(cs.cs_net_paid, 0)
                              * 100) AS INTEGER)) AS REAL)
                         currency_ratio"""),
    ("""cast(sum(coalesce(sr.sr_return_amt, 0))
                              AS REAL) /
                         cast(sum(coalesce(sts.ss_net_paid, 0))
                              AS REAL) currency_ratio""",
     """CAST(sum(CAST(ROUND(coalesce(sr.sr_return_amt, 0) * 100)
                              AS INTEGER)) AS REAL) /
                         CAST(sum(CAST(ROUND(coalesce(sts.ss_net_paid, 0)
                              * 100) AS INTEGER)) AS REAL)
                         currency_ratio"""),
]:
    ORACLE[49] = ORACLE[49].replace(_old, _new)

# sqlite CAST(x AS decimal) keeps INTEGER affinity -> integer division;
# the ratio filter must divide as REAL
ORACLE[75] = ORACLE[75].replace(
    "cast(curr_yr.sales_cnt AS decimal(17,2))",
    "CAST(curr_yr.sales_cnt AS REAL)").replace(
    "cast(prev_yr.sales_cnt AS decimal(17,2))",
    "CAST(prev_yr.sales_cnt AS REAL)")

# q49 ranks over floating-point ratio ties are ULP-sensitive between the
# engine's XLA-simplified division and sqlite REAL arithmetic; the row
# SET matches but tie ranks can swap. Compared unordered with ranks
# dropped by the harness.
ULP_SENSITIVE = {49}
