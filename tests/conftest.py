"""Test configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §7 / driver contract):
multi-chip sharding semantics are validated without TPU hardware, the same
way Trino's DistributedQueryRunner boots a multi-node cluster inside one JVM
(testing/trino-testing/.../DistributedQueryRunner.java:107).

Environment must be set before jax is imported anywhere.
"""

import os
import sys

# the environment pre-sets JAX_PLATFORMS=axon (the real TPU tunnel) and
# `import pytest` already imported jax via a plugin entrypoint, so env vars
# alone are too late — use the runtime config API (backends are still
# uninitialized at conftest time, so this takes effect)
os.environ["JAX_PLATFORMS"] = "cpu"
# test assertions on executor stats (capacity retries, sync counts) assume
# a cold decision state; the on-disk decision cache would let a previous
# pytest session's runs leak in. Tests that exercise persistence opt back
# in with a tmp TRINO_TPU_DATA_CACHE.
os.environ.setdefault("TRINO_TPU_DECISION_CACHE", "0")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# XLA CPU compiler hygiene: one process compiling many hundreds of distinct
# programs (the full TPC-DS sweep) deterministically SEGFAULTS inside
# backend_compile_and_load around the ~80th jit-heavy test — reproduced on
# two unrelated commits, independent of stack size, with the persistent
# cache off, so it is backend-state accumulation, not this engine. Dropping
# the live executables every N tests keeps the compiler healthy; the
# recompiles cost seconds on CPU.
#
# The SLOW mesh tier (tests/test_distributed.py, -m slow) additionally hits
# an intermittent virtual-device collective rendezvous abort
# (rendezvous.cc "only 7 of 8 arrived") after ~44 jit-heavy mesh tests in
# one process — each test passes in isolation, and the tier passes under
# process isolation: run it as `pytest tests/test_distributed.py -m slow
# -n 2` (xdist). The quick tier (the CI gate) is unaffected.
# ---------------------------------------------------------------------------

_CLEAR_EVERY = 10
_test_count = [0]


def pytest_runtest_teardown(item, nextitem):
    _test_count[0] += 1
    if _test_count[0] % _CLEAR_EVERY == 0:
        import gc
        jax.clear_caches()
        gc.collect()      # drop executables whose last ref died mid-test
