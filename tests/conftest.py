"""Test configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §7 / driver contract):
multi-chip sharding semantics are validated without TPU hardware, the same
way Trino's DistributedQueryRunner boots a multi-node cluster inside one JVM
(testing/trino-testing/.../DistributedQueryRunner.java:107).

Environment must be set before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
