"""Memory-pressure survival chain tests (round 9).

Reference patterns: MemoryPool reserve/revoke (memory/MemoryPool.java:44,
execution/MemoryRevokingScheduler.java:47), the spilling operators' must-
be-identical-results contract, ClusterMemoryManager + the total-
reservation-dominant LowMemoryKiller, OutputBuffer byte bounds, and
resource-group soft memory limits (InternalResourceGroup).
"""

import json
import threading
import time
from urllib.request import urlopen

import pytest

from trino_tpu.exec.memory import (ExceededMemoryLimitError,
                                   MemoryAccountingError, MemoryPool,
                                   parse_bytes)
from trino_tpu.exec.session import Session

JOIN_Q = """
SELECT o_custkey, count(*) AS c, sum(o_totalprice) AS s
FROM orders JOIN customer ON o_custkey = c_custkey
WHERE c_acctbal > 0
GROUP BY o_custkey
ORDER BY s DESC, o_custkey LIMIT 50
"""

AGG_Q = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS q, count(*) AS c,
       min(l_discount) AS mn, max(l_tax) AS mx
FROM lineitem GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


@pytest.fixture(scope="module")
def baseline():
    s = Session(default_schema="tiny")
    join_rows = s.execute(JOIN_Q).rows
    agg_rows = s.execute(AGG_Q).rows
    peak = s.executor.pool.peak
    return {"join": join_rows, "agg": agg_rows, "peak": peak}


# -- pool semantics ---------------------------------------------------------

def test_pool_revocable_reservations_and_callbacks():
    pool = MemoryPool(1000, strict=True)
    freed = []

    def spill(target):
        take = min(target, 600)
        pool.free_revocable(take, tag="cache")
        freed.append(take)
        return take

    pool.register_revocation(spill, tag="cache")
    pool.reserve_revocable(600, tag="cache")
    pool.reserve(300)
    # 600 revocable + 300 user: the next 300-byte reserve is 200 over
    # the limit and must trigger revocation (spill) instead of failing
    pool.reserve(300)
    assert freed == [200]
    assert pool.reserved == 600
    assert pool.revocable == 400
    pool.free(600)
    pool.free_revocable(400, tag="cache")
    pool.close()


def test_pool_limit_raises_without_revocable():
    pool = MemoryPool(100, strict=True)
    pool.reserve(80)
    with pytest.raises(ExceededMemoryLimitError):
        pool.reserve(30)
    assert pool.reserved == 80        # failed reserve takes nothing
    pool.free(80)
    pool.close()


def test_pool_double_free_detected_strict():
    pool = MemoryPool(1000, strict=True)
    pool.reserve(100)
    with pytest.raises(MemoryAccountingError):
        pool.free(200)


def test_pool_close_detects_leak():
    pool = MemoryPool(1000, strict=True)
    pool.reserve(64, tag="q1")
    with pytest.raises(MemoryAccountingError):
        pool.close()
    # non-strict: counted, ledger zeroed
    pool2 = MemoryPool(1000, strict=False)
    pool2.reserve(64, tag="q1")
    pool2.close()
    assert pool2.accounting_errors == 1
    assert pool2.reserved == 0


def test_pool_accounting_error_metric_nonstrict():
    from trino_tpu.metrics import MEMORY_ACCOUNTING_ERRORS
    before = MEMORY_ACCOUNTING_ERRORS.value()
    pool = MemoryPool(1000, strict=False)
    pool.reserve(10)
    pool.free(50)                     # clamped + counted, no raise
    assert pool.reserved == 0
    assert MEMORY_ACCOUNTING_ERRORS.value() == before + 1


def test_pool_holder_ledger_attribution():
    pool = MemoryPool(1 << 20, strict=True)
    pool.reserve(100, tag="q1")
    pool.reserve(300, tag="q2")
    assert pool.query_bytes("q2") == 300
    snap = pool.snapshot()
    assert snap["holders"] == {"q1": 100, "q2": 300}
    pool.free(100, tag="q1")
    pool.free(300, tag="q2")
    pool.close()


def test_parse_bytes():
    assert parse_bytes("1024") == 1024
    assert parse_bytes("2GB") == 2 << 30
    assert parse_bytes("512MB") == 512 << 20
    assert parse_bytes("64kB") == 64 << 10


# -- spill-vs-resident bit-exactness ---------------------------------------

@pytest.mark.parametrize("frac", [2, 4])
def test_spill_join_agg_bitexact_at_pool_fractions(baseline, frac):
    """The acceptance shape: a query whose working set exceeds its pool
    spills and returns results identical to the resident run — at 50%
    and 25% of the measured working set."""
    s = Session(default_schema="tiny")
    limit = max(1, baseline["peak"] // frac)
    s.executor.pool.set_limit(limit)
    s.properties["query_max_memory_mb"] = max(1, limit >> 20)
    got = s.execute(JOIN_Q).rows
    assert got == baseline["join"]
    got2 = s.execute(AGG_Q).rows
    assert got2 == baseline["agg"]
    st = s.executor.stats
    if frac >= 4:
        assert st.spilled_joins + st.spilled_aggregations >= 1


def test_spill_disabled_fails_cleanly(baseline):
    s = Session(default_schema="tiny")
    s.execute("SET SESSION spill_enabled = false")
    s.execute("SET SESSION query_max_memory_mb = 1")
    with pytest.raises(ExceededMemoryLimitError):
        s.execute(JOIN_Q)
    # raising the limit restores service on the same session
    s.execute("SET SESSION query_max_memory_mb = 4096")
    assert s.execute("SELECT count(*) FROM nation").rows[0][0] == 25


def test_chunked_partial_state_spills_under_pressure():
    """The chunked driver's partial-aggregation state is revocable:
    under a small pool the revocation callback moves partials to host
    and the merge re-aggregates partition-wise — results identical."""
    q = ("SELECT l_orderkey, sum(l_quantity) AS q FROM lineitem "
         "GROUP BY l_orderkey ORDER BY q DESC, l_orderkey LIMIT 20")
    s = Session(default_schema="tiny")
    want = s.execute(q).rows
    s2 = Session(default_schema="tiny")
    s2.execute("SET SESSION spill_chunk_rows = 8192")
    s2.execute("SET SESSION query_max_memory_mb = 2")
    got = s2.execute(q).rows
    assert got == want


def test_spill_chaos_spool_write_fault_no_wrong_answer(baseline):
    """Chaos interaction: SPOOL_WRITE faults (clean raise AND payload
    corruption) during spill degrade to the RAM copy — the query
    retries nothing, loses nothing, and returns exact results."""
    from trino_tpu.exec.spill import get_spiller
    from trino_tpu.server.failureinjector import FailureInjector
    s = Session(default_schema="tiny")
    s.executor.spill_force_disk = True
    s.executor.pool.set_limit(max(1, baseline["peak"] // 4))
    s.properties["query_max_memory_mb"] = max(
        1, (baseline["peak"] // 4) >> 20)
    spiller = get_spiller(s.executor)
    inj = FailureInjector()
    inj.inject("SPOOL_WRITE", times=2, fault="RAISE")
    inj.inject("SPOOL_WRITE", times=2, fault="CORRUPT")
    spiller.injector = inj
    got = s.execute(JOIN_Q).rows
    assert got == baseline["join"]
    assert inj.injected_count >= 1
    assert spiller.write_recoveries >= 1


# -- cluster arbitration: the low-memory killer -----------------------------

def test_oom_killer_picks_dominant_query_others_complete():
    from trino_tpu.server.coordinator import CoordinatorState
    from trino_tpu.server.memorymanager import ClusterMemoryManager
    from trino_tpu.server.statemachine import (QueryStateMachine,
                                               TrackedQuery)
    state = CoordinatorState(Session(default_schema="tiny"))
    mm = ClusterMemoryManager(state, cluster_limit_bytes=1000,
                              kill_after_ticks=1)
    big = TrackedQuery("q-big", "SELECT 1", "u", QueryStateMachine("q-big"))
    small = TrackedQuery("q-small", "SELECT 2", "u",
                         QueryStateMachine("q-small"))
    state.tracker.register(big)
    state.tracker.register(small)
    big.state_machine.transition("RUNNING")
    small.state_machine.transition("RUNNING")
    pool = state.session.executor.pool
    pool.reserve(900, tag="q-big")
    pool.reserve(200, tag="q-small")
    try:
        mm.tick()
        assert big.state == "FAILED"
        assert big.state_machine.error_name == "QUERY_EXCEEDED_MEMORY"
        assert "low-memory killer" in big.state_machine.error
        assert small.state == "RUNNING"       # others complete
        assert mm.queries_killed == 1
    finally:
        pool.free(900, tag="q-big")
        pool.free(200, tag="q-small")


def test_memory_manager_revokes_before_killing():
    from trino_tpu.server.coordinator import CoordinatorState
    from trino_tpu.server.memorymanager import ClusterMemoryManager
    state = CoordinatorState(Session(default_schema="tiny"))
    mm = ClusterMemoryManager(state, cluster_limit_bytes=1000,
                              kill_after_ticks=1)
    pool = state.session.executor.pool

    def spill(target):
        take = min(target, pool.holder_revocable.get("partials", 0))
        pool.free_revocable(take, tag="partials")
        return take

    h = pool.register_revocation(spill, tag="partials")
    pool.reserve_revocable(800, tag="partials")
    pool.reserve(400, tag="q1")
    try:
        mm.tick()                 # 1200 > 1000: revocation covers it
        assert pool.revocable <= 600
        assert mm.queries_killed == 0
    finally:
        pool.free(400, tag="q1")
        spill(1 << 62)
        pool.unregister_revocation(h)


# -- exchange backpressure --------------------------------------------------

def test_backpressure_bounds_producer_buffer_bytes():
    from trino_tpu.catalog import default_catalog
    from trino_tpu.server.tasks import TaskManager, WorkerTask
    tm = TaskManager(default_catalog())
    tm.max_buffer_bytes = 20_000
    task = WorkerTask("bp1", "", [])
    task.state = "RUNNING"
    page = b"x" * 6000
    peaks = []

    def producer():
        for _ in range(12):
            tm._stage_page(task, 0, page, 1)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    drained = 0
    deadline = time.monotonic() + 30
    while drained < 12 and time.monotonic() < deadline:
        with task.cond:
            peaks.append(task.buffered_bytes)
            if task.buffers.get(0):
                drained += 1
                task.buffered_bytes -= len(task.buffers[0].pop(0))
                task.cond.notify_all()
        time.sleep(0.01)          # slow consumer
    t.join(timeout=10)
    assert drained == 12
    assert max(peaks) <= tm.max_buffer_bytes
    assert task.backpressure_waits >= 1
    assert task.rows_out == 12


def test_backpressure_releases_on_cancel():
    from trino_tpu.catalog import default_catalog
    from trino_tpu.server.tasks import TaskManager, WorkerTask
    tm = TaskManager(default_catalog())
    tm.max_buffer_bytes = 1_000
    task = WorkerTask("bp2", "", [])
    task.state = "RUNNING"
    tm.tasks["bp2"] = task
    done = threading.Event()

    def producer():
        tm._stage_page(task, 0, b"a" * 900, 1)
        tm._stage_page(task, 0, b"b" * 900, 1)   # blocks until cancel
        done.set()

    threading.Thread(target=producer, daemon=True).start()
    time.sleep(0.2)
    assert not done.is_set()          # producer paused on a full buffer
    tm.cancel("bp2")
    assert done.wait(5)               # cancel wakes it


# -- memory-aware admission (resource groups) -------------------------------

def test_soft_memory_limit_keeps_queries_queued():
    from trino_tpu.server.resourcegroups import (ResourceGroupConfig,
                                                 ResourceGroupManager)
    rgm = ResourceGroupManager(ResourceGroupConfig(
        "root", hard_concurrency_limit=4,
        soft_memory_limit_bytes=1000))
    ran = []
    rgm.set_cluster_memory(5000)          # over the soft limit
    rgm.submit("u", lambda: ran.append("a"))
    assert ran == []                      # queued, not rejected
    info = rgm.info()[0]
    assert info["queued"] == 1
    assert info["memoryUsageBytes"] == 5000
    assert info["softMemoryLimitBytes"] == 1000
    # memory drops: the tick admits the queued query and records its wait
    time.sleep(0.02)
    runnable = rgm.set_cluster_memory(100)
    for r in runnable:
        r()
    assert ran == ["a"]
    info = rgm.info()[0]
    assert info["queued"] == 0
    assert info["totalQueueWaitSeconds"] > 0
    assert info["avgQueueWaitSeconds"] > 0


def test_queue_wait_recorded_on_finished():
    from trino_tpu.server.resourcegroups import (ResourceGroupConfig,
                                                 ResourceGroupManager)
    rgm = ResourceGroupManager(ResourceGroupConfig(
        "root", hard_concurrency_limit=1, max_queued=5))
    ran = []
    rgm.submit("u", lambda: ran.append("first"))
    rgm.submit("u", lambda: ran.append("second"))
    time.sleep(0.02)
    nxt = rgm.finished("root")
    assert nxt is not None
    nxt()
    assert ran == ["first", "second"]
    info = rgm.info()[0]
    assert info["totalQueueWaitSeconds"] >= 0.01
    assert info["totalAdmitted"] == 2


# -- HTTP surfaces ----------------------------------------------------------

def test_query_exceeded_memory_surfaces_to_client():
    from trino_tpu.client.client import Client, QueryError
    from trino_tpu.server.coordinator import CoordinatorServer
    session = Session(default_schema="tiny")
    session.properties["spill_enabled"] = False
    session.properties["query_max_memory_mb"] = 1
    coord = CoordinatorServer(session).start()
    try:
        client = Client(coord.uri, user="oom")
        with pytest.raises(QueryError) as ei:
            client.execute(
                "SELECT sum(l_quantity), sum(l_extendedprice), "
                "sum(l_discount), sum(l_tax) FROM lineitem")
        assert ei.value.error_name == "QUERY_EXCEEDED_MEMORY"
        # the killer error is a USER error: no dispatch retry burned
        session.properties["query_max_memory_mb"] = 4096
        r = client.execute("SELECT count(*) FROM region")
        assert r.rows[0][0] == 5
    finally:
        coord.stop()


def test_memory_endpoint_and_system_table():
    from trino_tpu.client.client import Client
    from trino_tpu.server.coordinator import CoordinatorServer
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    try:
        client = Client(coord.uri, user="mem")
        client.execute("SELECT 1")
        with urlopen(f"{coord.uri}/v1/memory", timeout=5) as r:
            snap = json.loads(r.read())
        assert "reserved" in snap and "revocable" in snap
        assert "coordinator" in snap["nodes"]
        rows = client.execute(
            "SELECT group_name, running, total_queue_wait_seconds "
            "FROM system.runtime.resource_groups").rows
        assert rows and rows[0][0] == "root"
    finally:
        coord.stop()


def test_worker_status_reports_memory():
    from trino_tpu.server.worker import WorkerServer
    w = WorkerServer("mem-w0", "http://127.0.0.1:1",
                     announce_interval_s=30).start()
    try:
        with urlopen(f"{w.uri}/v1/status", timeout=5) as r:
            body = json.loads(r.read())
        assert body["memory"]["pool"] == "general"
        assert "reserved" in body["memory"]
        assert "outputBufferBytes" in body["memory"]
    finally:
        w.stop()
