"""sqlite3-based correctness oracle.

Reference pattern: Trino checks TPC-H results against the H2 database loaded
with the same data (testing/trino-testing/.../H2QueryRunner.java:92). We load
the generated TableData into sqlite3 and run a dialect-translated query.

Dialect translation handles the TPC-H subset:
- DATE 'x' literals (folding +/- INTERVAL arithmetic into a plain literal)
- EXTRACT(YEAR FROM x) -> CAST(strftime('%Y', x) AS INTEGER)
- decimals load as REAL; comparisons use tolerances
"""

from __future__ import annotations

import datetime
import re
import sqlite3
from typing import Iterable, List

import numpy as np

from trino_tpu.connectors.tpch.datagen import TableData
from trino_tpu.types import TypeKind


def _add_months(d: datetime.date, n: int) -> datetime.date:
    y, m = divmod((d.year * 12 + d.month - 1) + n, 12)
    # clamp day like SQL engines do
    for day in range(d.day, 27, -1):
        try:
            return datetime.date(y, m + 1, day)
        except ValueError:
            continue
    return datetime.date(y, m + 1, min(d.day, 28))


def translate(sql: str) -> str:
    """Trino dialect -> sqlite dialect for the supported subset."""

    def fold_interval(m):
        base = datetime.date.fromisoformat(m.group(1))
        sign = 1 if m.group(2) == '+' else -1
        n = int(m.group(3)) * sign
        unit = m.group(4).lower()
        if unit.startswith('year'):
            out = _add_months(base, 12 * n)
        elif unit.startswith('month'):
            out = _add_months(base, n)
        else:
            out = base + datetime.timedelta(days=n)
        return f"'{out.isoformat()}'"

    sql = re.sub(
        r"DATE\s+'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*INTERVAL\s+'(\d+)'\s+"
        r"(YEAR|MONTH|DAY)S?",
        fold_interval, sql, flags=re.IGNORECASE)
    sql = re.sub(r"DATE\s+'(\d{4}-\d{2}-\d{2})'", r"'\1'", sql,
                 flags=re.IGNORECASE)
    sql = re.sub(r"EXTRACT\s*\(\s*YEAR\s+FROM\s+([a-zA-Z_][\w.]*)\s*\)",
                 r"CAST(strftime('%Y', \1) AS INTEGER)", sql,
                 flags=re.IGNORECASE)
    return sql


class _Var:
    """Welford variance aggregate for sqlite (it ships none)."""
    samp = True
    sqrt = False

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def step(self, x):
        if x is None:
            return
        self.n += 1
        d = x - self.mean
        self.mean += d / self.n
        self.m2 += d * (x - self.mean)

    def finalize(self):
        denom = (self.n - 1) if self.samp else self.n
        if denom <= 0:
            return None
        v = self.m2 / denom
        return v ** 0.5 if self.sqrt else v


class _VarPop(_Var):
    samp = False


class _Stddev(_Var):
    sqrt = True


class _StddevPop(_Var):
    samp = False
    sqrt = True


def register_stats_functions(conn: sqlite3.Connection) -> None:
    for name, cls in [("var_samp", _Var), ("variance", _Var),
                      ("var_pop", _VarPop), ("stddev", _Stddev),
                      ("stddev_samp", _Stddev),
                      ("stddev_pop", _StddevPop)]:
        conn.create_aggregate(name, 1, cls)


def load_oracle(tables: Iterable[TableData]) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    register_stats_functions(conn)
    for t in tables:
        cols = []
        for f in t.schema:
            k = f.dtype.kind
            if k is TypeKind.VARCHAR or k is TypeKind.DATE:
                cols.append(f"{f.name} TEXT")
            elif k in (TypeKind.DOUBLE, TypeKind.DECIMAL):
                cols.append(f"{f.name} REAL")
            else:
                cols.append(f"{f.name} INTEGER")
        conn.execute(f"CREATE TABLE {t.name} ({', '.join(cols)})")
        host_cols = []
        for f, arr in zip(t.schema, t.columns):
            k = f.dtype.kind
            if k is TypeKind.VARCHAR:
                pool = np.array(f.dictionary, dtype=object)
                host_cols.append(pool[np.asarray(arr)])
            elif k is TypeKind.DATE:
                base = np.datetime64('1970-01-01')
                host_cols.append((base + np.asarray(arr)).astype(str))
            elif k is TypeKind.DECIMAL:
                host_cols.append(np.asarray(arr) / (10 ** f.dtype.scale))
            else:
                host_cols.append(np.asarray(arr))
        if t.valids is not None:
            for j, v in enumerate(t.valids):
                if v is None:
                    continue
                col = np.asarray(host_cols[j], dtype=object)
                col[~np.asarray(v)] = None
                host_cols[j] = col
        rows = list(zip(*[c.tolist() for c in host_cols]))
        ph = ", ".join("?" * len(t.schema))
        conn.executemany(f"INSERT INTO {t.name} VALUES ({ph})", rows)
        # surrogate-key indexes keep sqlite's nested-loop plans tractable
        # on star-join benchmark queries
        for f in t.schema:
            if f.name.endswith("_sk") or f.name.endswith("key"):
                conn.execute(f"CREATE INDEX IF NOT EXISTS "
                             f"idx_{t.name}_{f.name} ON {t.name}({f.name})")
    conn.execute("ANALYZE")
    conn.commit()
    return conn


def oracle_query(conn: sqlite3.Connection, sql: str) -> List[tuple]:
    return conn.execute(translate(sql)).fetchall()


def assert_rows_match(got: List[tuple], want: List[tuple],
                      rel_tol: float = 1e-6, abs_tol: float = 1e-4,
                      ordered: bool = True) -> None:
    if not ordered:
        got = sorted(got, key=repr)
        want = sorted(want, key=repr)
    assert len(got) == len(want), \
        f"row count mismatch: got {len(got)}, want {len(want)}\n" \
        f"got[:5]={got[:5]}\nwant[:5]={want[:5]}"
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"row {i} arity: {g} vs {w}"
        for j, (a, b) in enumerate(zip(g, w)):
            if isinstance(a, float) or isinstance(b, float):
                a_f, b_f = float(a), float(b)
                ok = abs(a_f - b_f) <= max(abs_tol, rel_tol * max(
                    abs(a_f), abs(b_f)))
                assert ok, f"row {i} col {j}: {a_f} != {b_f}"
            else:
                assert a == b, f"row {i} col {j}: {a!r} != {b!r}"
