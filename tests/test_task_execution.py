"""Worker-side task execution tests.

Reference pattern: tasks are created on workers over HTTP and execute plan
fragments against splits (server/TaskResource.java:146,
execution/SqlTaskManager.java:491); the scheduler reassigns splits when a
worker dies mid-query (EventDrivenFaultTolerantQueryScheduler.java:206);
results must be identical to single-node execution
(BaseFailureRecoveryTest.java:85's assertion).
"""

import time

import pytest

from trino_tpu.client.client import Client
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.worker import WorkerServer

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS q, count(*) AS c
FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate, l_orderkey
LIMIT 10
"""

CONCAT_Q = ("SELECT l_orderkey, l_quantity FROM lineitem "
            "WHERE l_shipdate > DATE '1998-11-01'")


@pytest.fixture()
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    # tiny-scale splits so every table distributes across workers
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"worker-{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)
    yield coord, workers, session
    for w in workers:
        w.stop()
    coord.stop()


def _local_rows(session, sql):
    return session.execute(sql).rows


def test_tasks_execute_on_workers(cluster):
    coord, workers, session = cluster
    want = _local_rows(session, Q1)
    client = Client(coord.uri, user="test")
    r = client.execute(Q1)
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
    # the work actually ran worker-side
    ran = sum(w.task_manager.tasks_run for w in workers)
    assert ran >= 3, f"expected tasks on every worker, got {ran}"
    assert coord.state.scheduler.stats["queries"] >= 1


def test_join_query_distributes(cluster):
    coord, workers, session = cluster
    want = _local_rows(session, Q3)
    client = Client(coord.uri, user="test")
    r = client.execute(Q3)
    assert r.state == "FINISHED"
    assert len(r.rows) == len(want)
    for got_row, want_row in zip(r.rows, want):
        assert tuple(got_row) == tuple(_json_vals(want_row))
    assert sum(w.task_manager.tasks_run for w in workers) >= 3


def test_local_fallback_is_reported(cluster):
    """A query the stage scheduler declines must say WHY in its query
    info instead of silently running local (round-3 verdict weak #5;
    the reference surfaces this as coordinator-only plan info)."""
    import json
    from urllib.request import urlopen
    coord, workers, session = cluster
    client = Client(coord.uri, user="test")
    # nation (25 rows) is below any split threshold -> local fallback
    r = client.execute("SELECT count(*) FROM nation")
    assert r.state == "FINISHED"
    tq = [q for q in coord.state.tracker.all()
          if "nation" in q.sql][-1]
    assert tq.distributed is False
    assert tq.fallback_reason is not None
    assert "split_rows" in tq.fallback_reason
    # surfaced over REST query info too
    with urlopen(f"{coord.uri}/v1/query/{tq.query_id}") as resp:
        info = json.loads(resp.read().decode())
    assert info["fallbackReason"] == tq.fallback_reason
    assert info["distributed"] is False
    # distributed queries carry no reason
    client.execute(Q1)
    tq1 = [q for q in coord.state.tracker.all()
           if "l_returnflag" in q.sql][-1]
    assert tq1.distributed is True and tq1.fallback_reason is None


def test_hll_distributes(cluster):
    """approx_distinct's HLL partial rows merge across worker tasks the
    same way other mergeable states do (bounded per-task state)."""
    coord, workers, session = cluster
    want = _local_rows(
        session, "SELECT count(DISTINCT l_suppkey) FROM lineitem")[0][0]
    client = Client(coord.uri, user="test")
    r = client.execute("SELECT approx_distinct(l_suppkey) FROM lineitem")
    assert r.state == "FINISHED"
    got = r.rows[0][0]
    # 2.3% is asymptotic; tiny-scale suppkey has only ~100 distinct
    # values, where a few-register absolute floor dominates
    assert abs(got - want) <= max(0.023 * want, 5)
    assert sum(w.task_manager.tasks_run for w in workers) >= 3


def test_concat_mode_distributes(cluster):
    coord, workers, session = cluster
    want = sorted(tuple(_json_vals(r)) for r in
                  _local_rows(session, CONCAT_Q))
    client = Client(coord.uri, user="test")
    r = client.execute(CONCAT_Q)
    assert r.state == "FINISHED"
    assert sorted(tuple(row) for row in r.rows) == want


def test_worker_death_reassigns_splits(cluster):
    """Kill one worker's task intake mid-cluster: its splits must land on
    survivors and the query still returns identical results."""
    coord, workers, session = cluster
    want = _local_rows(session, Q1)
    workers[0].fail_tasks = True          # injected TASK failure
    client = Client(coord.uri, user="test")
    r = client.execute(Q1)
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
    assert coord.state.scheduler.stats["task_retries"] >= 1
    # the failed node is out of the inventory until it re-announces
    workers[0].fail_tasks = False


def test_worker_results_failure_retries(cluster):
    coord, workers, session = cluster
    want = _local_rows(session, Q1)
    workers[1].fail_results = True        # injected GET-results failure
    client = Client(coord.uri, user="test")
    r = client.execute(Q1)
    workers[1].fail_results = False
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]


def test_all_workers_dead_degrades_to_local(cluster):
    """Whole-fleet failure: the coordinator degrades to local execution
    and still answers (the single-controller can always run the plan)."""
    coord, workers, session = cluster
    want = _local_rows(session, Q1)
    for w in workers:
        w.fail_tasks = True
    client = Client(coord.uri, user="test")
    r = client.execute(Q1)
    for w in workers:
        w.fail_tasks = False
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]


def _json_vals(row):
    out = []
    for v in row:
        if v is None or isinstance(v, (int, float, str, bool)):
            out.append(v)
        else:
            out.append(str(v))
    return out


def test_durable_exchange_resumes_from_spool(cluster):
    """FTE recovery at task granularity: a failure at the stage boundary
    (after source tasks spooled their outputs) triggers a QUERY retry,
    which must consume the spool instead of re-running tasks — the
    DeduplicatingDirectExchangeBuffer + FileSystemExchangeManager shape."""
    from trino_tpu.server.failureinjector import FailureInjector
    coord, workers, session = cluster
    sched = coord.state.scheduler
    sched.spool.clear()
    coord.state.dispatcher.retry_policy = "QUERY"
    injector = FailureInjector()
    injector.inject("STAGE_BOUNDARY", times=1)
    sched.failure_injector = injector
    ran_before = sum(w.task_manager.tasks_run for w in workers)
    want = _local_rows(session, Q1)
    try:
        client = Client(coord.uri, user="test")
        r = client.execute(Q1)
    finally:
        sched.failure_injector = None
        coord.state.dispatcher.retry_policy = "NONE"
    assert injector.injected_count == 1
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
    # the retry consumed spooled outputs: no new task executions
    ran_after = sum(w.task_manager.tasks_run for w in workers)
    first_attempt_tasks = ran_after - ran_before
    assert sched.stats["spool_hits"] >= first_attempt_tasks >= 1


PART_Q = """
SELECT o_orderpriority, count(*) AS c, sum(l_quantity) AS q
FROM lineitem, orders
WHERE l_orderkey = o_orderkey AND o_orderdate >= DATE '1996-01-01'
GROUP BY o_orderpriority ORDER BY o_orderpriority
"""


def test_partitioned_join_across_workers(cluster):
    """Worker<->worker partitioned exchange (round-4 verdict missing #1):
    both join sides hash-repartition by the join key into P buffers; P
    exchange-consumer tasks each pull their partition from EVERY
    upstream task and join/partial-aggregate it; the coordinator merges.
    Results must be oracle-identical to local execution. Reference:
    PipelinedQueryScheduler.java:164 FIXED_HASH_DISTRIBUTION,
    DirectExchangeClient.java:56."""
    coord, workers, session = cluster
    want = _local_rows(session, PART_Q)
    session.properties["join_distribution_type"] = "partitioned"
    try:
        client = Client(coord.uri, user="test")
        r = client.execute(PART_Q)
    finally:
        session.properties["join_distribution_type"] = "auto"
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
    sched = coord.state.scheduler
    assert sched.stats.get("partitioned_joins", 0) >= 1
    # exchange-consumer tasks actually ran (tasks carrying sources)
    consumers = [t for w in workers
                 for t in w.task_manager.tasks.values()
                 if t.sources is not None]
    assert len(consumers) == len(workers)
    assert all(t.state == "FINISHED" for t in consumers)
    # producer tasks partitioned their output into multiple buffers
    producers = [t for w in workers
                 for t in w.task_manager.tasks.values()
                 if t.partition is not None]
    assert producers and any(len(t.acked) + len(t.buffers) > 1
                             for t in producers)


def test_partitioned_left_join_keeps_unmatched(cluster):
    """NULL-extended probe rows survive the hash routing (left join rows
    with no match are emitted by whichever partition owns their key)."""
    coord, workers, session = cluster
    q = """
    SELECT count(*) AS n, count(o_orderkey) AS matched
    FROM lineitem LEFT JOIN orders
      ON l_orderkey = o_orderkey AND o_orderdate >= DATE '1997-01-01'
    """
    want = _local_rows(session, q)
    session.properties["join_distribution_type"] = "partitioned"
    try:
        client = Client(coord.uri, user="test")
        r = client.execute(q)
    finally:
        session.properties["join_distribution_type"] = "auto"
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]


def test_require_distributed_errors_not_silent(cluster):
    """require_distributed=true turns a cluster decline into an explicit
    error instead of a silent local run (round-4 verdict weak #6)."""
    from trino_tpu.client.client import QueryError
    coord, workers, session = cluster
    session.properties["require_distributed"] = True
    try:
        client = Client(coord.uri, user="test")
        with pytest.raises(QueryError, match="require_distributed"):
            client.execute("SELECT count(*) FROM nation")
    finally:
        session.properties["require_distributed"] = False


def test_partitioned_declines_sort_below_merge(cluster):
    """A Sort/Limit BETWEEN the aggregate and the join must not enter
    the per-partition consumer fragment (it would compute per-partition
    top-N, not global). The partitioned path declines; results stay
    oracle-identical via the fallback paths."""
    coord, workers, session = cluster
    q = """
    SELECT sum(q) FROM (
        SELECT l_quantity AS q FROM lineitem, orders
        WHERE l_orderkey = o_orderkey
        ORDER BY l_quantity DESC LIMIT 10) t
    """
    want = _local_rows(session, q)
    session.properties["join_distribution_type"] = "partitioned"
    before = coord.state.scheduler.stats.get("partitioned_joins", 0)
    try:
        client = Client(coord.uri, user="test")
        r = client.execute(q)
    finally:
        session.properties["join_distribution_type"] = "auto"
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
    assert coord.state.scheduler.stats.get("partitioned_joins", 0) == before


def test_distributed_order_by_merges_sorted_runs(cluster):
    """Sorted-merge exchange (round-4 verdict missing #6): workers sort
    per split; the coordinator n-way merges the runs order-preservingly
    instead of re-sorting (MergeOperator.java's role). Results must be
    identical to local execution."""
    coord, workers, session = cluster
    q = """
    SELECT l_orderkey, l_linenumber, l_extendedprice FROM lineitem
    WHERE l_shipdate > DATE '1998-06-01'
    ORDER BY l_extendedprice DESC, l_orderkey, l_linenumber
    """
    want = _local_rows(session, q)
    client = Client(coord.uri, user="test")
    r = client.execute(q)
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
    tq = [x for x in coord.state.tracker.all() if "1998-06-01" in x.sql][-1]
    assert tq.distributed is True, tq.fallback_reason


def test_distributed_order_by_nulls_and_desc(cluster):
    """NULL placement and DESC keys survive the merge."""
    coord, workers, session = cluster
    q = """
    SELECT o_orderkey, o_clerk FROM orders
    ORDER BY o_custkey DESC, o_orderkey
    LIMIT 10000
    """
    # LIMIT sits above the Sort -> local fallback is fine for this one;
    # use the unlimited variant for the distributed assertion
    q2 = """
    SELECT o_orderkey, o_custkey FROM orders
    ORDER BY o_custkey DESC, o_orderkey
    """
    want = _local_rows(session, q2)
    client = Client(coord.uri, user="test")
    r = client.execute(q2)
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == \
        [tuple(_json_vals(row)) for row in want]
