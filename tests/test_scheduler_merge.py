"""Coordinator-side merge of sorted runs (server/scheduler.py):
the vectorized np.lexsort merge must reproduce the priority-queue
semantics exactly — key order with nulls-first/last and descending
handled per key, ties broken by run order then within-run order — and
must also handle non-numeric sort keys (the old per-row heapq negated
values for descending, which assumed a numeric dtype)."""

import heapq
from types import SimpleNamespace

import numpy as np
import pytest

import trino_tpu.server.scheduler as sched


def _key(index, ascending=True, nulls_first=False):
    return SimpleNamespace(index=index, ascending=ascending,
                           nulls_first=nulls_first)


def _sort_node(*keys):
    return SimpleNamespace(keys=list(keys))


def _merge(monkeypatch, sort_node, runs):
    """Feed (arrays, valids) runs straight through (pages are already
    decoded in these unit tests)."""
    import trino_tpu.server.tasks as tasks
    monkeypatch.setattr(tasks, "decode_columns", lambda p: p)
    return sched._merge_sorted_runs(sort_node, runs)


def _heapq_reference(keys, runs):
    """The old per-row priority-queue merge (rank-coded so it also
    works for strings), as the semantics oracle."""
    pool = {}
    for k in keys:
        vals = np.concatenate([r[0][k.index] for r in runs])
        pool[k.index] = {v: i for i, v in enumerate(sorted(set(vals)))}

    def run_iter(ri, arrs, vals):
        for i in range(len(arrs[0])):
            kt = []
            for k in keys:
                ok = bool(vals[k.index][i])
                nr = (0 if k.nulls_first else 1) if not ok else \
                    (1 if k.nulls_first else 0)
                v = pool[k.index][arrs[k.index][i]] if ok else 0
                if not k.ascending and ok:
                    v = -v
                kt.append((nr, v))
            yield tuple(kt), ri, i
    order = list(heapq.merge(*[run_iter(ri, a, v)
                               for ri, (a, v) in enumerate(runs)]))
    out_rows = []
    for _, ri, i in order:
        arrs, vals = runs[ri]
        out_rows.append(tuple(
            (arrs[j][i], bool(vals[j][i])) for j in range(len(arrs))))
    return out_rows


def _rows(arrays, valids):
    return [tuple((arrays[j][i], bool(valids[j][i]))
                  for j in range(len(arrays)))
            for i in range(len(arrays[0]))]


def _make_runs(rng, n_runs, n, keyspec, dtype=np.int64, with_nulls=True):
    runs = []
    for _ in range(n_runs):
        k = rng.integers(-20, 20, n).astype(dtype)
        v = rng.integers(0, 1000, n).astype(np.int64)
        kv = rng.random(n) > 0.15 if with_nulls else np.ones(n, bool)
        order = np.lexsort(_levels_for(keyspec, k, kv))
        runs.append(([k[order], v[order]],
                     [kv[order], np.ones(n, bool)]))
    return runs


def _levels_for(keyspec, k, kv):
    codes = np.unique(k, return_inverse=True)[1].astype(np.int64)
    if not keyspec.ascending:
        codes = -codes
    codes = np.where(kv, codes, 0)
    nr = np.where(kv, 1 if keyspec.nulls_first else 0,
                  0 if keyspec.nulls_first else 1)
    return [codes, nr]


@pytest.mark.parametrize("asc,nf", [(True, False), (True, True),
                                    (False, False), (False, True)])
def test_merge_matches_heapq_reference(monkeypatch, asc, nf):
    rng = np.random.default_rng(hash((asc, nf)) % (1 << 31))
    key = _key(0, ascending=asc, nulls_first=nf)
    runs = _make_runs(rng, 3, 50, key)
    arrays, valids = _merge(monkeypatch, _sort_node(key), runs)
    assert _rows(arrays, valids) == _heapq_reference([key], runs)


def test_merge_non_numeric_descending(monkeypatch):
    """Object-dtype string keys can't be negated; rank codes sort them
    descending correctly."""
    key = _key(0, ascending=False)
    r1 = ([np.array(["apple", "mango", "zebra"], dtype=object)[::-1],
           np.array([1, 2, 3])],
          [np.ones(3, bool), np.ones(3, bool)])
    r2 = ([np.array(["kiwi", "pear"], dtype=object)[::-1],
           np.array([4, 5])],
          [np.ones(2, bool), np.ones(2, bool)])
    arrays, valids = _merge(monkeypatch, _sort_node(key), [r1, r2])
    assert list(arrays[0]) == ["zebra", "pear", "mango", "kiwi",
                               "apple"]


def test_merge_stable_run_order_tiebreak(monkeypatch):
    """Equal keys must come out in run order, runs keeping their
    internal order — heapq.merge's stability contract."""
    key = _key(0)
    r1 = ([np.array([5, 5, 5]), np.array([10, 11, 12])],
          [np.ones(3, bool), np.ones(3, bool)])
    r2 = ([np.array([5, 5]), np.array([20, 21])],
          [np.ones(2, bool), np.ones(2, bool)])
    arrays, _ = _merge(monkeypatch, _sort_node(key), [r1, r2])
    assert list(arrays[1]) == [10, 11, 12, 20, 21]


def test_merge_two_keys_mixed_directions(monkeypatch):
    rng = np.random.default_rng(9)
    k1 = _key(0, ascending=True, nulls_first=True)
    k2 = _key(1, ascending=False, nulls_first=False)
    runs = []
    for _ in range(3):
        n = 40
        a = rng.integers(0, 5, n).astype(np.int64)
        b = rng.integers(0, 7, n).astype(np.int64)
        v = rng.integers(0, 100, n).astype(np.int64)
        av = rng.random(n) > 0.2
        bv = rng.random(n) > 0.2
        order = np.lexsort(_levels_for(k2, b, bv) +
                           _levels_for(k1, a, av))
        runs.append(([a[order], b[order], v[order]],
                     [av[order], bv[order], np.ones(n, bool)]))
    arrays, valids = _merge(monkeypatch, _sort_node(k1, k2), runs)
    assert _rows(arrays, valids) == _heapq_reference([k1, k2], runs)


def test_merge_empty_and_unequal_runs(monkeypatch):
    key = _key(0)
    r1 = ([np.array([], dtype=np.int64), np.array([], dtype=np.int64)],
          [np.array([], dtype=bool), np.array([], dtype=bool)])
    r2 = ([np.array([3, 7]), np.array([1, 2])],
          [np.ones(2, bool), np.ones(2, bool)])
    arrays, valids = _merge(monkeypatch, _sort_node(key), [r1, r2])
    assert list(arrays[0]) == [3, 7]
    assert sched._merge_sorted_runs(_sort_node(key), []) == ([], [])
