"""Cross-process decision-cache persistence.

The executor's runtime decisions (row counts, min/max stats, LUT
validations) are pure functions of deterministic plan subtrees, keyed by
canonical wire-form hashes — so they persist to disk and a FRESH process
replays them: identical capacities/layouts mean the persistent XLA code
cache hits too, collapsing cold start to ingest + cached-program load.
Reference analog: the long-lived JVM keeping ExpressionCompiler output
warm across queries (sql/gen/ExpressionCompiler.java:38).
"""

import os

import pytest

from trino_tpu.exec.session import Session


@pytest.fixture
def decision_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_DATA_CACHE", str(tmp_path))
    monkeypatch.setenv("TRINO_TPU_DECISION_CACHE", "1")
    return tmp_path


Q = ("SELECT l_orderkey, sum(l_quantity) FROM lineitem "
     "GROUP BY l_orderkey ORDER BY l_orderkey LIMIT 7")


def test_decisions_persist_and_replay(decision_dir):
    s1 = Session(default_schema="tiny")
    want = s1.execute(Q).rows
    ex1 = s1.executor
    assert ex1._decision_cache                       # something recorded
    assert ex1._decision_dirty is False              # ...and saved
    path = os.path.join(str(decision_dir), "decisions.pkl")
    assert os.path.isfile(path)

    # fresh executor = fresh process stand-in: decisions replay from disk
    s2 = Session(default_schema="tiny")
    got = s2.execute(Q).rows
    assert got == want
    ex2 = s2.executor
    assert ex2._decision_loaded
    # every first-run decision replayed from disk into the fresh process
    for k, v in ex1._decision_cache.items():
        assert ex2._decision_cache.get(k) == v


def test_disk_corruption_is_cold_start(decision_dir):
    path = os.path.join(str(decision_dir), "decisions.pkl")
    with open(path, "wb") as f:
        f.write(b"\x80garbage")
    s = Session(default_schema="tiny")
    assert s.execute(Q).rows                          # no crash


def test_mutable_catalog_never_persists(decision_dir):
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    s.execute("CREATE TABLE m.s.t (x bigint)")
    s.execute("INSERT INTO m.s.t VALUES (1), (2), (3)")
    s.execute("SELECT x, count(*) FROM m.s.t GROUP BY x")
    # memory-connector subtrees have no structure key -> nothing cached
    assert not any("m" in str(k) and k[0] == "agggroups1024"
                   for k in s.executor._decision_cache)
