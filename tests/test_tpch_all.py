"""All 22 TPC-H queries, end-to-end against the sqlite oracle.

Reference pattern: AbstractTestQueries/TpchQueryRunner + H2QueryRunner —
the full TPC-H workload runs on both the engine and an independent SQL
engine over identical data; results must match (SURVEY.md §4.3-4.4, §6).
This exercises the whole stack: parser (WITH, subqueries), planner
(decorrelation to semi/anti/mark joins, correlated scalar aggregation
rewrites, uncorrelated scalar folding, join-graph ordering, OR-conjunct
extraction, distinct aggregates, dictionary substring), and every executor
kernel.
"""

import pytest

pytestmark = pytest.mark.slow

from oracle import assert_rows_match, load_oracle, oracle_query
from tpch_full import QUERIES
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query(session, oracle, qnum):
    sql = QUERIES[qnum]
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.02, ordered=True)
