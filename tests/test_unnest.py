"""ARRAY type + UNNEST tests.

Reference: operator/unnest/UnnestOperator.java:42 + spi/type/ArrayType.
Arrays follow the engine's pool-id discipline (types.py): device carries
int32 ids, element tuples live host-side — UNNEST/cardinality/contains
are pool transforms like the varchar functions.
"""

from trino_tpu.exec.session import Session


def session():
    return Session(default_schema="tiny")


def test_unnest_literal():
    r = session().execute(
        "SELECT x FROM UNNEST(ARRAY[3, 1, 2]) AS t(x) ORDER BY x")
    assert r.rows == [(1,), (2,), (3,)]


def test_unnest_with_ordinality_preserves_element_order():
    r = session().execute(
        "SELECT x, o FROM UNNEST(ARRAY[30, 10, 20]) "
        "WITH ORDINALITY AS t(x, o) ORDER BY o")
    assert r.rows == [(30, 1), (10, 2), (20, 3)]


def test_unnest_lateral_cross_product():
    r = session().execute(
        "SELECT n_name, x FROM nation, UNNEST(ARRAY['a', 'b']) AS u(x) "
        "WHERE n_nationkey < 2 ORDER BY n_name, x")
    assert r.rows == [("ALGERIA", "a"), ("ALGERIA", "b"),
                      ("ARGENTINA", "a"), ("ARGENTINA", "b")]


def test_unnest_feeds_aggregation():
    r = session().execute(
        "SELECT count(*), sum(x), min(x) "
        "FROM UNNEST(ARRAY[5, 10, 15, 20]) AS t(x)")
    assert r.rows == [(4, 50, 5)]


def test_unnest_varchar_elements():
    r = session().execute(
        "SELECT upper(x) FROM UNNEST(ARRAY['pear', 'fig']) AS t(x) "
        "ORDER BY x")
    assert r.rows == [("FIG",), ("PEAR",)]


def test_unnest_filter_on_element():
    r = session().execute(
        "SELECT x FROM UNNEST(ARRAY[1, 2, 3, 4, 5]) AS t(x) "
        "WHERE x > 3 ORDER BY x")
    assert r.rows == [(4,), (5,)]


def test_array_functions():
    r = session().execute(
        "SELECT cardinality(ARRAY[1, 2, 3]), contains(ARRAY[1, 2], 2), "
        "contains(ARRAY['a', 'b'], 'c')")
    assert r.rows == [(3, True, False)]


def test_empty_array_unnest():
    r = session().execute(
        "SELECT count(*) FROM UNNEST(ARRAY[]) AS t(x)")
    assert r.rows == [(0,)]
