"""Chunked-driver prefetch pipeline tests (round 14).

exec/chunked.py overlaps host decode+stage of chunk k+1 with device
compute of chunk k through a bounded double-buffered worker
(_PrefetchPipeline). The contracts under test:

- prefetch_depth=0 recovers the serial loop exactly (bit-exact rows);
- staged buffers are REVOCABLE memory-pool reservations tagged
  "scan-prefetch": pressure revokes them and the consumer silently
  re-decodes inline — correctness never depends on staging;
- chaos faults injected at the SCAN_PREFETCH point surface on the
  consumer thread as ordinary retryable failures, and the retry is
  bit-exact (0 wrong answers).

The fact cache is disabled throughout: device-resident fact tables
decode nothing per chunk, which bypasses the pipeline by design.
"""

import time

import numpy as np
import pytest

from trino_tpu.batch import batch_from_numpy
from trino_tpu.exec.chunked import _PrefetchPipeline
from trino_tpu.exec.session import Session
from trino_tpu.server.failureinjector import (RAISE, SCAN_PREFETCH,
                                              FailureInjector,
                                              InjectedFailure)

SQL = ("SELECT l_returnflag, count(*) AS c, sum(l_extendedprice) AS s "
       "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag")


@pytest.fixture(scope="module")
def session():
    s = Session(default_schema="tiny")
    s.executor.enable_fact_cache = False     # force per-chunk decode
    s.execute("SET SESSION spill_chunk_rows = 8192")
    return s


def test_depth0_is_serial_and_pipeline_bit_exact(session):
    s = session
    s.execute("SET SESSION prefetch_depth = 0")
    serial = s.execute(SQL).rows
    spans0 = s.executor.chunk_spans
    assert spans0["chunks"] > 1              # the chunked path really ran
    assert spans0["prefetched"] == 0         # depth 0: no pipeline at all

    s.execute("SET SESSION prefetch_depth = 2")
    piped = s.execute(SQL).rows
    spans2 = s.executor.chunk_spans
    assert piped == serial
    assert spans2["prefetched"] == spans2["chunks"]

    # staged-buffer gauge must return to zero after the run
    from trino_tpu.metrics import SCAN_PREFETCH_BUFFERS
    assert SCAN_PREFETCH_BUFFERS.value() == 0


def test_chaos_fault_in_prefetch_is_retryable(session):
    s = session
    s.execute("SET SESSION prefetch_depth = 2")
    want = s.execute(SQL).rows
    inj = FailureInjector(seed=3)
    inj.inject(SCAN_PREFETCH, times=1, fault=RAISE)
    s.executor.failure_injector = inj
    try:
        with pytest.raises(InjectedFailure):
            s.execute(SQL)
        got = s.execute(SQL).rows            # retry: injection exhausted
    finally:
        s.executor.failure_injector = None
    assert got == want


def test_staged_buffers_revocable_under_pressure(session):
    ex = session.executor
    starts = [0, 8, 16]

    def decode(start):
        return batch_from_numpy([np.arange(start, start + 8,
                                           dtype=np.int64)])

    pipe = _PrefetchPipeline(ex, starts, decode, depth=len(starts))
    try:
        deadline = time.time() + 5
        while len(pipe._staged) < len(starts) and time.time() < deadline:
            time.sleep(0.01)
        assert len(pipe._staged) == len(starts)
        # visible in the pool snapshot (the /v1/memory payload) as a
        # tagged revocable holder
        snap = ex.pool.snapshot()
        assert snap["revocable_holders"].get("scan-prefetch", 0) > 0
        freed = ex.pool.request_revocation(1 << 40)
        assert freed > 0
        assert not pipe._staged
        # the consumer re-decodes revoked chunks inline — same data
        for st in starts:
            got = np.asarray(pipe.next(st).columns[0].data)[:8]
            np.testing.assert_array_equal(
                got, np.arange(st, st + 8, dtype=np.int64))
    finally:
        pipe.close()
    assert ex.pool.snapshot()["revocable_holders"].get(
        "scan-prefetch", 0) == 0


def test_prefetch_composes_with_zone_pruning(session):
    """Chunk skipping (zone maps) and the pipeline stack: the pipeline
    only decodes the SURVIVING chunk list, and results stay bit-exact
    against serial-unpruned."""
    s = session
    s.execute("SET SESSION zone_map_rows = 8192")
    sql = ("SELECT count(*) AS c, sum(l_quantity) AS q FROM lineitem "
           "WHERE l_orderkey < 25000")
    s.execute("SET SESSION enable_zone_map_pruning = false")
    s.execute("SET SESSION prefetch_depth = 0")
    base = s.execute(sql).rows
    chunks_all = s.executor.chunk_spans["chunks"]
    s.execute("SET SESSION enable_zone_map_pruning = true")
    s.execute("SET SESSION prefetch_depth = 2")
    got = s.execute(sql).rows
    spans = s.executor.chunk_spans
    assert got == base
    assert spans["chunks"] < chunks_all      # zones skipped whole chunks
    assert spans["prefetched"] == spans["chunks"]
    s.execute("SET SESSION enable_zone_map_pruning = true")
