"""High-concurrency serving layer (server/serving.py, exec/router.py).

Round-11 acceptance surface: plan-cache hit/miss + eviction, result-cache
correctness including catalog-version invalidation after a write, router
decisions on small vs scan-heavy plans (forced via session property
overrides), micro-batch coalescing returning per-client-correct rows, and
a concurrent-mix throughput smoke (bench.py --concurrency with a small
client count).
"""

import threading
import time

import pytest

from trino_tpu.client.client import Client, QueryError
from trino_tpu.exec.session import Session
from trino_tpu.metrics import (MICROBATCH_BATCHES, MICROBATCH_QUERIES,
                               PLAN_CACHE_EVICTIONS, PLAN_CACHE_HITS,
                               PLAN_CACHE_MISSES, RESULT_CACHE_HITS,
                               RESULT_CACHE_INVALIDATIONS,
                               RESULT_CACHE_MISSES, ROUTER_DECISIONS)
from trino_tpu.server.coordinator import CoordinatorServer


@pytest.fixture
def coord():
    session = Session(default_schema="tiny")
    c = CoordinatorServer(session, max_concurrency=16).start()
    # deterministic router verdicts: the persistent query-history ring
    # accumulates across pytest sessions, and its medians would override
    # the row-estimate path these tests assert on
    c.state.dispatcher.serving.history = None
    session.history_store = None
    yield c
    c.stop()


def _client(coord, user="serve"):
    return Client(coord.uri, user=user, poll_interval_s=0.005)


# ---------------------------------------------------------------------------
# plan cache
# ---------------------------------------------------------------------------

def test_plan_cache_hit_miss(coord):
    client = _client(coord)
    sql = "SELECT count(*) FROM supplier"
    m0, h0 = PLAN_CACHE_MISSES.value(), PLAN_CACHE_HITS.value()
    first = client.execute(sql).rows
    assert PLAN_CACHE_MISSES.value() > m0
    h1 = PLAN_CACHE_HITS.value()
    second = client.execute(sql).rows
    assert PLAN_CACHE_HITS.value() > h1
    assert first == second
    # formatting differences share the normalized fingerprint: still hits
    h2 = PLAN_CACHE_HITS.value()
    third = client.execute("select   COUNT(*)  from SUPPLIER;").rows
    assert PLAN_CACHE_HITS.value() > h2
    assert third == first


def test_plan_cache_lru_and_byte_eviction():
    from trino_tpu.server.serving import PlanCache, PlanEntry

    def entry(i, weight):
        return PlanEntry(sql=f"q{i}", fingerprint=f"fp{i}", stmt=None,
                         rel=None, root=None, cacheable=True,
                         point_shape=None, weight=weight)

    e0 = PLAN_CACHE_EVICTIONS.value()
    cache = PlanCache(max_entries=3, max_bytes=10_000)
    for i in range(4):
        cache.put((f"fp{i}",), entry(i, 100))
    assert len(cache) == 3                       # LRU entry cap
    assert cache.get(("fp0",)) is None           # oldest evicted
    assert cache.get(("fp3",)) is not None
    assert PLAN_CACHE_EVICTIONS.value() > e0
    # byte cap: one huge entry evicts the rest but itself survives
    cache.put(("big",), entry(9, 9_999))
    assert cache.get(("big",)) is not None
    assert len(cache) == 1


def test_plan_cache_invalidated_by_catalog_version(coord):
    """DDL bumps the catalog version, which is part of the plan-cache
    key: the stale plan is simply never looked up again."""
    client = _client(coord)
    client.execute("CREATE TABLE memory.s.pc (x bigint)")
    client.execute("INSERT INTO memory.s.pc VALUES (1)")
    assert client.execute("SELECT count(*) FROM memory.s.pc"
                          ).rows == [[1]]
    client.execute("INSERT INTO memory.s.pc VALUES (2)")
    assert client.execute("SELECT count(*) FROM memory.s.pc"
                          ).rows == [[2]]


def test_plan_cache_system_table(coord):
    client = _client(coord)
    client.execute("SELECT count(*) FROM region")
    rows = client.execute(
        "SELECT fingerprint, hits FROM system.runtime.plan_cache").rows
    assert rows, "plan cache system table should list cached plans"


# ---------------------------------------------------------------------------
# result cache
# ---------------------------------------------------------------------------

def test_result_cache_hit_and_bit_exact(coord):
    client = _client(coord)
    client.execute("SET SESSION enable_result_cache = true")
    sql = "SELECT r_regionkey, r_name FROM region ORDER BY r_regionkey"
    uncached = client.execute(sql).rows            # populates
    h0 = RESULT_CACHE_HITS.value()
    cached = client.execute(sql).rows              # served from cache
    assert RESULT_CACHE_HITS.value() > h0
    assert cached == uncached                      # bit-exact
    info = client.query_info(client.execute(sql).query_id)
    assert info["route"] == "cache"


def test_result_cache_invalidated_by_write(coord):
    client = _client(coord)
    client.execute("CREATE TABLE memory.s.rc (k bigint, v bigint)")
    client.execute("INSERT INTO memory.s.rc VALUES (1, 10), (2, 20)")
    client.execute("SET SESSION enable_result_cache = true")
    sql = "SELECT sum(v) FROM memory.s.rc"
    assert client.execute(sql).rows == [[30]]
    assert client.execute(sql).rows == [[30]]      # hit
    i0 = RESULT_CACHE_INVALIDATIONS.value()
    client.execute("INSERT INTO memory.s.rc VALUES (3, 30)")
    # post-write rerun: the catalog version moved, the stale page must
    # be dropped (counted) and the fresh answer returned
    assert client.execute(sql).rows == [[60]]
    assert RESULT_CACHE_INVALIDATIONS.value() > i0
    # UPDATE/DELETE invalidate too
    client.execute("UPDATE memory.s.rc SET v = 0 WHERE k = 1")
    assert client.execute(sql).rows == [[50]]
    client.execute("DELETE FROM memory.s.rc WHERE k = 2")
    assert client.execute(sql).rows == [[30]]


def test_result_cache_never_caches_system_tables(coord):
    """system.runtime state changes without any catalog-version bump:
    those plans are marked non-cacheable and always execute."""
    client = _client(coord)
    client.execute("SET SESSION enable_result_cache = true")
    sql = "SELECT count(*) FROM system.runtime.queries"
    a = client.execute(sql).rows[0][0]
    b = client.execute(sql).rows[0][0]
    # every execution adds a tracked query, so a cached (stale) page
    # would return the SAME count twice
    assert b > a


def test_result_cache_disabled_by_default(coord):
    client = _client(coord)
    h0 = RESULT_CACHE_HITS.value() + RESULT_CACHE_MISSES.value()
    client.execute("SELECT count(*) FROM region")
    client.execute("SELECT count(*) FROM region")
    assert RESULT_CACHE_HITS.value() + RESULT_CACHE_MISSES.value() == h0


# ---------------------------------------------------------------------------
# cost router
# ---------------------------------------------------------------------------

def test_router_forced_host_and_device(coord):
    client = _client(coord)
    sql = "SELECT count(*) FROM nation"
    client.execute("SET SESSION routing_mode = host")
    h0 = ROUTER_DECISIONS.value(target="host")
    r = client.execute(sql)
    assert ROUTER_DECISIONS.value(target="host") > h0
    host_rows = r.rows
    assert client.query_info(r.query_id)["route"] == "host"
    client.execute("SET SESSION routing_mode = device")
    d0 = ROUTER_DECISIONS.value(target="device")
    r = client.execute(sql)
    assert ROUTER_DECISIONS.value(target="device") > d0
    assert client.query_info(r.query_id)["route"] == "device"
    assert r.rows == host_rows                     # bit-exact across routes


def test_router_auto_small_vs_scan_heavy(coord):
    client = _client(coord)
    # warm stats so the estimator sees materialized row counts
    client.execute("SELECT count(*) FROM nation")
    client.execute("SET SESSION router_host_max_rows = 1000")
    r = client.execute("SELECT n_name FROM nation WHERE n_nationkey = 7")
    assert client.query_info(r.query_id)["route"] == "host"
    # lineitem tiny is ~60k rows > the 1k threshold -> device
    r = client.execute(
        "SELECT count(*) FROM lineitem WHERE l_quantity > 49")
    info = client.query_info(r.query_id)
    assert info["route"] == "device"
    assert "scanned rows" in info["routeReason"]


def test_router_grouped_aggregation_goes_device(coord):
    client = _client(coord)
    client.execute("SET SESSION routing_mode = host")   # forced, but...
    r = client.execute(
        "SELECT r_regionkey, count(*) FROM region GROUP BY r_regionkey")
    # ...grouped aggregation is not host-eligible: falls back to device
    assert client.query_info(r.query_id)["route"] == "device"


def test_explain_shows_routing_decision(coord):
    client = _client(coord)
    rows = client.execute("EXPLAIN SELECT count(*) FROM region").rows
    text = "\n".join(r[0] for r in rows)
    assert "routing:" in text


def test_host_path_bit_exact_vs_device():
    """The numpy host path must decode bit-identically to the device
    executor across types: ints, decimals, doubles, varchar dictionary
    codes, dates, NULL handling, sorts and global aggregates."""
    session = Session(default_schema="tiny")
    queries = [
        "SELECT count(*), sum(l_quantity), min(l_shipdate), "
        "max(l_discount) FROM lineitem",
        "SELECT n_nationkey, n_name FROM nation "
        "WHERE n_regionkey = 2 ORDER BY n_nationkey",
        "SELECT r_name FROM region WHERE r_regionkey >= 1 "
        "ORDER BY r_name DESC LIMIT 3",
        "SELECT s_suppkey + 1, s_acctbal * 2 FROM supplier "
        "WHERE s_nationkey IN (1, 3) ORDER BY s_suppkey LIMIT 5",
        "SELECT count(*) FROM orders "
        "WHERE o_orderdate >= DATE '1996-01-01'",
    ]
    from trino_tpu.exec.router import host_supported, run_host
    from trino_tpu.planner.optimizer import prune_plan
    for sql in queries:
        stmt, rel = session.plan(sql)
        root = prune_plan(rel.node)
        assert host_supported(root) is None, sql
        host = run_host(session, rel, root, time.monotonic())
        device = session.execute(sql)
        assert host.rows == device.rows, sql
        assert host.column_names == device.column_names


def test_host_unsupported_reports_reason():
    session = Session(default_schema="tiny")
    from trino_tpu.exec.router import host_supported
    from trino_tpu.planner.optimizer import prune_plan
    _, rel = session.plan(
        "SELECT c_name FROM customer JOIN nation "
        "ON c_nationkey = n_nationkey")
    reason = host_supported(prune_plan(rel.node))
    assert reason is not None and "JoinNode" in reason


# ---------------------------------------------------------------------------
# micro-batching
# ---------------------------------------------------------------------------

def test_microbatch_coalesces_and_demuxes_per_client(coord):
    client = _client(coord)
    session = coord.state.session
    oracle = {k: session.execute(
        f"SELECT n_name, n_regionkey FROM nation WHERE n_nationkey = {k}"
    ).rows for k in range(8)}
    client.execute("SET SESSION enable_microbatch = true")
    client.execute("SET SESSION microbatch_window_ms = 40")
    q0, b0 = MICROBATCH_QUERIES.value(), MICROBATCH_BATCHES.value()
    results = {}

    def one(k):
        c = _client(coord, user=f"mb{k}")
        results[k] = c.execute(
            f"SELECT n_name, n_regionkey FROM nation "
            f"WHERE n_nationkey = {k}").rows

    threads = [threading.Thread(target=one, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for k in range(8):
        assert [tuple(r) for r in results[k]] == \
            [tuple(r) for r in oracle[k]], f"key {k}"
    dq = MICROBATCH_QUERIES.value() - q0
    db = MICROBATCH_BATCHES.value() - b0
    assert db >= 1, "no gather window flushed"
    assert dq > db, "no coalescing happened (queries == batches)"


def test_microbatch_duplicate_literals_share_one_dispatch(coord):
    client = _client(coord)
    client.execute("SET SESSION enable_microbatch = true")
    client.execute("SET SESSION microbatch_window_ms = 40")
    results = []
    lock = threading.Lock()

    def one(i):
        c = _client(coord, user=f"dup{i}")
        rows = c.execute(
            "SELECT n_name FROM nation WHERE n_nationkey = 5").rows
        with lock:
            results.append(rows)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert all(r == [["ETHIOPIA"]] for r in results), results


def test_microbatch_off_by_default(coord):
    client = _client(coord)
    q0 = MICROBATCH_QUERIES.value()
    client.execute("SELECT n_name FROM nation WHERE n_nationkey = 1")
    assert MICROBATCH_QUERIES.value() == q0


# ---------------------------------------------------------------------------
# concurrent-mix throughput smoke (tier-1 cover for bench --concurrency)
# ---------------------------------------------------------------------------

def test_concurrency_soak_smoke():
    import bench
    rec = bench.concurrency_soak(n_clients=12, queries_per_client=3,
                                 out_path=None)
    assert rec["wrong_answers"] == 0
    assert rec["failed_queries"] == 0
    assert rec["result_cache_hits"] > 0
    assert rec["plan_cache_hits"] > 0
    assert rec["router_host"] > 0 and rec["router_device"] > 0
    assert rec["invalidation_proven"]
    assert rec["passed"], rec
