"""Zone-map scan pruning tests (round 14).

Reference pattern: the reader-level predicate pushdown tier
(TupleDomain + stripe/row-group statistics in lib/trino-orc
StripeReader and lib/trino-parquet PredicateUtils): scans skip row
ranges the pushed-down predicate provably cannot match. Pruning is
conservative-only and the residual filter always re-runs, so the
load-bearing assertion throughout is BIT-EXACTNESS between pruning on
and off — on the full TPC-H suite and on edge predicates chosen to
break naive zone evaluation (NULL-only zones, decimal HALF_UP
boundaries, varchar dictionary ranges, open-ended ranges, NOT/OR
shapes that must not push down).
"""

import os
import time

import numpy as np
import pytest

from tpch_full import QUERIES
from trino_tpu.batch import Field, Schema
from trino_tpu.connectors.tpch.datagen import TableData
from trino_tpu.exec.session import Session
from trino_tpu.metrics import SCAN_SPLITS_PRUNED, SCAN_ZONES_PRUNED
from trino_tpu.types import BIGINT, DATE, VARCHAR, decimal

ZONE_ROWS = 2048          # tiny-scale tables span many zones


@pytest.fixture(scope="module")
def session():
    s = Session(default_schema="tiny")
    s.execute(f"SET SESSION zone_map_rows = {ZONE_ROWS}")
    # the host route never consults zone maps; force the device path so
    # pruning really executes under every query below
    s.execute("SET SESSION routing_mode = device")
    return s


def run_both(s, sql):
    """Execute with pruning on then off; returns (on_rows, off_rows)."""
    s.execute("SET SESSION enable_zone_map_pruning = true")
    on = s.execute(sql).rows
    s.execute("SET SESSION enable_zone_map_pruning = false")
    off = s.execute(sql).rows
    s.execute("SET SESSION enable_zone_map_pruning = true")
    return on, off


# ---------------------------------------------------------------------------
# full TPC-H: pruning on == pruning off, bit for bit
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_bit_exact_pruning_on_vs_off(session, qnum):
    on, off = run_both(session, QUERIES[qnum])
    assert on == off


# ---------------------------------------------------------------------------
# edge predicates on a purpose-built table
# ---------------------------------------------------------------------------

N = 8192
EDGE_ZONE_ROWS = 1024


@pytest.fixture(scope="module")
def edge_session():
    """Memory table with one all-NULL leading zone, clustered bigint /
    decimal / date / varchar columns (zone maps are built at insert
    time by the memory connector)."""
    s = Session()
    s.execute(f"SET SESSION zone_map_rows = {EDGE_ZONE_ROWS}")
    s.execute("SET SESSION routing_mode = device")
    k = np.arange(N, dtype=np.int64)
    # DECIMAL(12,2): values ...,-0.05, 0.00, 0.05,... — midpoints where
    # HALF_UP rounding drift would show up immediately
    d = (np.arange(N, dtype=np.int64) - N // 2) * 5
    dt = (10957 + (np.arange(N, dtype=np.int32) // 32)).astype(np.int32)
    pool = tuple(f"s{i:04d}" for i in range(64))
    codes = (np.arange(N) * 64 // N).astype(np.int32)   # clustered codes
    valids = [np.ones(N, dtype=np.bool_) for _ in range(4)]
    valids[0][:EDGE_ZONE_ROWS] = False                  # NULL-only zone
    data = TableData("edge", Schema((
        Field("k", BIGINT), Field("d", decimal(12, 2)),
        Field("dt", DATE), Field("v", VARCHAR, dictionary=pool))),
        [k, d, dt, codes], valids=valids)
    s.catalog.connector("memory").create_table("default", "edge", data)
    return s


EDGE_PREDICATES = [
    # NULL-only zone: k IS NULL there; no predicate on k may emit it
    "k >= 0",
    "k < 1500",
    # open-ended ranges
    "k > 7000",
    "k <= 100",
    # decimal HALF_UP boundary values (exact scaled-int compares)
    "d < 0.05",
    "d <= 0.05",
    "d = 0.05",
    "d > -0.05 AND d < 0.10",
    # dates
    "dt >= DATE '2000-03-01'",
    "dt BETWEEN DATE '2000-01-15' AND DATE '2000-02-15'",
    # varchar ranges through the dictionary-predicate path
    "v >= 's0050'",
    "v BETWEEN 's0010' AND 's0020'",
    "v = 's0001'",
]


@pytest.mark.parametrize("pred", EDGE_PREDICATES)
def test_edge_predicates_bit_exact(edge_session, pred):
    sql = (f"SELECT count(*) AS c, min(k) AS mn, max(k) AS mx "
           f"FROM memory.default.edge WHERE {pred}")
    on, off = run_both(edge_session, sql)
    assert on == off


def test_null_zone_never_matches(edge_session):
    """Rows in the all-NULL zone fail every comparison — with pruning on
    AND off (3VL at the residual filter), so counts exclude them."""
    on, off = run_both(
        edge_session,
        "SELECT count(*) AS c FROM memory.default.edge WHERE k >= 0")
    assert on == off == [(N - EDGE_ZONE_ROWS,)]


def test_selective_query_prunes_zones(edge_session):
    s = edge_session
    s.execute("SET SESSION enable_zone_map_pruning = true")
    before_metric = SCAN_ZONES_PRUNED.value()
    before = s.executor.stats.scan_zones_pruned
    s.executor.invalidate_scan_cache()
    s.execute("SELECT count(*) AS c FROM memory.default.edge "
              "WHERE k > 8000")
    assert s.executor.stats.scan_zones_pruned > before
    assert SCAN_ZONES_PRUNED.value() > before_metric


NO_PUSHDOWN_PREDICATES = [
    # disjunction across columns: not a conjunctive single-column range
    "k < 100 OR dt > DATE '2000-03-01'",
    # NOT of a range: conservatively not pushed
    "NOT (k < 5000)",
    # arithmetic over the column: not a bare column compare
    "k + 1 < 100",
]


@pytest.mark.parametrize("pred", NO_PUSHDOWN_PREDICATES)
def test_non_pushable_shapes_stay_correct(edge_session, pred):
    sql = (f"SELECT count(*) AS c FROM memory.default.edge "
           f"WHERE {pred}")
    on, off = run_both(edge_session, sql)
    assert on == off
    # and the planner did not claim a pushdown for these shapes
    plan = "\n".join(r[0] for r in
                     edge_session.execute("EXPLAIN " + sql).rows)
    assert "pushdown=" not in plan


# ---------------------------------------------------------------------------
# observability: EXPLAIN pushdown annotation + EXPLAIN ANALYZE verdicts
# ---------------------------------------------------------------------------

def test_explain_shows_pushdown(session):
    plan = "\n".join(r[0] for r in session.execute(
        "EXPLAIN SELECT count(*) FROM lineitem "
        "WHERE l_orderkey < 1000").rows)
    assert "pushdown=" in plan


def test_explain_analyze_reports_zone_pruning(session):
    session.execute("SET SESSION enable_zone_map_pruning = true")
    rows = session.execute(
        "EXPLAIN ANALYZE SELECT count(*) FROM lineitem "
        "WHERE l_orderkey < 1000").rows
    text = "\n".join(r[0] for r in rows)
    assert "pruned by zone maps" in text


# ---------------------------------------------------------------------------
# connector-level pruned decode (ORC stripes / parquet row groups)
# ---------------------------------------------------------------------------

def _clustered_table(n=16384):
    rng = np.random.default_rng(5)
    return TableData("t", Schema((
        Field("k", BIGINT), Field("x", BIGINT))),
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 100, n)])


def test_orc_connector_pruned_decode(tmp_path):
    from trino_tpu.connectors.orcdir import OrcConnector
    from trino_tpu.connectors.parquetdir import flatten_table
    from trino_tpu.formats.orc import write_orc
    data = _clustered_table()
    os.makedirs(tmp_path / "s")
    write_orc(str(tmp_path / "s" / "t.orc"),
              *flatten_table(data, "ORC"), stripe_rows=1024,
              compression="zlib")
    conn = OrcConnector(str(tmp_path))
    pruned = conn.get_table_pruned("s", "t", {"k": (0, 999)})
    assert pruned.skipped_stripes == 15
    assert pruned.total_stripes == 16
    assert pruned.num_rows == 1024
    np.testing.assert_array_equal(pruned.columns[0],
                                  np.arange(1024, dtype=np.int64))
    # the predicate-specific result must not poison the table cache
    full = conn.get_table("s", "t")
    assert full.num_rows == data.num_rows


def test_parquet_connector_pruned_decode(tmp_path):
    from trino_tpu.connectors.parquetdir import (ParquetConnector,
                                                 flatten_table)
    from trino_tpu.formats.parquet import write_parquet
    data = _clustered_table()
    os.makedirs(tmp_path / "s")
    write_parquet(str(tmp_path / "s" / "t.parquet"),
                  *flatten_table(data, "parquet"), row_group_rows=1024)
    conn = ParquetConnector(str(tmp_path))
    pruned = conn.get_table_pruned("s", "t", {"k": (4096, 5000)})
    assert pruned.skipped_row_groups == 15
    assert pruned.total_row_groups == 16
    assert pruned.num_rows == 1024           # only group 4 survives
    full = conn.get_table("s", "t")
    assert full.num_rows == data.num_rows


# ---------------------------------------------------------------------------
# distributed tier: the scheduler drops non-matching row-range splits
# ---------------------------------------------------------------------------

def test_scheduler_prunes_splits():
    from trino_tpu.client.client import Client
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer
    session = Session(default_schema="tiny")
    session.execute("SET SESSION zone_map_rows = 4096")
    coord = CoordinatorServer(session).start()
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"worker-{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(2)]
    try:
        deadline = time.time() + 5
        while len(coord.state.active_nodes()) < 2 and \
                time.time() < deadline:
            time.sleep(0.05)
        sql = ("SELECT l_linestatus, count(*) AS c FROM lineitem "
               "WHERE l_orderkey < 3000 GROUP BY l_linestatus "
               "ORDER BY l_linestatus")
        want = session.execute(sql).rows
        before = SCAN_SPLITS_PRUNED.value()
        client = Client(coord.uri, user="test")
        r = client.execute(sql)
        assert r.state == "FINISHED"
        assert [tuple(row) for row in r.rows] == \
            [tuple(row) for row in want]
        assert coord.state.scheduler.stats.get("splits_pruned", 0) > 0
        assert SCAN_SPLITS_PRUNED.value() > before
        # the operator_stats rollup row carries the verdict
        scans = [r for r in coord.state.scheduler.operator_history
                 if r["operator"] == "TableScan" and
                 r["strategy"].startswith("zone-pruned:")]
        assert scans, "TableScan rollup should record split pruning"
    finally:
        for w in workers:
            w.stop()
        coord.stop()


# ---------------------------------------------------------------------------
# ORC stripe statistics + ZLIB interop against a real reader/writer
# ---------------------------------------------------------------------------

def test_orc_zlib_round_trip_pyarrow_reads_ours(tmp_path):
    pa = pytest.importorskip("pyarrow")
    orc = pytest.importorskip("pyarrow.orc")
    from trino_tpu.formats.orc import write_orc
    n = 4096
    rng = np.random.default_rng(9)
    ints = rng.integers(-(1 << 40), 1 << 40, n)
    dbls = rng.standard_normal(n)
    strs = np.array([f"row{i % 97:03d}" for i in range(n)], dtype=object)
    path = str(tmp_path / "ours.orc")
    write_orc(path, ["i", "d", "s"], [ints, dbls, strs],
              stripe_rows=1024, compression="zlib")
    t = orc.read_table(path)
    np.testing.assert_array_equal(t.column("i").to_numpy(), ints)
    np.testing.assert_array_equal(t.column("d").to_numpy(), dbls)
    assert t.column("s").to_pylist() == list(strs)
    assert pa is not None


def test_orc_stripe_stats_prune_pyarrow_file(tmp_path):
    pa = pytest.importorskip("pyarrow")
    orc = pytest.importorskip("pyarrow.orc")
    from trino_tpu.formats.orc import read_orc_file
    n = 16384
    tbl = pa.table({"k": np.arange(n, dtype=np.int64)})
    path = str(tmp_path / "theirs.orc")
    orc.write_table(tbl, path, stripe_size=8 * 1024)
    f = read_orc_file(path, predicates={"k": (0, 100)})
    assert f.total_stripes > 1
    assert f.skipped_stripes == f.total_stripes - 1
    np.testing.assert_array_equal(
        f.columns[0][:101], np.arange(101, dtype=np.int64))


def test_orc_zlib_smaller_and_bit_exact(tmp_path):
    from trino_tpu.connectors.orcdir import load_orc
    from trino_tpu.connectors.parquetdir import flatten_table
    from trino_tpu.formats.orc import write_orc
    data = _clustered_table()
    flat = flatten_table(data, "ORC")
    raw, zl = str(tmp_path / "raw.orc"), str(tmp_path / "zl.orc")
    write_orc(raw, *flat, stripe_rows=2048)
    write_orc(zl, *flat, stripe_rows=2048, compression="zlib")
    assert os.path.getsize(zl) < os.path.getsize(raw)
    a, b = load_orc(raw, "t"), load_orc(zl, "t")
    for ca, cb in zip(a.columns, b.columns):
        np.testing.assert_array_equal(ca, cb)
