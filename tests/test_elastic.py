"""Round-15 elastic cluster membership: the worker lifecycle state
machine (ACTIVE -> DRAINING -> DRAINED -> LEFT), drain handoff as split
MIGRATION (not failure), join-mid-stream, per-tenant isolation +
fair-share routing, and the BENCH_soak regression gate.

The drain contract under test: an admin `PUT /v1/info/state` stops task
intake immediately (409 NODE_DRAINING), in-flight splits finish or hand
off to survivors through the retry machinery WITHOUT burning retry
budget, buffered exchange pages stay pullable through the flush grace,
and the final LEFT announce deregisters the node — all while results
stay bit-exact against a single-process oracle."""

import json
import os
import sys
import threading
import time
from urllib.error import HTTPError
from urllib.request import Request, urlopen

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from trino_tpu.client.client import Client                   # noqa: E402
from trino_tpu.exec.session import Session                   # noqa: E402
from trino_tpu.server.coordinator import CoordinatorServer   # noqa: E402
from trino_tpu.server.security import (INTERNAL_HEADER,      # noqa: E402
                                       internal_headers)
from trino_tpu.server.worker import WorkerServer             # noqa: E402

Q_AGG = ("SELECT l_returnflag, l_linestatus, sum(l_quantity), "
         "count(*) FROM lineitem GROUP BY l_returnflag, l_linestatus "
         "ORDER BY l_returnflag, l_linestatus")


def _vals(rows):
    return [tuple(v if v is None or isinstance(v, (int, float, str, bool))
                  else str(v) for v in r) for r in rows]


def _put_state(uri, state, headers=None, timeout=10):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(internal_headers() if headers is None else headers)
    req = Request(f"{uri}/v1/info/state",
                  data=json.dumps({"state": state}).encode(),
                  method="PUT", headers=hdrs)
    with urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


@pytest.fixture(scope="module")
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session, retry_policy="QUERY").start()
    sched = coord.state.scheduler
    sched.split_rows = 8192
    workers = [WorkerServer(f"elastic-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)
    yield coord, workers, session
    for w in workers:
        w.kill()
    coord.stop()


@pytest.fixture(autouse=True)
def _settle(request):
    # every cluster test leaves the 3 module workers ACTIVE and
    # re-registered before the next one runs
    if "cluster" not in request.fixturenames:
        yield
        return
    coord, workers, _ = request.getfixturevalue("cluster")
    yield
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)
    assert len(coord.state.active_nodes()) >= 3


# ---------------------------------------------------------------------------
# lifecycle state machine
# ---------------------------------------------------------------------------

def test_lifecycle_ratchet_edges():
    """The transition table is a one-way ratchet: no skipping DRAINING,
    no resurrecting a LEFT node, but a DRAINING node may be reverted to
    ACTIVE by an admin cancel."""
    w = WorkerServer("ratchet", "http://127.0.0.1:9")
    try:
        assert w.state == "ACTIVE"
        assert not w._transition("DRAINED")      # cannot skip DRAINING
        assert not w._transition("LEFT")
        assert w._transition("DRAINING")
        assert w._transition("ACTIVE")           # admin cancel
        assert w._transition("DRAINING")
        assert not w._transition("LEFT")         # must pass DRAINED
        assert w._transition("DRAINED")
        assert not w._transition("ACTIVE")       # past the point of return
        assert w._transition("LEFT")
        assert not w._transition("ACTIVE")       # LEFT is terminal
        assert w.drained()
    finally:
        w.httpd.server_close()


def test_admin_drain_under_load_bit_exact(cluster):
    """Join a 4th worker mid-stream, then admin-drain it while queries
    are in flight: every query stays bit-exact, the drain reaches LEFT,
    the node deregisters, and nothing is orphaned on it."""
    coord, workers, session = cluster
    sched = coord.state.scheduler
    want = _vals(session.execute(Q_AGG).rows)

    w3 = WorkerServer("elastic-w3", coord.uri, announce_interval_s=0.1,
                      catalog=session.catalog).start()
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 4 and time.time() < deadline:
        time.sleep(0.05)
    assert len(coord.state.active_nodes()) == 4

    results, stop = [], threading.Event()

    def stream():
        client = Client(coord.uri, user="elastic")
        while not stop.is_set():
            results.append(_vals(client.execute(Q_AGG).rows))

    # drop any spooled stage outputs so the stream dispatches real
    # tasks (the durable spool would otherwise replay earlier runs of
    # the same fragment and the joiner would never see a split)
    sched.spool.clear()
    t = threading.Thread(target=stream, daemon=True)
    t.start()
    # drain only once the joiner has demonstrably taken work — a fixed
    # sleep races the first query's dispatch against the drain
    deadline = time.time() + 15
    while time.time() < deadline and not any(
            rec.get("node") == "elastic-w3" for rec in sched.task_history):
        time.sleep(0.05)
    assert any(rec.get("node") == "elastic-w3"
               for rec in sched.task_history)
    status, body = _put_state(w3.uri, "DRAINING")
    assert status == 200
    assert body["state"] in ("DRAINING", "DRAINED", "LEFT")
    deadline = time.time() + 30
    while not w3.drained() and time.time() < deadline:
        time.sleep(0.05)
    stop.set()
    t.join(timeout=60)
    assert w3.drained(), w3.state
    # deregistered: the LEFT announce removed it from the node map
    with coord.state.nodes_lock:
        assert "elastic-w3" not in coord.state.nodes
    # nothing orphaned: no in-flight tasks, no unpulled buffers
    assert w3.task_manager.inflight() == []
    # the joiner actually participated before leaving
    assert any(rec.get("node") == "elastic-w3"
               for rec in sched.task_history)
    assert len(results) > 0
    assert all(r == want for r in results)
    w3.kill()


def test_draining_node_migrates_splits_without_retry_penalty(cluster):
    """A node that starts refusing work (409 NODE_DRAINING) before the
    coordinator learns it is draining: the scheduler re-places its
    splits on survivors as MIGRATIONS — splits_migrated grows, the
    retry counter does not, and the result is still bit-exact."""
    coord, workers, session = cluster
    sched = coord.state.scheduler
    want = _vals(session.execute(Q_AGG).rows)
    w2 = workers[2]
    orig_announce = w2.announce_once
    # keep announcing ACTIVE so the scheduler keeps placing splits on
    # the refusing node (the race window a real drain always has)
    w2.announce_once = lambda attempts=5, state=None: \
        orig_announce(attempts, "ACTIVE")
    w2.state = "DRAINING"
    retries0 = sched.stats["task_retries"]
    migrated0 = sched.stats["splits_migrated"]
    try:
        r = Client(coord.uri, user="elastic").execute(Q_AGG)
        assert r.state == "FINISHED"
        assert _vals(r.rows) == want
        assert sched.stats["splits_migrated"] > migrated0
        assert sched.stats["task_retries"] == retries0, \
            "drain handoff must not burn retry budget"
    finally:
        w2.state = "ACTIVE"
        w2.announce_once = orig_announce


def test_mid_drain_crash_detected_as_failed(cluster):
    """A worker that dies mid-drain must not stay DRAINING forever: the
    failure detector's unreachability signal overrides the last
    reported lifecycle state, and the cluster keeps serving."""
    from trino_tpu.server.failuredetector import HeartbeatFailureDetector
    coord, workers, session = cluster
    want = _vals(session.execute(Q_AGG).rows)
    wx = WorkerServer("elastic-crash", coord.uri, announce_interval_s=0.1,
                      catalog=session.catalog).start()
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 4 and time.time() < deadline:
        time.sleep(0.05)
    detector = HeartbeatFailureDetector(coord.state,
                                        interval_s=0.05).start()
    try:
        wx.state = "DRAINING"             # mid-drain: never reaches LEFT
        deadline = time.time() + 5
        while time.time() < deadline:
            with coord.state.nodes_lock:
                node = coord.state.nodes.get("elastic-crash")
                if node is not None and node.state == "DRAINING":
                    break
            time.sleep(0.05)
        with coord.state.nodes_lock:
            assert coord.state.nodes["elastic-crash"].state == "DRAINING"
        wx.kill()                         # crash before DRAINED
        deadline = time.time() + 10
        while time.time() < deadline:
            with coord.state.nodes_lock:
                if coord.state.nodes["elastic-crash"].state == "FAILED":
                    break
            time.sleep(0.05)
        with coord.state.nodes_lock:
            assert coord.state.nodes["elastic-crash"].state == "FAILED"
        r = Client(coord.uri, user="elastic").execute(Q_AGG)
        assert _vals(r.rows) == want
    finally:
        detector.stop()
        with coord.state.nodes_lock:
            coord.state.nodes.pop("elastic-crash", None)


def test_lifecycle_state_visible_in_info_and_nodes_table(cluster):
    """The reported state flows worker /v1/info -> announce ->
    system.runtime.nodes, and a DRAINING node drops out of
    active_nodes() (so placement and hedging skip it)."""
    coord, workers, session = cluster
    w2 = workers[2]
    with urlopen(f"{w2.uri}/v1/info", timeout=5) as resp:
        assert json.loads(resp.read().decode())["state"] == "ACTIVE"
    w2.state = "DRAINING"
    try:
        deadline = time.time() + 5
        while time.time() < deadline:
            with coord.state.nodes_lock:
                if coord.state.nodes["elastic-w2"].state == "DRAINING":
                    break
            time.sleep(0.05)
        assert "elastic-w2" not in \
            {n.node_id for n in coord.state.active_nodes()}
        rows = Client(coord.uri, user="elastic").execute(
            "SELECT node_id, state FROM system.runtime.nodes").rows
        states = {r[0]: r[1] for r in rows}
        assert states["elastic-w2"] == "DRAINING"
        assert states["elastic-w0"] == "ACTIVE"
    finally:
        w2.state = "ACTIVE"


def test_rogue_drain_rejected_without_internal_secret(cluster,
                                                      monkeypatch):
    """On a secured cluster the drain route is cluster-internal: a PUT
    without the shared secret is a 401 AUTHENTICATION_FAILED and the
    worker stays ACTIVE; the same request with the secret succeeds."""
    coord, workers, _ = cluster
    w0 = workers[0]
    monkeypatch.setenv("TRINO_TPU_INTERNAL_SECRET", "s3cr3t")
    with pytest.raises(HTTPError) as ei:
        _put_state(w0.uri, "DRAINING", headers={})
    assert ei.value.code == 401
    body = json.loads(ei.value.read().decode())
    assert body["error"]["errorName"] == "AUTHENTICATION_FAILED"
    assert w0.state == "ACTIVE"
    # wrong secret is just as dead
    with pytest.raises(HTTPError) as ei:
        _put_state(w0.uri, "DRAINING",
                   headers={INTERNAL_HEADER: "wrong"})
    assert ei.value.code == 401
    assert w0.state == "ACTIVE"
    # the real secret passes (ACTIVE request: a no-op cancel)
    status, body = _put_state(w0.uri, "ACTIVE",
                              headers={INTERNAL_HEADER: "s3cr3t"})
    assert status == 200 and body["state"] == "ACTIVE"


# ---------------------------------------------------------------------------
# per-tenant isolation
# ---------------------------------------------------------------------------

def test_tenant_tree_soft_limit_queues_then_admits():
    """Per-tenant resource groups gate admission on the soft memory
    limit: under pressure a tenant's queries queue; when the cluster
    memory tick reports pressure cleared, they admit — and other
    tenants without a limit are never blocked."""
    from trino_tpu.server.resourcegroups import tenant_tree
    rgm = tenant_tree({"alpha": {},
                       "beta": {"hard_concurrency_limit": 2,
                                "soft_memory_limit_bytes": 1000}})
    assert rgm.tenant_of("beta-7") == "beta"
    assert rgm.tenant_of("alpha-0") == "alpha"
    assert rgm.tenant_of("nobody") == "default"
    ran = []
    for r in rgm.set_cluster_memory(5000):   # pressure above beta's soft
        r()
    rgm.submit("beta-1", lambda: ran.append("beta"))
    assert ran == [], "beta must stay queued under memory pressure"
    rgm.submit("alpha-1", lambda: ran.append("alpha"))
    assert ran == ["alpha"], "alpha has no soft limit and runs"
    for r in rgm.set_cluster_memory(0):      # pressure cleared
        r()
    assert ran == ["alpha", "beta"], "beta admits once memory drops"


def test_tenant_fair_share_contention_signal():
    """TenantFairShare sees contention only from OTHER tenants' device
    occupancy — a tenant is never contended by itself."""
    from trino_tpu.exec.router import TenantFairShare
    fs = TenantFairShare()
    assert not fs.contended_by_others("alpha")
    fs.device_begin("beta")
    assert fs.contended_by_others("alpha")
    assert not fs.contended_by_others("beta")
    fs.device_begin("alpha")
    assert fs.contended_by_others("beta")
    fs.device_end("beta")
    assert not fs.contended_by_others("alpha")
    fs.device_end("alpha")
    assert fs.inflight() == {}


def test_tenant_label_flows_to_metrics_and_tracker(cluster):
    """A query from tenant user beta-1 is counted under its tenant in
    trino_tpu_tenant_queries_total and stamped on the tracked query."""
    from trino_tpu.metrics import REGISTRY
    from trino_tpu.server.resourcegroups import tenant_tree
    coord, workers, _ = cluster
    dispatcher = coord.state.dispatcher
    saved = dispatcher.resource_groups
    dispatcher.resource_groups = tenant_tree(
        {"alpha": {}, "beta": {}, "gamma": {}})
    key = ("trino_tpu_tenant_queries_total", "beta")
    before = REGISTRY.snapshot().get(key, 0)
    try:
        r = Client(coord.uri, user="beta-1").execute(
            "SELECT count(*) FROM nation")
        assert r.rows[0][0] == 25
        assert REGISTRY.snapshot().get(key, 0) == before + 1
        tq = next(q for q in coord.state.tracker.all()
                  if q.session_user == "beta-1")
        assert tq.tenant == "beta"
    finally:
        dispatcher.resource_groups = saved


# ---------------------------------------------------------------------------
# BENCH_soak: the sustained-soak smoke and its regression gate
# ---------------------------------------------------------------------------

def test_elastic_soak_smoke(tmp_path):
    """The full soak harness at smoke duration: mixed multi-tenant load
    with chaos ON, a worker drained and a fresh one joined mid-run —
    the acceptance booleans must all hold even at a few seconds."""
    import bench
    rec = bench.elastic_soak(duration_s=7.0,
                             out_path=str(tmp_path / "BENCH_soak.json"))
    assert rec["passed"], rec
    assert rec["wrong_answers"] == 0
    assert rec["failed_queries"] == 0
    assert rec["orphaned_splits"] == 0
    assert rec["drain_completed"] and rec["drained_node_deregistered"]
    assert rec["join_received_splits"]
    assert rec["writes_visible"]
    assert rec["lifecycle_transitions"]["LEFT"] >= 1
    assert rec["fair_share_held"]
    for tname in ("alpha", "beta", "gamma"):
        assert rec["tenants"][tname]["slo_ok"], rec["tenants"]


def _soak_round(tmp_path, name, alpha_p99, qps=100.0):
    doc = {"metric": "soak", "throughput_qps": qps,
           "tenants": {"alpha": {"p99_ms": alpha_p99, "queries": 100},
                       "beta": {"p99_ms": 2000.0, "queries": 100},
                       "gamma": {"p99_ms": 150.0, "queries": 100}}}
    (tmp_path / name).write_text(json.dumps(doc))


def test_check_regressions_gates_soak_series(tmp_path, monkeypatch):
    """BENCH_soak rounds feed --check-regressions as their own AND-ed
    sub-series: a per-tenant p99 blowout in a later round fails the
    gate (median + MAD, same rule as every other series)."""
    import bench
    _soak_round(tmp_path, "BENCH_soak.json", 100.0)
    _soak_round(tmp_path, "BENCH_soak_r02.json", 110.0)
    _soak_round(tmp_path, "BENCH_soak_r03.json", 95.0)
    monkeypatch.chdir(tmp_path)
    assert bench.main(["--check-regressions"]) == 0
    # injected SLO regression: alpha's p99 blows out 9x in a new round
    _soak_round(tmp_path, "BENCH_soak_r04.json", 900.0)
    assert bench.main(["--check-regressions"]) == 1


def test_load_bench_round_parses_soak_record(tmp_path):
    import bench
    _soak_round(tmp_path, "BENCH_soak.json", 123.0, qps=50.0)
    cfg = bench.load_bench_round(str(tmp_path / "BENCH_soak.json"))
    assert cfg["soak_alpha_p99"] == 123.0
    assert cfg["soak_beta_p99"] == 2000.0
    assert cfg["soak_ms_per_query"] == 20.0
