"""Device-time profiling, JIT-compile observability, query history and
latency-regression detection (round 10)."""

import json
import os
import time

import jax
import pytest

from trino_tpu.exec.profiler import (RECORDER, CompileRecorder,
                                     device_memory_stats, instrument)
from trino_tpu.exec.session import Session
from trino_tpu.server.history import (HistoryEventListener,
                                      QueryHistoryStore, is_regressed,
                                      plan_fingerprint, robust_baseline)
from trino_tpu.server.statemachine import (QueryStateMachine,
                                           QueryTracker, TrackedQuery)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)


# ---------------------------------------------------------------------------
# compile recorder
# ---------------------------------------------------------------------------

def test_recorder_counts_compiles_and_hits():
    rec = CompileRecorder()
    f = instrument(jax.jit(lambda x: x * 2), "test.double",
                   recorder=rec)
    import jax.numpy as jnp
    f(jnp.ones(8))                    # compile
    f(jnp.ones(8))                    # hit
    f(jnp.ones(16))                   # new shape: compile
    t = rec.totals()
    assert t["compiles"] == 2 and t["hits"] == 1
    assert t["compileSeconds"] > 0
    entries = rec.snapshot()
    assert len(entries) == 2          # two fingerprints, same site
    assert all(e["site"] == "test.double" for e in entries)
    hit_entry = next(e for e in entries if e["hits"] == 1)
    assert hit_entry["compiles"] == 1
    assert hit_entry["last_compile_ms"] > 0


def test_recorder_silent_inside_outer_trace():
    """A jit site called during another site's trace must not record —
    the outer program owns the compile."""
    rec = CompileRecorder()
    inner = instrument(jax.jit(lambda x: x + 1), "test.inner",
                       recorder=rec)

    @jax.jit
    def outer(x):
        return inner(x) * 3

    import jax.numpy as jnp
    outer(jnp.ones(4))
    assert rec.totals()["compiles"] == 0
    inner(jnp.ones(4))                # eager boundary: records
    assert rec.totals()["compiles"] == 1


def test_exec_stats_jit_compiles_agree_with_recorder():
    """The satellite fix: every jit site routes through the recorder, so
    ExecStats.jit_compiles (thread-bound attribution) moves in lockstep
    with the process recorder during a single-threaded query."""
    s = Session(default_schema="tiny")
    s.execute("SELECT count(*) FROM region")       # warm common kernels
    stats0 = s.executor.stats.jit_compiles
    rec0 = RECORDER.totals()["compiles"]
    # a fresh literal is a fresh static in the fused filter trace, so at
    # least one program compiles for this query
    s.execute("SELECT count(*) FROM nation WHERE n_nationkey > 17")
    d_stats = s.executor.stats.jit_compiles - stats0
    d_rec = RECORDER.totals()["compiles"] - rec0
    assert d_stats >= 1
    assert d_stats == d_rec


def test_device_memory_stats_shape():
    st = device_memory_stats()
    assert st.get("platform") == "cpu"
    assert "bytesInUse" in st and "bytesLimit" in st


# ---------------------------------------------------------------------------
# fenced device/host/compile attribution
# ---------------------------------------------------------------------------

def test_profile_split_sums_to_wall():
    s = Session(default_schema="tiny")
    s.execute("SET SESSION enable_profiling = true")
    s.execute("SELECT l_returnflag, sum(l_quantity) FROM lineitem "
              "GROUP BY l_returnflag ORDER BY l_returnflag")
    ns = s.executor.node_stats
    assert ns, "profiled run produced no node stats"
    for st in ns.values():
        wall, rows, device_s, host_s, compile_s = st
        assert wall >= 0 and device_s >= 0 and host_s >= 0 \
            and compile_s >= 0
        # the fence splits wall exactly into components
        assert abs(wall - (device_s + host_s + compile_s)) < 1e-9


def test_profiling_off_adds_zero_fences(monkeypatch):
    """With enable_profiling off, the dispatch path must never fence —
    a per-node sync would serialize the whole async pipeline."""
    s = Session(default_schema="tiny")
    s.execute("SELECT count(*) FROM nation")       # warm compiles
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda *a, **k: (calls.append(1),
                                         real(*a, **k))[1])
    s.execute("SELECT count(*) FROM nation")
    assert calls == []
    assert s.executor.node_stats == {}
    # and turning profiling on uses the fence
    s.execute("SET SESSION enable_profiling = true")
    s.execute("SELECT count(*) FROM nation")
    assert len(calls) > 0


def test_explain_analyze_renders_device_split():
    s = Session(default_schema="tiny")
    text = "\n".join(r[0] for r in s.execute(
        "EXPLAIN ANALYZE SELECT n_regionkey, count(*) FROM nation "
        "GROUP BY n_regionkey").rows)
    assert "(device " in text and "+ compile " in text, text
    assert "rows]" in text


# ---------------------------------------------------------------------------
# query history store + regression detector
# ---------------------------------------------------------------------------

def _rec(i, elapsed, fp_sql="SELECT 1 FROM t", state="FINISHED",
         **extra):
    return dict({"query_id": f"q{i}", "sql": fp_sql, "user": "u",
                 "state": state, "elapsed_s": elapsed, "rows": 1,
                 "bytes_shuffled": 0, "spills": 0}, **extra)


def test_history_store_persists_and_reloads(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    store = QueryHistoryStore(path=path)
    for i, el in enumerate((1.0, 1.1, 0.9)):
        store.record(_rec(i, el))
    assert len(store) == 3
    # dedup by query id (eviction flush after the completion event)
    store.record(_rec(0, 5.0))
    assert len(store) == 3
    # a fresh store reloads the ring from disk
    again = QueryHistoryStore(path=path)
    assert len(again) == 3
    fp = plan_fingerprint("SELECT   1 from T;")
    assert [r["query_id"] for r in again.for_fingerprint(fp)] == \
        ["q0", "q1", "q2"]


def test_fingerprint_normalizes_statement_shape():
    assert plan_fingerprint("SELECT 1  FROM t") == \
        plan_fingerprint("select 1 from t;")
    assert plan_fingerprint("SELECT 1 FROM t") != \
        plan_fingerprint("SELECT 2 FROM t")


def test_regression_detector_flags_3x_and_stays_quiet_on_jitter(
        tmp_path):
    from trino_tpu.metrics import LATENCY_REGRESSIONS
    store = QueryHistoryStore(path=str(tmp_path / "h.jsonl"))
    jitter = (1.0, 1.08, 0.95, 1.02, 0.9, 1.1)
    for i, el in enumerate(jitter):
        assert store.record(_rec(i, el)) is None
    # jittered value inside the envelope: quiet
    assert store.record(_rec(50, 1.05)) is None
    # synthetic 3x slowdown: flagged, logged, counted
    before = LATENCY_REGRESSIONS.value()
    verdict = store.record(_rec(51, 3.0))
    assert verdict is not None and verdict["metric"] == "elapsed_s"
    assert LATENCY_REGRESSIONS.value() == before + 1
    flagged = [r for r in store.snapshot() if r["query_id"] == "q51"]
    assert flagged and flagged[0]["regressed"]


def test_detector_needs_min_baseline_and_skips_failures(tmp_path):
    store = QueryHistoryStore(path=str(tmp_path / "h.jsonl"))
    # too few priors: never judged
    for i, el in enumerate((1.0, 1.0)):
        store.record(_rec(i, el))
    assert store.record(_rec(10, 30.0)) is None
    # failed queries neither build baselines nor get judged
    for i in range(20, 26):
        store.record(_rec(i, 1.0, state="FAILED"))
    assert store.record(_rec(30, 30.0, state="FAILED")) is None


def test_robust_baseline_and_rule():
    med, mad = robust_baseline([1.0, 1.1, 0.9, 1.0, 1.2])
    assert abs(med - 1.0) < 1e-9
    assert mad == pytest.approx(0.1)
    assert is_regressed(3.0, med, mad)
    assert not is_regressed(1.3, med, mad)       # inside the ratio gate
    assert not is_regressed(0.5, med, mad)


def test_tracker_eviction_flushes_history_and_env_cap(tmp_path,
                                                      monkeypatch):
    store = QueryHistoryStore(path=str(tmp_path / "h.jsonl"))
    tracker = QueryTracker(max_history=2)
    tracker.on_evict = store.record_tracked
    for i in range(5):
        tq = TrackedQuery(f"ev{i}", f"SELECT {i}", "u",
                          QueryStateMachine(f"ev{i}"))
        tq.elapsed_s = 0.5
        tq.state_machine.fail("boom")
        tracker.register(tq)
        time.sleep(0.002)      # distinct ended_at ordering
    # cap held, evicted queries flushed to the store
    done = [q for q in tracker.all() if q.state_machine.is_done()]
    assert len(done) == 2
    evicted_ids = {r["query_id"] for r in store.snapshot()}
    assert {"ev0", "ev1", "ev2"} <= evicted_ids
    # the cap is env-configurable
    monkeypatch.setenv("TRINO_TPU_QUERY_HISTORY", "7")
    assert QueryTracker().max_history == 7
    monkeypatch.setenv("TRINO_TPU_QUERY_HISTORY", "bogus")
    assert QueryTracker().max_history == 100


def test_completed_event_feeds_listener(tmp_path):
    from trino_tpu.events import QueryCompletedEvent
    store = QueryHistoryStore(path=str(tmp_path / "h.jsonl"))
    li = HistoryEventListener(store)
    li.query_completed(QueryCompletedEvent(
        "qz", "u", "SELECT 1", "FINISHED", None, 0.2, 1, 0,
        time.time(), spills=3))
    (rec,) = store.snapshot()
    assert rec["spills"] == 3 and rec["state"] == "FINISHED"


# ---------------------------------------------------------------------------
# cluster surface: /v1/jit, system tables, worker device stats,
# distributed EXPLAIN ANALYZE split
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    os.environ["TRINO_TPU_HISTORY_PATH"] = str(
        tmp_path_factory.mktemp("hist") / "query_history.jsonl")
    try:
        from trino_tpu.server.coordinator import CoordinatorServer
        from trino_tpu.server.failuredetector import \
            HeartbeatFailureDetector
        from trino_tpu.server.worker import WorkerServer
        session = Session(default_schema="tiny")
        coord = CoordinatorServer(session).start()
        coord.state.scheduler.split_rows = 8192
        workers = [WorkerServer(f"prof-w{i}", coord.uri,
                                announce_interval_s=0.1,
                                catalog=session.catalog).start()
                   for i in range(2)]
        detector = HeartbeatFailureDetector(coord.state,
                                            interval_s=0.2).start()
        deadline = time.time() + 5
        while len(coord.state.active_nodes()) < 2 and \
                time.time() < deadline:
            time.sleep(0.05)
        yield coord, workers, session
        detector.stop()
        for w in workers:
            w.stop()
        coord.stop()
    finally:
        os.environ.pop("TRINO_TPU_HISTORY_PATH", None)


DIST_SQL = ("SELECT l_returnflag, count(*) AS c FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag")


def test_v1_jit_route_serves_recorder(cluster):
    from urllib.request import urlopen
    coord, workers, session = cluster
    from trino_tpu.client.client import Client
    Client(coord.uri, user="prof").execute("SELECT count(*) FROM nation")
    with urlopen(f"{coord.uri}/v1/jit", timeout=10) as resp:
        payload = json.loads(resp.read().decode())
    assert payload["totals"]["compiles"] >= 1
    assert payload["entries"], "no jit-cache entries after a query"
    e = payload["entries"][0]
    assert {"site", "fingerprint", "compiles", "hits"} <= set(e)


def test_system_runtime_jit_cache_queryable(cluster):
    coord, workers, session = cluster
    from trino_tpu.client.client import Client
    client = Client(coord.uri, user="prof")
    client.execute("SELECT count(*) FROM nation")
    r = client.execute("SELECT site, fingerprint, compiles, cache_hits, "
                       "compile_ms FROM system.runtime.jit_cache")
    assert r.state == "FINISHED" and len(r.rows) >= 1
    assert any(int(row[2]) >= 1 for row in r.rows)


def test_system_runtime_query_history_end_to_end(cluster):
    coord, workers, session = cluster
    from trino_tpu.client.client import Client
    client = Client(coord.uri, user="prof")
    r = client.execute("SELECT count(*) FROM region")
    deadline = time.time() + 5
    while time.time() < deadline:
        rows = client.execute(
            "SELECT query_id, state, regressed FROM "
            "system.runtime.query_history").rows
        if any(row[0] == r.query_id for row in rows):
            break
        time.sleep(0.05)
    assert any(row[0] == r.query_id and row[1] == "FINISHED"
               for row in rows)
    # and the ring persisted to the JSONL file
    path = os.environ["TRINO_TPU_HISTORY_PATH"]
    with open(path) as f:
        ids = [json.loads(line)["query_id"] for line in f if line.strip()]
    assert r.query_id in ids


def test_worker_status_and_nodes_table_carry_device_stats(cluster):
    from urllib.request import urlopen
    coord, workers, session = cluster
    with urlopen(f"{workers[0].uri}/v1/status", timeout=10) as resp:
        st = json.loads(resp.read().decode())
    assert st["device"]["platform"] == "cpu"
    assert "bytesInUse" in st["device"]
    # the heartbeat carried it into the node inventory + system table
    deadline = time.time() + 5
    while time.time() < deadline:
        if all(n.device is not None
               for n in coord.state.nodes.values()):
            break
        time.sleep(0.05)
    from trino_tpu.client.client import Client
    r = Client(coord.uri, user="prof").execute(
        "SELECT node_id, reserved_bytes, device_bytes_in_use, "
        "device_bytes_limit FROM system.runtime.nodes")
    assert len(r.rows) >= 2
    for row in r.rows:
        assert int(row[2]) >= 0     # zeros on CPU, live bytes on TPU


def test_distributed_explain_analyze_renders_split(cluster):
    import re
    coord, workers, session = cluster
    coord.state.scheduler.spool.clear()
    from trino_tpu.client.client import Client
    r = Client(coord.uri, user="prof").execute(
        "EXPLAIN ANALYZE " + DIST_SQL)
    text = "\n".join(row[0] for row in r.rows)
    assert "Distributed execution" in text
    m = re.search(r"operator \w+: rows=\d+, wall=[\d.]+ms "
                  r"\(device [\d.]+ \+ host [\d.]+ \+ "
                  r"compile [\d.]+\), calls=\d+", text)
    assert m, text


# ---------------------------------------------------------------------------
# bench --check-regressions gate
# ---------------------------------------------------------------------------

def _round_file(tmp_path, name, configs):
    detail = {cfg: {"tpu_steady_ms": v, "speedup": 1.0}
              for cfg, v in configs.items()}
    line = json.dumps({"metric": "tpch_e2e_sql_to_result_wall_ms",
                       "value": 1.0, "detail": detail})
    p = tmp_path / name
    p.write_text(json.dumps({"n": 1, "cmd": "python bench.py", "rc": 0,
                             "tail": "noise\n" + line + "\n"}))
    return str(p)


def test_check_regressions_flags_injected_3x(tmp_path):
    import bench
    paths = [_round_file(tmp_path, f"BENCH_r0{i}.json", {"q": v})
             for i, v in enumerate((100.0, 110.0, 95.0, 105.0), 1)]
    ok, report = bench.check_regressions(paths)
    assert ok and report["configs"]["q"]["status"] == "ok"
    # injected 3x latency regression in a new round: gate trips
    paths.append(_round_file(tmp_path, "BENCH_r05.json", {"q": 315.0}))
    ok2, report2 = bench.check_regressions(paths)
    assert not ok2
    assert report2["configs"]["q"]["status"] == "REGRESSED"
    assert report2["regressions"] == ["q"]


def test_check_regressions_passes_current_trajectory():
    """The acceptance gate: the repo's own BENCH_r*.json rounds must
    pass (a regression here means the build actually got slower)."""
    import glob

    import bench
    paths = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    ok, report = bench.check_regressions(paths)
    assert ok, report


def test_check_regressions_tolerates_unparseable_rounds(tmp_path):
    import bench
    bad = tmp_path / "BENCH_r01.json"
    bad.write_text("not json")
    killed = tmp_path / "BENCH_r02.json"
    killed.write_text(json.dumps({"n": 2, "rc": 124, "tail": ""}))
    ok, report = bench.check_regressions([str(bad), str(killed)])
    assert ok and report["rounds"] == 0


def test_bench_main_check_regressions_exit_codes(tmp_path, monkeypatch):
    import bench
    for i, v in enumerate((100.0, 101.0, 99.0), 1):
        _round_file(tmp_path, f"BENCH_r0{i}.json", {"q": v})
    monkeypatch.chdir(tmp_path)
    assert bench.main(["--check-regressions"]) == 0
    _round_file(tmp_path, "BENCH_r04.json", {"q": 900.0})
    assert bench.main(["--check-regressions"]) == 1
