"""Window function tests against the sqlite oracle.

Reference pattern: Trino's window operator tests (AbstractTestWindowQueries,
operator/window/ unit tests) — here every query also runs on sqlite (3.25+
implements the same SQL window semantics) over identical TPC-H tiny data.
"""

import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, sql, ordered=True, abs_tol=0.01):
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol,
                      ordered=ordered)
    return got


def test_row_number(session, oracle):
    check(session, oracle, """
        SELECT n_name, n_regionkey,
               row_number() OVER (PARTITION BY n_regionkey
                                  ORDER BY n_name) AS rn
        FROM nation ORDER BY n_regionkey, rn""")


def test_rank_dense_rank(session, oracle):
    check(session, oracle, """
        SELECT o_custkey, o_orderpriority,
               rank() OVER (PARTITION BY o_orderpriority
                            ORDER BY o_custkey) AS r,
               dense_rank() OVER (PARTITION BY o_orderpriority
                                  ORDER BY o_custkey) AS dr
        FROM orders
        ORDER BY o_orderpriority, o_custkey, r""")


def test_running_sum_default_frame(session, oracle):
    # default frame = RANGE UNBOUNDED PRECEDING..CURRENT ROW (peers included)
    check(session, oracle, """
        SELECT o_orderkey, o_custkey,
               sum(o_totalprice) OVER (PARTITION BY o_custkey
                                       ORDER BY o_orderkey) AS rt
        FROM orders ORDER BY o_custkey, o_orderkey""")


def test_partition_total_no_order(session, oracle):
    check(session, oracle, """
        SELECT l_orderkey, l_linenumber,
               sum(l_quantity) OVER (PARTITION BY l_orderkey) AS part_total,
               count(*) OVER (PARTITION BY l_orderkey) AS part_count
        FROM lineitem ORDER BY l_orderkey, l_linenumber""")


def test_rows_frame(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey,
               sum(o_totalprice) OVER (ORDER BY o_orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rt,
               min(o_totalprice) OVER (ORDER BY o_orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS mn,
               max(o_totalprice) OVER (ORDER BY o_orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS mx
        FROM orders ORDER BY o_orderkey""")


def test_unbounded_following_frame(session, oracle):
    check(session, oracle, """
        SELECT n_nationkey,
               sum(n_regionkey) OVER (ORDER BY n_nationkey
                   RANGE BETWEEN UNBOUNDED PRECEDING
                   AND UNBOUNDED FOLLOWING) AS total
        FROM nation ORDER BY n_nationkey""")


def test_lead_lag(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey, o_custkey,
               lag(o_orderkey) OVER (PARTITION BY o_custkey
                                     ORDER BY o_orderkey) AS prev_key,
               lead(o_orderkey, 1, -1) OVER (PARTITION BY o_custkey
                                             ORDER BY o_orderkey) AS next_key
        FROM orders ORDER BY o_custkey, o_orderkey""")


def test_first_last_value(session, oracle):
    check(session, oracle, """
        SELECT l_orderkey, l_linenumber,
               first_value(l_quantity) OVER (PARTITION BY l_orderkey
                                             ORDER BY l_linenumber) AS fv,
               last_value(l_quantity) OVER (PARTITION BY l_orderkey
                   ORDER BY l_linenumber
                   ROWS BETWEEN UNBOUNDED PRECEDING
                   AND UNBOUNDED FOLLOWING) AS lv
        FROM lineitem ORDER BY l_orderkey, l_linenumber""")


def test_ntile(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey,
               ntile(4) OVER (ORDER BY o_orderkey) AS quartile
        FROM orders ORDER BY o_orderkey""")


def test_window_avg(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey, o_custkey,
               avg(o_totalprice) OVER (PARTITION BY o_custkey) AS cavg
        FROM orders ORDER BY o_orderkey""", abs_tol=0.02)


def test_window_over_aggregation(session, oracle):
    # windows over aggregated output: sum(sum(x)) OVER (...)
    check(session, oracle, """
        SELECT o_custkey, sum(o_totalprice) AS t,
               rank() OVER (ORDER BY sum(o_totalprice) DESC) AS r
        FROM orders GROUP BY o_custkey
        ORDER BY r, o_custkey""")


def test_window_varchar_passthrough(session, oracle):
    check(session, oracle, """
        SELECT n_nationkey,
               first_value(n_name) OVER (PARTITION BY n_regionkey
                                         ORDER BY n_nationkey) AS first_name
        FROM nation ORDER BY n_nationkey""")


def test_window_in_expression(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey,
               o_totalprice - avg(o_totalprice) OVER () AS delta
        FROM orders ORDER BY o_orderkey""", abs_tol=0.02)


def test_multiple_window_specs(session, oracle):
    # two different (partition, order) groups -> chained WindowNodes
    check(session, oracle, """
        SELECT o_orderkey,
               row_number() OVER (ORDER BY o_totalprice DESC,
                                  o_orderkey) AS by_price,
               row_number() OVER (PARTITION BY o_orderpriority
                                  ORDER BY o_orderkey) AS by_prio
        FROM orders ORDER BY o_orderkey""")


def test_lead_decimal_default_rescales(session, oracle):
    # the default literal (1.5 at scale 1) must rescale to the column's
    # decimal(12,2) representation
    check(session, oracle, """
        SELECT o_orderkey,
               lead(o_totalprice, 1, 1.5) OVER (ORDER BY o_orderkey) AS nx
        FROM orders ORDER BY o_orderkey""")


def test_agg_inside_over_clause(session, oracle):
    check(session, oracle, """
        SELECT o_custkey,
               rank() OVER (ORDER BY sum(o_totalprice) DESC,
                            o_custkey) AS r
        FROM orders GROUP BY o_custkey ORDER BY r""")


def test_window_with_nulls(session, oracle):
    # lag at partition start is NULL; sum over empty frame is NULL
    got = session.execute("""
        SELECT o_custkey, o_orderkey,
               lag(o_orderkey) OVER (PARTITION BY o_custkey
                                     ORDER BY o_orderkey) AS prev
        FROM orders ORDER BY o_custkey, o_orderkey LIMIT 5""").rows
    assert got[0][2] is None


def test_bounded_rows_frames(session, oracle):
    check(session, oracle, """
        SELECT o_custkey, o_orderdate, o_totalprice,
               sum(o_totalprice) OVER (
                 PARTITION BY o_custkey ORDER BY o_orderdate, o_orderkey
                 ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) mv3,
               count(*) OVER (
                 PARTITION BY o_custkey ORDER BY o_orderdate, o_orderkey
                 ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) w3,
               avg(o_totalprice) OVER (
                 PARTITION BY o_custkey ORDER BY o_orderdate, o_orderkey
                 ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) a4
        FROM orders
        WHERE o_custkey < 200
        ORDER BY o_custkey, o_orderdate, o_orderkey
        LIMIT 300
    """)
