"""Window function tests against the sqlite oracle.

Reference pattern: Trino's window operator tests (AbstractTestWindowQueries,
operator/window/ unit tests) — here every query also runs on sqlite (3.25+
implements the same SQL window semantics) over identical TPC-H tiny data.
"""

import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, sql, ordered=True, abs_tol=0.01):
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol,
                      ordered=ordered)
    return got


def test_row_number(session, oracle):
    check(session, oracle, """
        SELECT n_name, n_regionkey,
               row_number() OVER (PARTITION BY n_regionkey
                                  ORDER BY n_name) AS rn
        FROM nation ORDER BY n_regionkey, rn""")


def test_rank_dense_rank(session, oracle):
    check(session, oracle, """
        SELECT o_custkey, o_orderpriority,
               rank() OVER (PARTITION BY o_orderpriority
                            ORDER BY o_custkey) AS r,
               dense_rank() OVER (PARTITION BY o_orderpriority
                                  ORDER BY o_custkey) AS dr
        FROM orders
        ORDER BY o_orderpriority, o_custkey, r""")


def test_running_sum_default_frame(session, oracle):
    # default frame = RANGE UNBOUNDED PRECEDING..CURRENT ROW (peers included)
    check(session, oracle, """
        SELECT o_orderkey, o_custkey,
               sum(o_totalprice) OVER (PARTITION BY o_custkey
                                       ORDER BY o_orderkey) AS rt
        FROM orders ORDER BY o_custkey, o_orderkey""")


def test_partition_total_no_order(session, oracle):
    check(session, oracle, """
        SELECT l_orderkey, l_linenumber,
               sum(l_quantity) OVER (PARTITION BY l_orderkey) AS part_total,
               count(*) OVER (PARTITION BY l_orderkey) AS part_count
        FROM lineitem ORDER BY l_orderkey, l_linenumber""")


def test_rows_frame(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey,
               sum(o_totalprice) OVER (ORDER BY o_orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rt,
               min(o_totalprice) OVER (ORDER BY o_orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS mn,
               max(o_totalprice) OVER (ORDER BY o_orderkey
                   ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS mx
        FROM orders ORDER BY o_orderkey""")


def test_unbounded_following_frame(session, oracle):
    check(session, oracle, """
        SELECT n_nationkey,
               sum(n_regionkey) OVER (ORDER BY n_nationkey
                   RANGE BETWEEN UNBOUNDED PRECEDING
                   AND UNBOUNDED FOLLOWING) AS total
        FROM nation ORDER BY n_nationkey""")


def test_lead_lag(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey, o_custkey,
               lag(o_orderkey) OVER (PARTITION BY o_custkey
                                     ORDER BY o_orderkey) AS prev_key,
               lead(o_orderkey, 1, -1) OVER (PARTITION BY o_custkey
                                             ORDER BY o_orderkey) AS next_key
        FROM orders ORDER BY o_custkey, o_orderkey""")


def test_first_last_value(session, oracle):
    check(session, oracle, """
        SELECT l_orderkey, l_linenumber,
               first_value(l_quantity) OVER (PARTITION BY l_orderkey
                                             ORDER BY l_linenumber) AS fv,
               last_value(l_quantity) OVER (PARTITION BY l_orderkey
                   ORDER BY l_linenumber
                   ROWS BETWEEN UNBOUNDED PRECEDING
                   AND UNBOUNDED FOLLOWING) AS lv
        FROM lineitem ORDER BY l_orderkey, l_linenumber""")


def test_ntile(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey,
               ntile(4) OVER (ORDER BY o_orderkey) AS quartile
        FROM orders ORDER BY o_orderkey""")


def test_window_avg(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey, o_custkey,
               avg(o_totalprice) OVER (PARTITION BY o_custkey) AS cavg
        FROM orders ORDER BY o_orderkey""", abs_tol=0.02)


def test_window_over_aggregation(session, oracle):
    # windows over aggregated output: sum(sum(x)) OVER (...)
    check(session, oracle, """
        SELECT o_custkey, sum(o_totalprice) AS t,
               rank() OVER (ORDER BY sum(o_totalprice) DESC) AS r
        FROM orders GROUP BY o_custkey
        ORDER BY r, o_custkey""")


def test_window_varchar_passthrough(session, oracle):
    check(session, oracle, """
        SELECT n_nationkey,
               first_value(n_name) OVER (PARTITION BY n_regionkey
                                         ORDER BY n_nationkey) AS first_name
        FROM nation ORDER BY n_nationkey""")


def test_window_in_expression(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey,
               o_totalprice - avg(o_totalprice) OVER () AS delta
        FROM orders ORDER BY o_orderkey""", abs_tol=0.02)


def test_multiple_window_specs(session, oracle):
    # two different (partition, order) groups -> chained WindowNodes
    check(session, oracle, """
        SELECT o_orderkey,
               row_number() OVER (ORDER BY o_totalprice DESC,
                                  o_orderkey) AS by_price,
               row_number() OVER (PARTITION BY o_orderpriority
                                  ORDER BY o_orderkey) AS by_prio
        FROM orders ORDER BY o_orderkey""")


def test_lead_decimal_default_rescales(session, oracle):
    # the default literal (1.5 at scale 1) must rescale to the column's
    # decimal(12,2) representation
    check(session, oracle, """
        SELECT o_orderkey,
               lead(o_totalprice, 1, 1.5) OVER (ORDER BY o_orderkey) AS nx
        FROM orders ORDER BY o_orderkey""")


def test_agg_inside_over_clause(session, oracle):
    check(session, oracle, """
        SELECT o_custkey,
               rank() OVER (ORDER BY sum(o_totalprice) DESC,
                            o_custkey) AS r
        FROM orders GROUP BY o_custkey ORDER BY r""")


def test_window_with_nulls(session, oracle):
    # lag at partition start is NULL; sum over empty frame is NULL
    got = session.execute("""
        SELECT o_custkey, o_orderkey,
               lag(o_orderkey) OVER (PARTITION BY o_custkey
                                     ORDER BY o_orderkey) AS prev
        FROM orders ORDER BY o_custkey, o_orderkey LIMIT 5""").rows
    assert got[0][2] is None


def test_bounded_rows_frames(session, oracle):
    check(session, oracle, """
        SELECT o_custkey, o_orderdate, o_totalprice,
               sum(o_totalprice) OVER (
                 PARTITION BY o_custkey ORDER BY o_orderdate, o_orderkey
                 ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) mv3,
               count(*) OVER (
                 PARTITION BY o_custkey ORDER BY o_orderdate, o_orderkey
                 ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) w3,
               avg(o_totalprice) OVER (
                 PARTITION BY o_custkey ORDER BY o_orderdate, o_orderkey
                 ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) a4
        FROM orders
        WHERE o_custkey < 200
        ORDER BY o_custkey, o_orderdate, o_orderkey
        LIMIT 300
    """)


def test_range_frame_numeric_bounds(session, oracle):
    """RANGE BETWEEN x PRECEDING AND y FOLLOWING: value-offset frames
    over the sorted ORDER BY key (WindowOperator.java:70 frame
    semantics; round-4 verdict weak #8)."""
    check(session, oracle, """
        SELECT o_custkey, o_orderkey,
               sum(o_shippriority + 1) OVER (
                   PARTITION BY o_orderpriority ORDER BY o_custkey
                   RANGE BETWEEN 100 PRECEDING AND 50 FOLLOWING) AS s
        FROM orders ORDER BY o_orderkey LIMIT 500""")


def test_range_frame_preceding_only(session, oracle):
    check(session, oracle, """
        SELECT c_custkey,
               count(*) OVER (ORDER BY c_acctbal
                   RANGE BETWEEN 50000 PRECEDING AND CURRENT ROW) AS c
        FROM customer ORDER BY c_custkey""")


def test_range_frame_desc_order(session, oracle):
    check(session, oracle, """
        SELECT s_suppkey,
               sum(s_nationkey) OVER (ORDER BY s_suppkey DESC
                   RANGE BETWEEN 3 PRECEDING AND 3 FOLLOWING) AS s
        FROM supplier ORDER BY s_suppkey""")


def test_range_frame_unbounded_preceding_value_following(session, oracle):
    check(session, oracle, """
        SELECT n_nationkey,
               sum(n_regionkey) OVER (ORDER BY n_nationkey
                   RANGE BETWEEN UNBOUNDED PRECEDING AND 2 FOLLOWING) AS s
        FROM nation ORDER BY n_nationkey""")


def test_range_frame_with_ties_and_dates(session):
    """Date keys are integer days; peers (equal keys) share frames.
    (sqlite stores dates as TEXT, so ITS range arithmetic is wrong —
    the oracle here is a direct numpy count over day numbers.)"""
    import numpy as np
    got = session.execute("""
        SELECT o_orderkey,
               count(*) OVER (ORDER BY o_orderdate
                   RANGE BETWEEN 30 PRECEDING AND 30 FOLLOWING) AS c
        FROM orders ORDER BY o_orderkey LIMIT 300""").rows
    t = session.catalog.get_table("tpch", "tiny", "orders")
    days = np.asarray(t.columns[t.schema.index_of("o_orderdate")])
    keys = np.asarray(t.columns[t.schema.index_of("o_orderkey")])
    order = np.argsort(keys)
    want = {}
    for k, d in zip(keys[order[:300]], days[order[:300]]):
        want[int(k)] = int(((days >= d - 30) & (days <= d + 30)).sum())
    for k, c in got:
        assert int(c) == want[int(k)], (k, c, want[int(k)])


def test_range_frame_rejects_nonnumeric_key():
    s = Session(default_schema="tiny")
    from trino_tpu.planner.analyzer import AnalysisError
    with pytest.raises(AnalysisError, match="integer-valued"):
        s.execute("""
            SELECT sum(o_shippriority) OVER (ORDER BY o_orderpriority
                RANGE BETWEEN 1 PRECEDING AND 1 FOLLOWING)
            FROM orders""")
    with pytest.raises(AnalysisError, match="one ORDER BY"):
        s.execute("""
            SELECT sum(o_shippriority) OVER (
                ORDER BY o_custkey, o_orderkey
                RANGE BETWEEN 1 PRECEDING AND 1 FOLLOWING)
            FROM orders""")


def test_range_frame_null_keys_match_sqlite():
    """NULL ORDER BY keys: RANGE frames of NULL rows cover their peer
    block; UNBOUNDED PRECEDING frames of non-NULL rows include a leading
    NULL block (SQL 2003 10.9; Trino WindowOperator semantics)."""
    import sqlite3

    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    rows = [(1, 10), (2, None), (3, 5), (4, 20), (5, None), (6, 22)]
    s.execute("CREATE TABLE m.s.t (id bigint, k bigint)")
    s.execute("INSERT INTO m.s.t VALUES " + ", ".join(
        f"({i}, {'NULL' if k is None else k})" for i, k in rows))
    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE t (id INTEGER, k INTEGER)")
    conn.executemany("INSERT INTO t VALUES (?, ?)", rows)
    # explicit NULLS placement: the engines' DEFAULT null ordering
    # differs (this engine: NULLS LAST on ASC; sqlite: NULLS FIRST),
    # and RANGE frames of NULL rows depend on where the NULL block sits
    for order in ("k NULLS FIRST", "k NULLS LAST"):
        for frame in ("RANGE BETWEEN 5 PRECEDING AND 5 FOLLOWING",
                      "RANGE BETWEEN UNBOUNDED PRECEDING AND 3 FOLLOWING",
                      "RANGE BETWEEN CURRENT ROW AND 10 FOLLOWING"):
            q = (f"SELECT id, count(k) OVER (ORDER BY {order} {frame}), "
                 f"sum(k) OVER (ORDER BY {order} {frame}) "
                 f"FROM t ORDER BY id")
            got = [tuple(int(x) if x is not None else None for x in r)
                   for r in s.execute(q).rows]
            want = [tuple(r) for r in conn.execute(q)]
            assert got == want, (order, frame, got, want)
    q = ("SELECT id, count(k) OVER (ORDER BY k DESC NULLS LAST "
         "RANGE BETWEEN 4 PRECEDING AND 4 FOLLOWING) FROM t ORDER BY id")
    got = [tuple(int(x) for x in r) for r in s.execute(q).rows]
    want = [tuple(r) for r in conn.execute(q)]
    assert got == want, (got, want)
