"""Cross-implementation format tests: files produced by pyarrow (an
independent parquet/ORC implementation) must read correctly, including
compressed pages, nested lists, multiple row groups/stripes, and
statistics-based row-group pruning.

Reference pattern: lib/trino-parquet and lib/trino-orc read files from
the whole ecosystem (Spark, Hive, Impala writers) — their test suites
pin golden files from foreign writers. pyarrow plays that role here.
"""

import datetime

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.orc as pa_orc        # noqa: E402
import pyarrow.parquet as pq        # noqa: E402

from trino_tpu.catalog import Catalog                      # noqa: E402
from trino_tpu.connectors.orcdir import OrcConnector       # noqa: E402
from trino_tpu.connectors.parquetdir import ParquetConnector  # noqa: E402
from trino_tpu.exec.session import Session                 # noqa: E402
from trino_tpu.formats.orc import read_orc                 # noqa: E402
from trino_tpu.formats.parquet import (read_parquet,       # noqa: E402
                                       read_parquet_file, write_parquet)


def _mixed_table():
    return pa.table({
        "a": pa.array([1, 2, None, 4], type=pa.int64()),
        "d": pa.array([1.5, 2.5, 3.5, None], type=pa.float64()),
        "s": pa.array(["x", None, "zz", "w"]),
        "arr": pa.array([[1, 2], None, [], [3, None, 5]],
                        type=pa.list_(pa.int64())),
    })


@pytest.mark.parametrize("codec", ["snappy", "gzip", "lz4", "none"])
def test_parquet_codecs_from_pyarrow(tmp_path, codec):
    path = str(tmp_path / f"t_{codec}.parquet")
    pq.write_table(_mixed_table(), path, compression=codec)
    names, cols, valids, logicals = read_parquet(path)
    assert names == ["a", "d", "s", "arr"]
    assert valids[0].tolist() == [True, True, False, True]
    assert cols[0].tolist()[:2] == [1, 2]
    assert cols[2][0] == "x" and valids[2].tolist() == \
        [True, False, True, True]
    # nested LIST with NULL list, empty list, NULL element
    assert logicals[3][0] == "list"
    assert cols[3][0] == (1, 2) and cols[3][2] == ()
    assert cols[3][3] == (3, None, 5)
    assert valids[3].tolist() == [True, False, True, True]


def test_parquet_zstd_mixed_table(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(_mixed_table(), path, compression="zstd")
    names, cols, valids, _ = read_parquet(path)
    assert names and len(cols[0]) == _mixed_table().num_rows


def test_parquet_row_group_pruning_from_stats(tmp_path):
    path = str(tmp_path / "rg.parquet")
    t = pa.table({"k": pa.array(np.arange(10_000), type=pa.int64()),
                  "v": pa.array(np.arange(10_000) * 2,
                                type=pa.int64())})
    pq.write_table(t, path, row_group_size=1000, compression="snappy")
    f = read_parquet_file(path, predicates={"k": (2500, 3500)})
    assert f.total_row_groups == 10
    assert f.skipped_row_groups == 8
    assert f.columns[0].min() == 2000 and f.columns[0].max() == 3999
    # no predicate -> everything
    f2 = read_parquet_file(path)
    assert len(f2.columns[0]) == 10_000


def test_own_writer_cross_read_by_pyarrow(tmp_path):
    path = str(tmp_path / "own.parquet")
    arrays = [np.arange(100, dtype=np.int64),
              np.array([f"s{i % 7}" for i in range(100)], dtype=object)]
    valids = [(np.arange(100) % 5 != 0), None]
    write_parquet(path, ["x", "s"], arrays, valids,
                  compression="gzip", row_group_rows=30)
    t = pq.read_table(path)
    xs = t.column("x").to_pylist()
    assert xs[0] is None and xs[1] == 1 and xs[99] == 99
    assert t.column("s").to_pylist()[:3] == ["s0", "s1", "s2"]
    # our own reader prunes our own statistics
    f = read_parquet_file(path, predicates={"x": (95, 200)})
    assert f.skipped_row_groups == 3


@pytest.mark.parametrize("codec", ["uncompressed", "zlib", "snappy",
                                   "lz4"])
def test_orc_codecs_from_pyarrow(tmp_path, codec):
    path = str(tmp_path / f"t_{codec}.orc")
    t = pa.table({
        "i": pa.array([1, 2, None, 4_000_000_000], type=pa.int64()),
        "d": pa.array([1.5, None, 3.25, -2.0], type=pa.float64()),
        "s": pa.array(["alpha", "beta", None, "alpha"]),
        "b": pa.array([True, False, None, True]),
        "dt": pa.array([datetime.date(1994, 1, 1), None,
                        datetime.date(2000, 6, 15),
                        datetime.date(1970, 1, 1)]),
    })
    pa_orc.write_table(t, path, compression=codec)
    names, cols, valids, logicals = read_orc(path)
    assert names == ["i", "d", "s", "b", "dt"]
    assert cols[0][3] == 4_000_000_000
    assert valids[0].tolist() == [True, True, False, True]
    assert cols[2].tolist()[:2] == ["alpha", "beta"]
    assert cols[3].tolist()[:2] == [True, False]
    assert cols[4][0] == 8766 and logicals[4] == ("date",)


def test_orc_multi_stripe_rlev2_paths(tmp_path):
    path = str(tmp_path / "big.orc")
    n = 200_000
    t = pa.table({
        "k": pa.array(np.arange(n), type=pa.int64()),       # DELTA runs
        "r": pa.array(np.random.default_rng(0).integers(0, 1000, n),
                      type=pa.int64()),                     # DIRECT
        "s": pa.array([f"cat{i % 50}" for i in range(n)]),  # DICTIONARY
    })
    pa_orc.write_table(t, path, compression="zlib",
                       stripe_size=64 * 1024)
    names, cols, valids, logicals = read_orc(path)
    assert cols[0].tolist() == list(range(n))
    want = pa_orc.read_table(path).column("r").to_pylist()
    assert cols[1].tolist() == want
    assert cols[2][137] == "cat37"


def test_sql_over_pyarrow_files(tmp_path):
    """End to end: SQL against pyarrow-written snappy parquet and zlib
    ORC through the directory connectors."""
    (tmp_path / "pq" / "s").mkdir(parents=True)
    (tmp_path / "orc" / "s").mkdir(parents=True)
    n = 5000
    rng = np.random.default_rng(3)
    ks = np.arange(n)
    vs = rng.integers(0, 100, n)
    cats = [f"c{i % 5}" for i in range(n)]
    t = pa.table({"k": pa.array(ks, type=pa.int64()),
                  "v": pa.array(vs, type=pa.int64()),
                  "cat": pa.array(cats)})
    pq.write_table(t, str(tmp_path / "pq" / "s" / "t.parquet"),
                   compression="snappy", row_group_size=1000)
    pa_orc.write_table(t, str(tmp_path / "orc" / "s" / "t.orc"),
                       compression="zlib")
    cat = Catalog()
    cat.register("pq", ParquetConnector(str(tmp_path / "pq")))
    cat.register("orc", OrcConnector(str(tmp_path / "orc")))
    s = Session(catalog=cat, default_cat="pq", default_schema="s")
    want = [("c0", int(vs[0::5].sum())), ("c1", int(vs[1::5].sum())),
            ("c2", int(vs[2::5].sum())), ("c3", int(vs[3::5].sum())),
            ("c4", int(vs[4::5].sum()))]
    for src in ("pq.s.t", "orc.s.t"):
        r = s.execute(f"SELECT cat, sum(v) FROM {src} "
                      "GROUP BY cat ORDER BY cat")
        assert [(a, int(b)) for a, b in r.rows] == want, src


def test_parquet_list_through_connector(tmp_path):
    (tmp_path / "s").mkdir(parents=True)
    t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                  "xs": pa.array([[5, 6], [], [7]],
                                 type=pa.list_(pa.int64()))})
    pq.write_table(t, str(tmp_path / "s" / "t.parquet"),
                   compression="snappy")
    cat = Catalog()
    cat.register("pq", ParquetConnector(str(tmp_path)))
    s = Session(catalog=cat, default_cat="pq", default_schema="s")
    r = s.execute("SELECT id, cardinality(xs) FROM pq.s.t ORDER BY id")
    assert r.rows == [(1, 2), (2, 0), (3, 1)]
    r = s.execute("SELECT id, x FROM pq.s.t, UNNEST(xs) AS u(x) "
                  "ORDER BY id, x")
    assert r.rows == [(1, 5), (1, 6), (3, 7)]


def test_orc_writer_roundtrip_and_pyarrow(tmp_path):
    """Round-4 verdict item #10: ORC write parity — our writer's files
    read back identically through BOTH our reader and pyarrow."""
    import decimal

    import numpy as np
    import pyarrow.orc as po

    from trino_tpu.formats.orc import read_orc, write_orc
    p = str(tmp_path / "w.orc")
    n = 4000
    rng = np.random.default_rng(5)
    names = ["i", "f", "s", "dec", "day", "b"]
    cols = [rng.integers(-1 << 40, 1 << 40, n),
            rng.normal(size=n),
            np.asarray([f"v{i % 13}" for i in range(n)], dtype=object),
            rng.integers(-10**12, 10**12, n),
            rng.integers(0, 20000, n).astype(np.int32),
            rng.integers(0, 2, n).astype(bool)]
    valids = [None, (np.arange(n) % 7 != 0), None, None, None, None]
    logicals = [None, None, None, ("decimal", 18, 4), ("date",), None]
    write_orc(p, names, cols, valids, logicals,
              stripe_rows=1500)                  # multi-stripe
    ns, cs, vs, lg = read_orc(p)
    assert ns == names
    assert np.array_equal(cs[0], cols[0])
    m = valids[1]
    assert np.allclose(cs[1][m], cols[1][m]) and np.array_equal(vs[1], m)
    assert list(cs[2]) == list(cols[2])
    assert np.array_equal(cs[3], cols[3]) and lg[3] == ("decimal", 18, 4)
    assert np.array_equal(cs[4], cols[4]) and lg[4] == ("date",)
    assert np.array_equal(cs[5], cols[5])

    t = po.read_table(p)
    assert t.num_rows == n
    assert t.column("i").to_pylist() == cols[0].tolist()
    f_got = t.column("f").to_pylist()
    assert f_got[0] is None and abs(f_got[1] - cols[1][1]) < 1e-12
    assert t.column("dec").to_pylist()[0] == \
        decimal.Decimal(int(cols[3][0])).scaleb(-4)


def test_orc_timestamp_read_from_pyarrow(tmp_path):
    """TIMESTAMP columns decode (seconds-from-2015 + nanos trick),
    including pre-1970 fractional seconds: the C++ writer (pyarrow)
    stores trunc-toward-zero seconds with sign-carrying nanos, which
    must NOT receive the Java readers' negative-time adjustment."""
    import datetime

    import pyarrow as pa
    import pyarrow.orc as po

    from trino_tpu.formats.orc import read_orc
    ts = [datetime.datetime(2021, 3, 4, 5, 6, 7, 250000),
          datetime.datetime(1999, 12, 31, 23, 59, 59, 1),
          datetime.datetime(2015, 1, 1, 0, 0, 0, 0),
          None,
          datetime.datetime(1969, 12, 31, 23, 59, 59, 500000),
          datetime.datetime(1960, 6, 1, 0, 0, 0, 250000),
          datetime.datetime(1960, 6, 1, 0, 0, 0, 0)]
    p = str(tmp_path / "ts.orc")
    po.write_table(pa.table({"t": pa.array(ts, pa.timestamp("us"))}), p)
    ns, cs, vs, lg = read_orc(p)
    assert lg[0] == ("timestamp",)
    epoch = datetime.datetime(1970, 1, 1)
    for i, want in enumerate(ts):
        if want is None:
            continue
        assert int(cs[0][i]) == int(
            (want - epoch).total_seconds() * 1_000_000), (i, want)
    assert not vs[0][3] and all(vs[0][:3]) and all(vs[0][4:])


def test_orc_timestamp_java_negative_adjustment():
    """Java ORC writers store trunc-toward-zero seconds with POSITIVE
    nanos; a pre-1970 fractional timestamp then needs the reader-side
    secs-1 adjustment (ADVICE round-5: without it those values read one
    second high vs Java). Exercised on raw stream values since our
    writer doesn't emit timestamps."""
    import numpy as np

    from trino_tpu.formats.orc import timestamp_micros
    base = 1420070400
    # 1969-12-31 23:59:58.5: Java stores secs1970 = trunc(-1.5) = -1
    # with nanos = +5e8, encoded (5 << 3) | 7 (8 trailing zeros
    # stripped); the reader must subtract the second back
    secs = np.array([-1 - base], dtype=np.int64)
    nraw = np.array([(5 << 3) | 7], dtype=np.int64)
    assert timestamp_micros(secs, nraw)[0] == -1_500_000
    # the C++ (pyarrow) convention for the same instants: signed nanos,
    # no adjustment — (-5 << 3) | 7 encodes -5e8
    secs = np.array([0 - base, -1 - base], dtype=np.int64)
    nraw = np.array([(-5 << 3) | 7, (-5 << 3) | 7], dtype=np.int64)
    got = timestamp_micros(secs, nraw)
    assert got[0] == -500_000 and got[1] == -1_500_000
    # positive side unaffected: 2015-01-01 00:00:00.000001
    secs = np.array([0], dtype=np.int64)
    nraw = np.array([(1 << 3) | 2], dtype=np.int64)
    assert timestamp_micros(secs, nraw)[0] == base * 1_000_000 + 1


def test_parquet_zstd_read(tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.formats.parquet import read_parquet
    p = str(tmp_path / "z.parquet")
    vals = np.arange(50_000, dtype=np.int64) * 7
    pq.write_table(pa.table({"x": vals}), p, compression="zstd")
    names, cols, valids, _ = read_parquet(p)
    assert names == ["x"]
    assert np.array_equal(cols[0], vals)


def test_orc_zstd_read(tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.orc as po

    from trino_tpu.formats.orc import read_orc
    p = str(tmp_path / "z.orc")
    vals = np.arange(50_000, dtype=np.int64) * 3
    po.write_table(pa.table({"x": vals}), p, compression="zstd")
    ns, cs, vs, lg = read_orc(p)
    assert np.array_equal(cs[0], vals)


def test_orc_connector_export_roundtrip(tmp_path):
    """Engine table -> ORC file -> engine table, through the orcdir
    connector pair (export_table/load_orc) — SQL-level write parity."""
    from trino_tpu.connectors.orcdir import export_table, load_orc
    from trino_tpu.exec.session import Session
    s = Session(default_schema="tiny")
    t = s.catalog.get_table("tpch", "tiny", "nation")
    p = str(tmp_path / "nation.orc")
    export_table(t, p)
    back = load_orc(p, "nation")
    assert [f.name for f in back.schema] == [f.name for f in t.schema]
    for i, f in enumerate(t.schema):
        a, b = np.asarray(t.columns[i]), np.asarray(back.columns[i])
        if f.dictionary is not None:
            ap = np.array(f.dictionary, dtype=object)[a]
            bp = np.array(back.schema.fields[i].dictionary,
                          dtype=object)[b]
            assert list(ap) == list(bp)
        else:
            assert np.array_equal(a, b)
    # and pyarrow can read the exported file
    import pyarrow.orc as po
    assert po.read_table(p).num_rows == t.num_rows
