"""Pallas tiled-gather kernel tests (interpret mode on CPU so tier-1
exercises the real kernel logic): bit-exact parity with the jnp.take
path for windowed offsets, miss sentinels, multi-payload gathers and
non-tile-aligned tails, plus the three probe-site integrations
(ops/join.py dense gather, the windowed-LUT chunk probe, and the
aggregate group readback) with clean fallback when disabled."""

import numpy as np
import pytest

import jax.numpy as jnp

from trino_tpu.batch import batch_from_numpy, batch_to_numpy
from trino_tpu.ops import pallas_gather as pg


def rows_of(batch):
    arrays, valids = batch_to_numpy(batch)
    return [tuple(a[i].item() if v[i] else None
                  for a, v in zip(arrays, valids))
            for i in range(len(arrays[0]))]


def _ref(tables, idx, fills):
    return pg._xla_gather(tables, idx, fills)


@pytest.mark.parametrize("n,w", [(pg.TILE, pg.SLAB),       # aligned
                                 (3000, 5000),             # ragged tail
                                 (17, 129)])               # tiny
def test_gather_matches_take(n, w):
    rng = np.random.default_rng(n + w)
    tables = [
        jnp.asarray(rng.integers(-(1 << 62), 1 << 62, w)),
        jnp.asarray(rng.integers(-100, 100, w).astype(np.int8)),
        jnp.asarray(rng.integers(0, 2, w).astype(bool)),
        jnp.asarray(rng.normal(size=w)),
        jnp.asarray(rng.normal(size=w).astype(np.float32)),
        jnp.asarray(rng.integers(-(1 << 30), 1 << 30, w)
                    .astype(np.int32))]
    idx = jnp.asarray(rng.integers(0, w, n))
    fills = [0, -1, False, 0.0, 0.0, 7]
    got = pg.gather_columns(tables, idx, fills, mode="interpret")
    want = _ref(tables, idx, fills)
    for g, t, wv in zip(got, tables, want):
        assert g.dtype == t.dtype
        assert np.array_equal(np.asarray(g), np.asarray(wv),
                              equal_nan=True)


def test_gather_miss_sentinel_fills():
    rng = np.random.default_rng(0)
    w, n = 2048, 1500
    t = jnp.asarray(rng.integers(-(1 << 40), 1 << 40, w))
    idx = np.asarray(rng.integers(0, w, n))
    idx[::7] = -1                                # miss sentinel
    idx[::11] = w + 3                            # out of range -> fill
    got = pg.gather_columns([t], jnp.asarray(idx), [-5],
                            mode="interpret")[0]
    want = _ref([t], jnp.asarray(idx), [-5])[0]
    assert np.array_equal(np.asarray(got), np.asarray(want))
    assert (np.asarray(got)[::7] == -5).all()


def test_gather_many_tables_plane_groups():
    """More int32 planes than one pallas_call carries -> the wrapper
    splits into groups; results stay exact per table."""
    rng = np.random.default_rng(1)
    w, n = 1000, 900
    n_tables = pg.MAX_PLANES + 3          # int64 tables: 2 planes each
    tables = [jnp.asarray(rng.integers(-(1 << 50), 1 << 50, w))
              for _ in range(n_tables)]
    idx = jnp.asarray(rng.integers(0, w, n))
    got = pg.gather_columns(tables, idx, mode="interpret")
    for g, t in zip(got, tables):
        assert np.array_equal(np.asarray(g), np.asarray(t[idx]))


def test_gather_fallback_when_disabled_or_oversized():
    rng = np.random.default_rng(2)
    t = jnp.asarray(rng.integers(0, 100, 64))
    idx = jnp.asarray(rng.integers(0, 64, 32))
    off = pg.gather_columns([t], idx, mode="off")[0]
    assert np.array_equal(np.asarray(off), np.asarray(t[idx]))
    # above the scan cap the wrapper must fall back, not fail
    big = jnp.zeros(pg.SCAN_MAX_ELEMS + 1, dtype=jnp.int64)
    out = pg.gather_columns([big], idx, mode="interpret")[0]
    assert np.asarray(out).shape == (32,)


def test_windowed_near_sorted_no_escapes():
    rng = np.random.default_rng(3)
    w = 1 << 15
    lut = jnp.asarray(rng.integers(-(1 << 40), 1 << 40, w))
    planes = pg.prepare_word_planes(lut)
    idx = jnp.sort(jnp.asarray(rng.integers(0, w, 4096)))
    word, esc = pg.gather_word_windowed(planes, idx, "int64",
                                        mode="interpret")
    assert int(esc) == 0
    assert np.array_equal(np.asarray(word),
                          np.asarray(lut[idx].astype(jnp.int64)))


def test_windowed_escapes_counted_and_filled():
    """Scattered indices overflow their tile's window: every escaped
    row must come back as the miss word (0) and be counted, so the
    chunked driver's escape check forces the plain rerun."""
    rng = np.random.default_rng(4)
    w = 1 << 15
    lut = jnp.asarray(rng.integers(1, 1 << 40, w))   # nonzero words
    planes = pg.prepare_word_planes(lut)
    idx = jnp.asarray(rng.integers(0, w, 2048))
    word, esc = pg.gather_word_windowed(planes, idx, "int64",
                                        mode="interpret")
    got, want = np.asarray(word), np.asarray(lut[idx].astype(jnp.int64))
    mism = got != want
    assert int(esc) > 0
    assert mism.sum() == int(esc)
    assert (got[mism] == 0).all()


def test_windowed_miss_sentinel_not_escaped():
    rng = np.random.default_rng(5)
    w = 8192
    lut = jnp.asarray(rng.integers(1, 1 << 30, w).astype(np.int32))
    planes = pg.prepare_word_planes(lut)
    idx = np.sort(rng.integers(0, w, 1024))
    idx[::5] = -1
    word, esc = pg.gather_word_windowed(planes, jnp.asarray(idx),
                                        "int32", mode="interpret")
    assert int(esc) == 0
    got = np.asarray(word)
    assert (got[::5] == 0).all()
    ok = idx >= 0
    assert np.array_equal(got[ok], np.asarray(lut)[idx[ok]])


# ---------------------------------------------------------------------------
# probe-site integrations: kernel on vs off must be row-identical
# ---------------------------------------------------------------------------

def _join_fixture(seed=11, domain=2048, nb=500, np_=3000):
    rng = np.random.default_rng(seed)
    bk = rng.permutation(domain)[:nb].astype(np.int64)
    build = batch_from_numpy(
        [bk, rng.integers(-1000, 1000, nb).astype(np.int64),
         rng.normal(size=nb)],
        valids=[None, rng.random(nb) > .2, None])
    probe = batch_from_numpy(
        [rng.integers(-10, domain + 10, np_).astype(np.int64),
         rng.integers(0, 50, np_).astype(np.int64)],
        valids=[rng.random(np_) > .1, None])
    return probe, build, domain


@pytest.mark.parametrize("kind", ["inner", "left", "semi", "anti"])
def test_dense_join_site_parity(kind):
    from trino_tpu.ops.join import join_unique_build_dense
    probe, build, domain = _join_fixture()
    out_off, d0, o0 = join_unique_build_dense(
        probe, build, (0,), (0,), kind, domain)
    out_on, d1, o1 = join_unique_build_dense(
        probe, build, (0,), (0,), kind, domain, "interpret")
    assert rows_of(out_off) == rows_of(out_on)
    assert int(d0) == int(d1) and int(o0) == int(o1)


def test_windowed_join_site_parity():
    from trino_tpu.ops.join import (dense_build_packed_lut,
                                    dense_join_packed,
                                    dense_join_packed_windowed)
    rng = np.random.default_rng(12)
    domain, nb, np_ = 4096, 800, 2048
    bk = rng.permutation(domain)[:nb].astype(np.int64)
    bval = rng.integers(-500, 500, nb).astype(np.int64)
    build = batch_from_numpy([bk, bval])
    meta = ((1, -500, 10, 1, 11),)
    lut, exp, oob, occ = dense_build_packed_lut(build, (0,), domain,
                                                meta, "int32")
    probe = batch_from_numpy(
        [np.sort(rng.integers(0, domain, np_)).astype(np.int64),
         rng.integers(0, 9, np_).astype(np.int64)])
    out_dtypes = ("int64", "int64")
    planes = pg.prepare_word_planes(lut)
    o_xla, e_xla, s_xla = dense_join_packed_windowed(
        probe, lut, (0,), meta, 0, out_dtypes, "inner", 8192)
    o_pal, e_pal, s_pal = dense_join_packed_windowed(
        probe, lut, (0,), meta, 0, out_dtypes, "inner", 8192,
        word_dtype="int32", gather_mode="interpret", lut_planes=planes)
    assert int(e_xla) == 0 and int(e_pal) == 0
    assert int(s_xla) == int(s_pal)
    assert rows_of(o_xla) == rows_of(o_pal)
    # and both agree with the full-table probe
    o_full = dense_join_packed(probe, lut, (0,), meta, 0, out_dtypes,
                               "inner", "interpret")
    assert rows_of(o_full) == rows_of(o_pal)


def test_aggregate_group_gather_parity():
    from trino_tpu.ops.aggregate import AggSpec, sort_group_aggregate
    rng = np.random.default_rng(13)
    n = 4000
    b = batch_from_numpy(
        [rng.integers(0, 40, n), rng.integers(-5, 5, n),
         rng.integers(-100, 100, n)],
        valids=[rng.random(n) > .1, None, rng.random(n) > .2])
    aggs = (AggSpec("sum", 2), AggSpec("count", 2), AggSpec("min", 2),
            AggSpec("max", 2), AggSpec("count_star", None))
    off = sort_group_aggregate(b, (0, 1), aggs, 512)
    on = sort_group_aggregate(b, (0, 1), aggs, 512, "interpret")
    assert rows_of(off) == rows_of(on)


def test_session_property_end_to_end():
    """SET SESSION enable_pallas_gather = true routes the dense join
    probes through the kernel (interpret mode on CPU) and the results
    stay identical to the default path."""
    from trino_tpu.exec.session import Session
    sql = ("SELECT o_orderkey, o_totalprice, c_name"
           " FROM orders o JOIN customer c ON o.o_custkey = c.c_custkey"
           " ORDER BY o_orderkey LIMIT 20")
    want = Session(default_schema="tiny").execute(sql).rows
    s = Session(default_schema="tiny")
    s.execute("SET SESSION enable_pallas_gather = true")
    got = s.execute(sql)
    assert got.rows == want
    assert s.executor.gather_mode() == "interpret"
    assert s.executor.stats.pallas_gather_calls >= 1
    # and off again
    s.execute("SET SESSION enable_pallas_gather = false")
    got2 = s.execute(sql)
    assert got2.rows == want
    assert s.executor.gather_mode() == "off"


def test_gather_micro_harness(tmp_path):
    """bench.py --gather-micro smoke: emits the JSON artifact with
    kernel-vs-take records (interpret mode under JAX_PLATFORMS=cpu)."""
    import bench
    out = bench.gather_micro(table_sizes=[1024], probe_rows=2048,
                             n_tables=2, runs=1,
                             out_path=str(tmp_path / "gm.json"))
    assert out["smoke"] is True and out["mode"] == "interpret"
    kinds = {r["kind"] for r in out["records"]}
    assert kinds == {"scan", "windowed"}
    for r in out["records"]:
        assert r["kernel_ns_per_elem"] > 0
        assert r["take_ns_per_elem"] > 0
    import json
    assert json.load(open(tmp_path / "gm.json"))["records"]
