"""Chaos-hardened fault tolerance tests.

Reference pattern: BaseFailureRecoveryTest (testing/trino-testing/...
/BaseFailureRecoveryTest.java:85) extended chaos-style: seeded fault
schedules (crash / delay / drop / corrupt) fired at every distributed
control-plane boundary must leave query results bit-identical to the
fault-free run — graceful degradation, never wrong answers.

Fast tier here: unit tests for the RetryPolicy backoff, CRC32C page
checksums, the chaos injector, failure-detector hysteresis, plus
in-cluster corruption recovery, straggler hedging (first-success-wins
dedup) and a small seeded soak. The 50-schedule soak is the slow/chaos
tier (`pytest -m chaos`); `bench.py --chaos` runs it standalone.
"""

import threading
import time

import numpy as np
import pytest

from trino_tpu.client.client import Client
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.exchange_spool import ExchangeSpool
from trino_tpu.server.failureinjector import (CORRUPT, CRASH, DELAY, DROP,
                                              RAISE, FailureInjector,
                                              InjectedDrop, InjectedFailure)
from trino_tpu.server.pageserde import (MAGIC, PageChecksumError,
                                        decode_page, encode_page,
                                        verify_page)
from trino_tpu.server.retrypolicy import RetryPolicy
from trino_tpu.server.worker import WorkerServer


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_backoff_schedule_bounded_and_seeded():
    p = RetryPolicy(base_delay_s=0.01, max_delay_s=0.5, max_attempts=6,
                    seed=42)
    d1, d2 = list(p.delays()), list(p.delays())
    assert d1 == d2                       # deterministic per seed
    assert len(d1) == 5                   # attempts - 1 sleeps
    assert all(0.01 <= d <= 0.5 for d in d1)
    # different seeds decorrelate
    assert list(RetryPolicy(0.01, 0.5, 6, seed=7).delays()) != d1


def test_backoff_growth_is_exponential_in_expectation():
    # decorrelated jitter: each delay drawn from [base, prev*3] — the
    # CAP must engage for long schedules (no unbounded growth)
    p = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, max_attempts=50,
                    seed=3)
    ds = list(p.delays())
    assert max(ds) <= 1.0
    assert ds[-1] >= 0.1


def test_retry_call_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    slept = []
    p = RetryPolicy(0.01, 0.1, max_attempts=5, seed=0)
    assert p.call(flaky, sleep=slept.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2


def test_retry_call_exhausts_attempts():
    p = RetryPolicy(0.001, 0.01, max_attempts=3, seed=0)
    calls = []

    def always():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        p.call(always, sleep=lambda d: None)
    assert len(calls) == 3


def test_retry_call_respects_deadline_budget():
    p = RetryPolicy(base_delay_s=10.0, max_delay_s=10.0, max_attempts=5,
                    deadline_s=0.5, seed=0)
    calls = []

    def always():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        p.call(always, sleep=lambda d: None)
    # first sleep (>=10s) would blow the 0.5s budget: exactly one try
    assert len(calls) == 1


def test_retry_call_does_not_catch_unlisted_errors():
    p = RetryPolicy(0.001, 0.01, max_attempts=5)
    with pytest.raises(ValueError):
        p.call(lambda: (_ for _ in ()).throw(ValueError("user error")),
               retry_on=(OSError,))


# ---------------------------------------------------------------------------
# CRC32C page checksums
# ---------------------------------------------------------------------------

def _frame():
    rng = np.random.default_rng(5)
    arrays = [rng.integers(-(1 << 40), 1 << 40, 64),
              rng.random(64)]
    valids = [np.ones(64, np.bool_), rng.random(64) < 0.9]
    return encode_page(arrays, valids)


def test_checksum_roundtrip():
    f = _frame()
    assert f[:4] == MAGIC
    verify_page(f)
    decode_page(f)


def test_every_single_bit_flip_is_detected():
    """CRC32C guarantees all 1-bit errors are caught; sweep EVERY bit of
    a whole frame (header, checksum field and body included) and require
    a detection — the zero-wrong-answer-escape property."""
    f = _frame()
    for bit in range(len(f) * 8):
        buf = bytearray(f)
        buf[bit >> 3] ^= 1 << (bit & 7)
        with pytest.raises((PageChecksumError, ValueError)):
            decode_page(bytes(buf))
            verify_page(bytes(buf))


def test_truncated_frame_rejected():
    f = _frame()
    with pytest.raises(PageChecksumError):
        verify_page(f[: len(f) // 2])
    with pytest.raises(PageChecksumError):
        verify_page(b"TPG2\x00\x01")


def test_legacy_v1_frame_still_decodes():
    """Rolling upgrade: checksum-free TPG1 frames decode unverified."""
    f = _frame()
    legacy = b"TPG1" + f[8:]           # strip the crc field
    verify_page(legacy)
    arrs, _ = decode_page(legacy)
    want, _ = decode_page(f)
    np.testing.assert_array_equal(arrs[0], want[0])


def test_spool_rejects_corrupt_pages_and_self_heals():
    """A corrupt spool container must read as a MISS (work re-dispatches)
    and be deleted so the next attempt rewrites it — never served."""
    spool = ExchangeSpool()
    f = _frame()
    spool.put("k", [f, f])
    assert spool.get("k") == [f, f]
    # flip one bit inside the second page's body, on disk
    import os
    path = spool._path("k")
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0x10
    open(path, "wb").write(bytes(blob))
    assert spool.get("k") is None
    assert spool.checksum_rejects == 1
    assert not os.path.exists(path)       # self-healed: container dropped


def test_spool_write_corruption_injected_is_caught_on_read():
    inj = FailureInjector(seed=9)
    inj.inject("SPOOL_WRITE", times=1, fault=CORRUPT)
    spool = ExchangeSpool(injector=inj)
    spool.put("k", [_frame()])
    assert inj.injected_by_fault[CORRUPT] == 1
    assert spool.get("k") is None         # CRC32C catches the bit-flip


def test_spool_read_write_faults_degrade_to_miss():
    inj = FailureInjector()
    inj.inject("SPOOL_WRITE", times=1, fault=RAISE)
    inj.inject("SPOOL_READ", times=1, fault=DROP)
    spool = ExchangeSpool(injector=inj)
    f = _frame()
    spool.put("k", [f])                   # injected write failure: skipped
    assert spool.write_skips == 1
    spool.put("k", [f])                   # second write succeeds
    assert spool.get("k") is None         # injected read failure: miss
    assert spool.get("k") == [f]          # then recovers


# ---------------------------------------------------------------------------
# FailureInjector
# ---------------------------------------------------------------------------

def test_injector_fault_types():
    inj = FailureInjector(seed=1)
    inj.inject("P", times=1, fault=RAISE)
    with pytest.raises(InjectedFailure):
        inj.maybe_fail("P", "x")
    inj.maybe_fail("P", "x")              # consumed: passes through

    inj.inject("P", times=1, fault=DROP)
    with pytest.raises(ConnectionResetError):   # OSError retry path
        inj.maybe_fail("P", "x")

    inj.inject("P", times=1, fault=DELAY, delay_s=0.15)
    t0 = time.monotonic()
    inj.maybe_fail("P", "x")              # sleeps, does not raise
    assert time.monotonic() - t0 >= 0.14

    assert inj.injected_count == 3
    assert len(inj.events) == 3


def test_injector_match_filters_site_key():
    inj = FailureInjector()
    inj.inject("P", times=5, match_sql="lineitem", fault=RAISE)
    inj.maybe_fail("P", "SELECT 1 FROM nation")      # no match: no fire
    with pytest.raises(InjectedFailure):
        inj.maybe_fail("P", "SELECT count(*) FROM lineitem")


def test_injector_corrupt_only_fires_on_payload_sites():
    inj = FailureInjector(seed=2)
    inj.inject("P", times=1, fault=CORRUPT)
    inj.maybe_fail("P", "x")              # CORRUPT rules skip maybe_fail
    page = _frame()
    out = inj.corrupt_page("P", "x", page)
    assert out != page and len(out) == len(page)
    with pytest.raises(PageChecksumError):
        verify_page(out)
    assert inj.corrupt_page("P", "x", page) == page   # consumed


def test_seeded_schedule_is_deterministic():
    for seed in range(20):
        a = FailureInjector.from_seed(seed).schedule()
        b = FailureInjector.from_seed(seed).schedule()
        assert [(r.point, r.fault, r.remaining, r.delay_s) for r in a] == \
            [(r.point, r.fault, r.remaining, r.delay_s) for r in b]
        for r in a:
            if r.fault == CORRUPT:
                assert r.point in ("SPOOL_WRITE", "EXCHANGE_DRAIN")


# ---------------------------------------------------------------------------
# failure-detector hysteresis (scheduler-reported failures)
# ---------------------------------------------------------------------------

def test_task_failure_engages_detector_hysteresis():
    """_mark_failed must fold into the detector's decayed NodeStats so
    neither a re-announce nor one clean ping resurrects a node whose
    task executor is wedged; sustained clean pings do."""
    from trino_tpu.server.coordinator import CoordinatorState
    from trino_tpu.server.failuredetector import HeartbeatFailureDetector
    state = CoordinatorState(Session(default_schema="tiny"))
    det = HeartbeatFailureDetector(state)          # not started: no pings
    assert state.failure_detector is det
    state.announce("w1", "http://127.0.0.1:1")
    state.scheduler._mark_failed("w1", RuntimeError("boom"))
    assert state.nodes["w1"].state == "FAILED"
    assert det.stats["w1"].failure_ratio > det.threshold
    # the wedged node's announcer keeps running: must NOT flip back
    state.announce("w1", "http://127.0.0.1:1")
    assert state.nodes["w1"].state == "FAILED"
    # several clean heartbeat samples decay the ratio below threshold
    while det.stats["w1"].failure_ratio > det.threshold:
        det.stats["w1"].record(True)
    state.announce("w1", "http://127.0.0.1:1")
    assert state.nodes["w1"].state == "ACTIVE"


# ---------------------------------------------------------------------------
# cluster-level chaos (real HTTP, 3 workers)
# ---------------------------------------------------------------------------

Q_AGG = ("SELECT l_returnflag, l_linestatus, sum(l_quantity) AS q, "
         "count(*) AS c FROM lineitem WHERE l_shipdate <= DATE "
         "'1998-09-02' GROUP BY l_returnflag, l_linestatus "
         "ORDER BY l_returnflag, l_linestatus")
Q_CONCAT = ("SELECT l_orderkey, l_quantity FROM lineitem "
            "WHERE l_shipdate > DATE '1998-11-01'")
Q_SORT = ("SELECT l_orderkey, l_linenumber FROM lineitem "
          "WHERE l_shipdate > DATE '1998-09-01' "
          "ORDER BY l_orderkey, l_linenumber")


def _json_vals(rows):
    return [tuple(v if v is None or isinstance(v, (int, float, str, bool))
                  else str(v) for v in r) for r in rows]


@pytest.fixture(scope="module")
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session, retry_policy="QUERY").start()
    sched = coord.state.scheduler
    sched.split_rows = 8192
    workers = [WorkerServer(f"worker-{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)
    yield coord, workers, session
    for w in workers:
        w.stop()
    coord.stop()


@pytest.fixture(autouse=True)
def _clean(request):
    # only cluster tests pay for (and reset) the cluster
    if "cluster" not in request.fixturenames:
        yield
        return
    coord, workers, _ = request.getfixturevalue("cluster")
    sched = coord.state.scheduler
    sched.spool.clear()
    yield
    sched.failure_injector = None
    sched.spool.injector = None
    for w in workers:
        w.task_manager.injector = None
    # let failed nodes re-announce before the next test
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)


def test_corrupt_page_detected_and_recovered(cluster):
    """A bit-flipped result page must be caught by CRC32C at drain and
    converted into a task retry — identical results, zero escapes."""
    coord, workers, session = cluster
    sched = coord.state.scheduler
    want = _json_vals(session.execute(Q_AGG).rows)
    inj = FailureInjector(seed=101)
    inj.inject("EXCHANGE_DRAIN", times=1, fault=CORRUPT)
    sched.failure_injector = inj
    r = Client(coord.uri, user="chaos").execute(Q_AGG)
    assert r.state == "FINISHED"
    assert _json_vals(r.rows) == want
    assert inj.injected_by_fault[CORRUPT] == 1
    assert sched.stats["checksum_failures"] >= 1
    assert sched.stats["task_retries"] >= 1


def test_straggler_hedged_and_deduped(cluster):
    """A delayed worker's unit is speculatively re-dispatched once it
    exceeds the hedge threshold; the fast attempt wins, the straggler's
    late output is dropped (first-success-wins) — row counts must match
    exactly (no duplicated splits)."""
    coord, workers, session = cluster
    sched = coord.state.scheduler
    want = sorted(_json_vals(session.execute(Q_CONCAT).rows))
    # warm the worker-side fragment (first execution pays XLA compile,
    # which would dominate the drain-time median the hedge keys off)
    Client(coord.uri, user="chaos").execute(Q_CONCAT)
    sched.spool.clear()
    inj = FailureInjector(seed=102)
    inj.inject("WORKER_TASK_RUN", times=1, fault=DELAY, delay_s=3.0)
    workers[0].task_manager.injector = inj
    sched.hedge_min_s, sched.hedge_multiplier = 0.1, 2.0
    hedged_before = sched.stats["hedged_tasks"]
    try:
        t0 = time.monotonic()
        r = Client(coord.uri, user="chaos").execute(Q_CONCAT)
        wall = time.monotonic() - t0
    finally:
        sched.hedge_min_s, sched.hedge_multiplier = 2.0, 4.0
    assert r.state == "FINISHED"
    assert sorted(_json_vals(r.rows)) == want       # exact multiset: dedup
    assert sched.stats["hedged_tasks"] > hedged_before
    assert wall < 2.5, f"hedge did not mitigate the 3s straggler: {wall}"


def test_worker_crash_mid_split_recovers(cluster):
    coord, workers, session = cluster
    sched = coord.state.scheduler
    want = _json_vals(session.execute(Q_AGG).rows)
    inj = FailureInjector(seed=103)
    inj.inject("WORKER_TASK_RUN", times=1, fault=CRASH)
    workers[1].task_manager.injector = inj
    r = Client(coord.uri, user="chaos").execute(Q_AGG)
    assert r.state == "FINISHED"
    assert _json_vals(r.rows) == want
    assert inj.injected_by_fault[CRASH] == 1


def test_task_create_drop_reassigns(cluster):
    coord, workers, session = cluster
    want = _json_vals(session.execute(Q_AGG).rows)
    inj = FailureInjector(seed=104)
    inj.inject("WORKER_TASK_CREATE", times=2, fault=DROP)
    for w in workers:
        w.task_manager.injector = inj
    r = Client(coord.uri, user="chaos").execute(Q_AGG)
    assert r.state == "FINISHED"
    assert _json_vals(r.rows) == want


def test_worker_announce_retries_until_coordinator_up():
    """A worker that boots before its coordinator must not permanently
    fail its announcement — the backoff policy carries it through."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    w = WorkerServer("early-bird", f"http://127.0.0.1:{port}",
                     announce_interval_s=0.1).start()
    try:
        time.sleep(0.2)                 # worker is already failing polls
        coord = CoordinatorServer(Session(default_schema="tiny"),
                                  port=port).start()
        try:
            deadline = time.time() + 5
            while time.time() < deadline:
                if any(n.node_id == "early-bird"
                       for n in coord.state.active_nodes()):
                    break
                time.sleep(0.05)
            assert any(n.node_id == "early-bird"
                       for n in coord.state.active_nodes())
        finally:
            coord.stop()
    finally:
        w.stop()


def test_client_timeout_cancels_server_side_query(cluster):
    """CLIENT_TIMEOUT must DELETE the executing URI before raising so
    the server-side query is canceled, not leaked."""
    from trino_tpu.client.client import QueryError
    coord, workers, session = cluster
    inj = FailureInjector(seed=105)
    # hold the source stage long enough for a 0.3s client budget to lapse
    inj.inject("WORKER_TASK_RUN", times=3, fault=DELAY, delay_s=1.0)
    for w in workers:
        w.task_manager.injector = inj
    client = Client(coord.uri, user="chaos", timeout_s=0.3,
                    poll_interval_s=0.02)
    with pytest.raises(QueryError, match="client timeout"):
        client.execute(Q_AGG)
    # the leaked-query check: the coordinator's tracked query must reach
    # a terminal state promptly (canceled), not keep running
    deadline = time.time() + 10
    tq = coord.state.tracker.all()[-1]
    while not tq.state_machine.is_done() and time.time() < deadline:
        time.sleep(0.05)
    assert tq.state_machine.is_done()
    assert tq.state in ("CANCELED", "FINISHED", "FAILED")


def test_chaos_mini_soak_bit_identical(cluster):
    """Seeded mini-soak (fast tier): randomized schedules over the query
    matrix; every run must return bit-identical rows to the fault-free
    run. The 50-schedule soak runs as -m chaos / bench.py --chaos."""
    coord, workers, session = cluster
    sched = coord.state.scheduler
    client = Client(coord.uri, user="chaos")
    # Q_CONCAT carries no ORDER BY: page arrival order legitimately
    # varies under retry/hedging, so it compares as a multiset (exact
    # rows, any order); ordered queries compare exactly.
    matrix = {
        Q_AGG: (_json_vals(session.execute(Q_AGG).rows), False),
        Q_CONCAT: (sorted(_json_vals(session.execute(Q_CONCAT).rows)),
                   True),
    }
    for seed in range(4):
        inj = FailureInjector.from_seed(seed, max_delay_s=0.2)
        sched.failure_injector = inj
        det = coord.state.failure_detector
        if det is not None:
            det.injector = inj
        for w in workers:
            w.task_manager.injector = inj
        for q, (want, unordered) in matrix.items():
            sched.spool.clear()
            r = client.execute(q)
            assert r.state == "FINISHED", (seed, q)
            got = _json_vals(r.rows)
            if unordered:
                got = sorted(got)
            assert got == want, \
                f"seed {seed} changed results for {q!r}"
        sched.failure_injector = None
        for w in workers:
            w.task_manager.injector = None
        inj.clear()
        # let any FAILED nodes re-announce
        deadline = time.time() + 5
        while len(coord.state.active_nodes()) < 3 and \
                time.time() < deadline:
            time.sleep(0.05)


# ---------------------------------------------------------------------------
# full chaos soak (slow tier; bench.py --chaos is the standalone runner)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_50_schedules(cluster):
    from bench import chaos_soak
    coord, workers, session = cluster
    rec = chaos_soak(n_seeds=50, cluster=(coord, workers, session),
                     out_path=None)
    assert rec["schedules"] == 50
    assert rec["wrong_answers"] == 0
    assert rec["failed_queries"] == 0
    assert rec["injected_total"] > 0


# ---------------------------------------------------------------------------
# cluster-internal shared secret (round-5 medium finding): with
# TRINO_TPU_INTERNAL_SECRET set, the worker data plane and the
# coordinator announce route reject callers without the header — a
# rogue process with network reach can neither join the cluster nor
# pull result pages.
# ---------------------------------------------------------------------------

def test_rogue_announce_and_secretless_page_pull_rejected(monkeypatch):
    import json
    import urllib.error
    from urllib.request import Request, urlopen

    from trino_tpu.server.security import INTERNAL_HEADER

    monkeypatch.setenv("TRINO_TPU_INTERNAL_SECRET", "cluster-secret")
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    worker = WorkerServer("sec-w0", coord.uri, announce_interval_s=0.1,
                          catalog=session.catalog).start()
    try:
        # the legitimate worker announces WITH the header and registers
        deadline = time.time() + 5
        while not coord.state.active_nodes() and time.time() < deadline:
            time.sleep(0.05)
        assert [n.node_id for n in coord.state.active_nodes()] == \
            ["sec-w0"]

        # a rogue worker's announce (no header) is rejected with 401
        # and never enters the node inventory
        body = json.dumps({"nodeId": "rogue", "uri": "http://evil:1"}
                          ).encode()
        req = Request(f"{coord.uri}/v1/announce", data=body,
                      headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urlopen(req, timeout=5)
        assert e.value.code == 401
        assert "rogue" not in coord.state.nodes

        # a secretless page pull off the worker data plane is rejected
        # before any task lookup happens
        with pytest.raises(urllib.error.HTTPError) as e:
            urlopen(f"{worker.uri}/v1/task/any/results/0/0", timeout=5)
        assert e.value.code == 401
        # task status and task creation are equally closed
        with pytest.raises(urllib.error.HTTPError) as e:
            urlopen(f"{worker.uri}/v1/task/any", timeout=5)
        assert e.value.code == 401

        # with the right header the route works (404: unknown task —
        # authentication passed, resource genuinely absent)
        req = Request(f"{worker.uri}/v1/task/any",
                      headers={INTERNAL_HEADER: "cluster-secret"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urlopen(req, timeout=5)
        assert e.value.code == 404

        # a wrong secret is as good as none
        req = Request(f"{worker.uri}/v1/task/any",
                      headers={INTERNAL_HEADER: "wrong"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urlopen(req, timeout=5)
        assert e.value.code == 401

        # liveness + metrics stay open for probes and scrapers
        for route in ("/v1/status", "/v1/metrics"):
            with urlopen(f"{worker.uri}{route}", timeout=5) as resp:
                assert resp.status == 200
    finally:
        worker.stop()
        coord.stop()


def test_secured_cluster_still_executes_distributed(monkeypatch):
    """End-to-end under the shared secret: scheduler task POSTs, status
    polls, and exchange pulls all carry the header, so a secured
    cluster behaves exactly like an open one for its members."""
    monkeypatch.setenv("TRINO_TPU_INTERNAL_SECRET", "s3cret")
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"sec-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(2)]
    try:
        deadline = time.time() + 5
        while len(coord.state.active_nodes()) < 2 and \
                time.time() < deadline:
            time.sleep(0.05)
        client = Client(coord.uri, user="sec")
        r = client.execute(
            "SELECT count(*), sum(l_quantity) FROM lineitem")
        assert r.rows[0][0] > 0
        info = client.query_info(r.query_id)
        assert info["distributed"], info
    finally:
        for w in workers:
            w.stop()
        coord.stop()
