"""Parquet format + connector tests.

Reference pattern: lib/trino-parquet's reader tests (round-trip through
the writer) and the hive connector's TPC-H-on-files suites — the same
queries must verify when the data comes off parquet files instead of the
in-memory generator (TestHiveDistributedQueries pattern).
"""

import os

import numpy as np
import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.connectors.parquetdir import (ParquetConnector, export_table,
                                             load_parquet)
from trino_tpu.exec.session import Session
from trino_tpu.formats.parquet import read_parquet, rle_decode, \
    rle_encode_bitpacked, write_parquet


def test_roundtrip_scalar_types(tmp_path):
    path = str(tmp_path / "t.parquet")
    rng = np.random.default_rng(7)
    n = 10_000
    i64 = rng.integers(-1 << 40, 1 << 40, n)
    i32 = rng.integers(-1 << 20, 1 << 20, n).astype(np.int32)
    f64 = rng.standard_normal(n)
    boo = rng.random(n) < 0.5
    strs = np.array([f"s{v % 97}" for v in i64], dtype=object)
    valid = rng.random(n) < 0.9
    write_parquet(path, ["a", "b", "c", "d", "e"],
                  [i64, i32, f64, boo, strs],
                  [None, valid, None, None, valid])
    names, cols, valids, logicals = read_parquet(path)
    assert names == ["a", "b", "c", "d", "e"]
    np.testing.assert_array_equal(cols[0], i64)
    np.testing.assert_array_equal(cols[1][valid], i32[valid])
    np.testing.assert_array_equal(valids[1], valid)
    np.testing.assert_array_equal(cols[2], f64)
    np.testing.assert_array_equal(cols[3], boo)
    assert list(cols[4][valid]) == list(strs[valid])
    assert valids[0] is None and logicals[0] is None


def test_rle_hybrid_decode_mixed_runs():
    # hand-build: RLE run of 13 ones, bit-packed group of 8, RLE 5 zeros
    from trino_tpu.formats.parquet import _enc_uvarint
    payload = _enc_uvarint(13 << 1) + bytes([1])
    bp = rle_encode_bitpacked(np.array([0, 1, 0, 1, 1, 0, 0, 1]), 1)
    payload += bp
    payload += _enc_uvarint(5 << 1) + bytes([0])
    out = rle_decode(payload, 1, 26)
    want = [1] * 13 + [0, 1, 0, 1, 1, 0, 0, 1] + [0] * 5
    np.testing.assert_array_equal(out, want)


def test_empty_and_all_null_columns(tmp_path):
    path = str(tmp_path / "t.parquet")
    n = 100
    vals = np.arange(n, dtype=np.int64)
    none_valid = np.zeros(n, dtype=np.bool_)
    write_parquet(path, ["x", "y"], [vals, vals], [None, none_valid])
    _, cols, valids, _ = read_parquet(path)
    np.testing.assert_array_equal(cols[0], vals)
    assert not valids[1].any()


@pytest.fixture(scope="module")
def parquet_tpch(tmp_path_factory):
    """Export generated TPC-H tiny to parquet files, serve via the
    connector."""
    root = tmp_path_factory.mktemp("pq")
    os.makedirs(root / "tiny", exist_ok=True)
    session = Session(default_schema="tiny")
    conn = session.catalog.connector("tpch")
    tables = ["region", "nation", "supplier", "customer", "part",
              "partsupp", "orders", "lineitem"]
    for t in tables:
        export_table(conn.get_table("tiny", t),
                     str(root / "tiny" / f"{t}.parquet"))
    pq = ParquetConnector(str(root))
    session.catalog.register("parquet", pq)
    return session, pq, tables


def test_parquet_schema_matches_generator(parquet_tpch):
    session, pq, tables = parquet_tpch
    gen = session.catalog.connector("tpch")
    for t in tables:
        a = gen.get_table("tiny", t)
        b = pq.get_table("tiny", t)
        assert [f.name for f in a.schema] == [f.name for f in b.schema]
        assert a.num_rows == b.num_rows
        for fa, fb, ca, cb in zip(a.schema, b.schema, a.columns,
                                  b.columns):
            assert fa.dtype == fb.dtype, (t, fa.name)


def test_tpch_queries_from_parquet(parquet_tpch):
    """TPC-H off parquet files verifies against the oracle — the
    VERDICT's 'loaded from Parquet passes the verifier suite' gate
    (spot-check: the join/agg-heavy subset)."""
    import sys
    sys.path.insert(0, "tests")
    from tpch_full import QUERIES
    session, pq, tables = parquet_tpch
    oracle = load_oracle([pq.get_table("tiny", t) for t in tables])
    pq_session = Session(catalog=session.catalog, default_cat="parquet",
                         default_schema="tiny")
    for qnum in (1, 3, 5, 6, 10, 18):
        got = pq_session.execute(QUERIES[qnum]).rows
        want = oracle_query(oracle, QUERIES[qnum])
        assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.02,
                          ordered=True)
