"""TPU-native hash aggregation + hybrid hash join (ops/pallas_hash.py),
interpret mode on CPU so tier-1 exercises the real kernel logic.

Property: the hash strategy must be bit-exact vs the sort path — across
int/decimal/varchar-dict/date keys, NULL keys and values, crafted
splitmix64-collision keys, the overflow-escape -> radix-partition ->
re-enter chain, and composition with the round-9 host-spill tier. The
hybrid hash join must match the sorted searchsorted join for every
kind, detect duplicate build keys, and degrade partition-by-partition
when the build exceeds the table.

Shapes stay small (<= 4k rows, 1-2k table slots): the interpreter runs
the per-row insert loop in XLA CPU, so cost scales with rows x planes.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from trino_tpu.batch import batch_from_numpy, batch_to_numpy
from trino_tpu.ops import pallas_hash as ph
from trino_tpu.ops.aggregate import (AggSpec, key_pack_plan,
                                     sort_group_aggregate)


def rows_of(batch):
    live = np.asarray(batch.live)
    out = []
    for i in np.nonzero(live)[0]:
        out.append(tuple(
            (np.asarray(c.data)[i].item()
             if np.asarray(c.valid)[i] else None)
            for c in batch.columns))
    return sorted(out, key=repr)


def run_hash(batch, keys, aggs, slots=1024):
    plan = key_pack_plan(batch, keys)
    assert plan is not None
    kmins, bits = plan
    return ph.hash_group_aggregate(batch, jnp.asarray(kmins), keys,
                                   bits, aggs, slots, "interpret")


AGGS5 = (AggSpec("sum", 1), AggSpec("count", 1), AggSpec("min", 1),
         AggSpec("max", 1), AggSpec("count_star", None))


def test_hash_agg_bitexact_vs_sort_with_nulls():
    """Random int keys (negative too), NULL keys AND NULL values: every
    aggregate state matches the sort kernel bit for bit."""
    rng = np.random.default_rng(7)
    n = 2000
    keys = rng.integers(-40, 160, n)
    vals = rng.integers(-(1 << 52), 1 << 52, n)
    batch = batch_from_numpy(
        [keys, vals], valids=[rng.random(n) > 0.1, rng.random(n) > 0.1])
    out, esc, occ = run_hash(batch, (0,), AGGS5)
    assert int(esc) == 0
    ref = sort_group_aggregate(batch, (0,), AGGS5, 1024)
    assert rows_of(out) == rows_of(ref)
    assert int(occ) == len(rows_of(ref))


def test_hash_agg_multikey_packed_and_null_groups():
    """Two packed key columns; NULL keys form their own groups (SQL
    GROUP BY treats NULLs as equal), exactly like the sort path."""
    rng = np.random.default_rng(8)
    n = 2000
    k1 = rng.integers(0, 12, n)
    k2 = rng.integers(-5, 7, n)
    v = rng.integers(-1000, 1000, n)
    batch = batch_from_numpy(
        [k1, k2, v], valids=[rng.random(n) > 0.2, rng.random(n) > 0.2,
                             None])
    aggs = (AggSpec("sum", 2), AggSpec("count_star", None))
    out, esc, _ = run_hash(batch, (0, 1), aggs)
    assert int(esc) == 0
    ref = sort_group_aggregate(batch, (0, 1), aggs, 1024)
    assert rows_of(out) == rows_of(ref)


def _np_splitmix64(x):
    with np.errstate(over="ignore"):
        z = x + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def test_hash_agg_crafted_collision_keys():
    """Keys crafted so many distinct values share ONE home slot: linear
    probing must keep them distinct groups (equality is on the exact
    packed key, so hash collisions can never merge groups)."""
    slots = 1024
    # packed word for key k with kmin=0 is k+1 (include 0 so kmin=0)
    cands = np.arange(0, 60000, dtype=np.int64)
    with np.errstate(over="ignore"):
        home = (_np_splitmix64(
            (cands + 1).view(np.uint64) + ph._SLOT_SEED)
            % np.uint64(slots)).astype(np.int64)
    target = home[0]
    all_colliders = cands[home == target]
    assert len(all_colliders) >= 8      # the craft actually collided
    colliders = all_colliders[:ph.MAX_PROBES - 4]
    keys = np.concatenate([[0], np.repeat(colliders, 3)])
    vals = np.arange(len(keys), dtype=np.int64) * 7 - 11
    batch = batch_from_numpy([keys, vals])
    aggs = (AggSpec("sum", 1), AggSpec("count_star", None))
    out, esc, occ = run_hash(batch, (0,), aggs, slots)
    assert int(esc) == 0
    ref = sort_group_aggregate(batch, (0,), aggs, 1024)
    assert rows_of(out) == rows_of(ref)
    # a chain DEEPER than the probe bound must escape, never drop rows
    if len(all_colliders) > ph.MAX_PROBES + 4:
        # keep key 0 so kmin stays 0 and the crafted homes still hold
        dk = np.concatenate([[0], all_colliders[:ph.MAX_PROBES + 4]])
        deep = batch_from_numpy([dk, np.ones(len(dk), dtype=np.int64)])
        _, esc2, _ = run_hash(deep, (0,), aggs, slots)
        assert int(esc2) > 0


def test_hash_agg_overflow_escape_counts():
    """More distinct keys than the load cap: the kernel reports the
    breach instead of dropping rows silently."""
    n = 900
    keys = np.arange(n, dtype=np.int64)      # 900 > 640 = 1024 * 5/8
    vals = np.ones(n, dtype=np.int64)
    batch = batch_from_numpy([keys, vals])
    out, esc, occ = run_hash(batch, (0,), (AggSpec("sum", 1),), 1024)
    assert int(esc) > 0
    assert int(occ) <= 1024 * ph.LOAD_NUM // ph.LOAD_DEN


def test_executor_escape_partitions_and_reenters():
    """The executor's escape chain: overflow -> radix partition by the
    spill tier's splitmix64 -> per-partition re-entry, bit-exact."""
    from trino_tpu.exec.executor import Executor
    from trino_tpu.catalog import default_catalog
    ex = Executor(default_catalog())
    ex.enable_pallas_hash = "true"        # interpret on CPU
    ex.hash_table_slots = 1024
    rng = np.random.default_rng(9)
    n = 3000
    keys = rng.integers(0, 1500, n)       # ~1400 groups > 640 cap
    vals = rng.integers(-(1 << 40), 1 << 40, n)
    batch = batch_from_numpy([keys, vals],
                             valids=[rng.random(n) > 0.05, None])
    aggs = (AggSpec("sum", 1), AggSpec("count_star", None))
    out = ex.try_hash_group_agg(batch, (0,), aggs, est_groups=1500)
    assert out is not None
    assert ex.stats.hash_agg_escapes == 1
    ref = sort_group_aggregate(batch, (0,), aggs, 2048)
    assert rows_of(out) == rows_of(ref)


def test_merge_group_aggregate_hash_partial_merge():
    """The chunked driver's FINAL step routes hash-strategy partials
    through the hash-partial merge; states merge exactly."""
    from types import SimpleNamespace
    from trino_tpu.exec.executor import Executor
    from trino_tpu.catalog import default_catalog
    ex = Executor(default_catalog())
    ex.enable_pallas_hash = "true"
    rng = np.random.default_rng(10)
    # two partial pages: (key, sum_state, count_state)
    pages = []
    for seed in (1, 2):
        k = rng.integers(0, 50, 400)
        s = rng.integers(-(1 << 30), 1 << 30, 400)
        c = rng.integers(1, 5, 400)
        pages.append(batch_from_numpy([k, s, c]))
    from trino_tpu.exec.executor import concat_batches
    merged = concat_batches(*pages)
    node = SimpleNamespace(strategy="hash", group_keys=(0,))
    merge_aggs = (AggSpec("sum", 1), AggSpec("sum", 2))
    out = ex.merge_group_aggregate(node, merged, merge_aggs, 1024)
    ref = sort_group_aggregate(merged, (0,), merge_aggs, 1024)
    assert rows_of(out) == rows_of(ref)
    node2 = SimpleNamespace(strategy="sort", group_keys=(0,))
    out2 = ex.merge_group_aggregate(node2, merged, merge_aggs, 1024)
    assert rows_of(out2) == rows_of(ref)


# -- session-level: typed keys, DISTINCT fallback, spill composition -------

@pytest.fixture(scope="module")
def hash_session():
    from trino_tpu.catalog import Catalog, default_catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = default_catalog()
    cat.register("m", MemoryConnector())
    from trino_tpu.exec.session import Session
    s = Session(catalog=cat, default_schema="tiny")
    s.execute("CREATE TABLE m.s.t AS SELECT o_orderdate AS d, "
              "o_orderpriority AS pr, o_totalprice AS v, "
              "o_custkey AS k FROM orders WHERE o_orderkey <= 1600")
    return s


def _hash_on(s, slots=2048):
    s.execute("SET SESSION enable_pallas_hash = true")
    s.execute("SET SESSION hash_agg_mode = force")
    s.execute(f"SET SESSION hash_table_slots = {slots}")


def _hash_off(s):
    s.execute("SET SESSION enable_pallas_hash = false")
    s.execute("SET SESSION hash_agg_mode = auto")
    s.execute("SET SESSION hash_table_slots = 0")


def test_session_hash_agg_date_and_decimal_keys(hash_session):
    """Date keys, decimal(HALF_UP) AVG and sum: hash-forced results are
    row-identical to the default (sort) plan."""
    s = hash_session
    q = ("SELECT d, count(*), sum(v), avg(v) FROM m.s.t "
         "GROUP BY d ORDER BY d")
    _hash_off(s)
    ref = s.execute(q).rows
    _hash_on(s)
    got = s.execute(q).rows
    _hash_off(s)
    assert s.executor.stats.hash_agg_calls >= 1
    assert got == ref


def test_session_hash_agg_varchar_dict_keys(hash_session):
    """Varchar keys ride their dictionary codes through the hash table;
    decoded strings match the sort plan."""
    s = hash_session
    q = ("SELECT pr, count(*), min(k), max(k) FROM m.s.t "
         "GROUP BY pr ORDER BY pr")
    _hash_off(s)
    ref = s.execute(q).rows
    _hash_on(s)
    got = s.execute(q).rows
    assert s.executor.strategy_decisions.get("AggregateNode") == "hash"
    _hash_off(s)
    assert got == ref


def test_session_distinct_aggregate_routes_to_sort(hash_session):
    """DISTINCT aggregates are outside the kernel's contract: even
    under hash_agg_mode=force the planner keeps the sort strategy and
    results stay exact."""
    s = hash_session
    q = ("SELECT pr, count(DISTINCT k) FROM m.s.t "
         "GROUP BY pr ORDER BY pr")
    _hash_off(s)
    ref = s.execute(q).rows
    _hash_on(s)
    got = s.execute(q).rows
    assert s.executor.strategy_decisions.get("AggregateNode") == "sort"
    _hash_off(s)
    assert got == ref


def test_session_hash_agg_spill_composition(hash_session):
    """Overflow-escape + host-spill composition: the round-9 spill tier
    radix-partitions the aggregation with the SAME splitmix64
    partitioner the hash kernel's escape path uses, so spilled
    partitions re-enter the kernel — bit-exact, 0 wrong rows."""
    import time as _time
    from trino_tpu.exec.spill import spill_aggregate
    from trino_tpu.planner import logical as L
    from trino_tpu.planner.optimizer import prune_plan
    s = hash_session
    _hash_on(s, slots=1024)
    s._apply_executor_properties(_time.monotonic())
    _stmt, rel = s.plan("SELECT k, count(*), sum(v) FROM m.s.t "
                        "GROUP BY k")
    root = prune_plan(rel.node)

    def find_agg(node):
        if isinstance(node, L.AggregateNode):
            return node
        for c in L.children(node):
            got = find_agg(c)
            if got is not None:
                return got
        return None

    agg = find_agg(root)
    assert agg is not None and agg.strategy == "hash"
    ex = s.executor
    calls0 = ex.stats.hash_agg_calls
    out = spill_aggregate(ex, agg)          # the 25%-pool retry path
    spilled = ex.stats.spilled_aggregations
    # resident reference with the kernel OFF: the spilled partitions'
    # hash outputs must match the sort path exactly
    ex.enable_pallas_hash = "false"
    ref = ex.run(agg)
    _hash_off(s)
    assert out is not None
    assert spilled >= 1
    assert ex.stats.hash_agg_calls > calls0   # partitions re-entered
    assert rows_of(out) == rows_of(ref)


def test_explain_carries_strategy_lines(hash_session):
    s = hash_session
    _hash_on(s)
    rows = [r[0] for r in s.execute(
        "EXPLAIN SELECT k, count(*) FROM m.s.t GROUP BY k").rows]
    _hash_off(s)
    assert any(r.startswith("agg strategy: hash") for r in rows)
    rows2 = [r[0] for r in s.execute(
        "EXPLAIN SELECT c_name, o_orderdate FROM customer, orders "
        "WHERE c_custkey = o_custkey").rows]
    assert any(r.startswith("join strategy:") for r in rows2)


def test_strategy_decision_metrics_move():
    from trino_tpu.metrics import (AGG_STRATEGY_DECISIONS,
                                   JOIN_STRATEGY_DECISIONS)
    # pre-initialized families (lint also enforces this)
    for strat in ("direct", "sort", "hash"):
        assert AGG_STRATEGY_DECISIONS.has_sample(strategy=strat)
    for strat in ("dense-lut", "hybrid-hash"):
        assert JOIN_STRATEGY_DECISIONS.has_sample(strategy=strat)
    from trino_tpu.exec.session import Session
    s = Session(default_schema="tiny")
    before = AGG_STRATEGY_DECISIONS.value(strategy="direct")
    jsnap = {st: JOIN_STRATEGY_DECISIONS.value(strategy=st)
             for st in ("dense-lut", "sort-merge", "sorted", "expand")}
    s.execute("SELECT l_returnflag, count(*) FROM lineitem "
              "GROUP BY l_returnflag")
    s.execute("SELECT n_name FROM nation, region "
              "WHERE n_regionkey = r_regionkey AND r_name = 'ASIA'")
    assert AGG_STRATEGY_DECISIONS.value(strategy="direct") > before
    ran = s.executor.strategy_decisions.get("JoinNode")
    assert ran in jsnap
    assert JOIN_STRATEGY_DECISIONS.value(strategy=ran) > jsnap[ran]


def test_operator_stats_table_has_strategy_column():
    """The system table carries the per-operator strategy column and
    surfaces what the scheduler rollup recorded."""
    from types import SimpleNamespace
    from trino_tpu.server.system_connector import SystemConnector
    sched = SimpleNamespace(operator_history=[
        {"query_id": "q1", "operator": "AggregateNode", "rows": 10,
         "wall_ms": 1.0, "calls": 1, "strategy": "hash"}])
    state = SimpleNamespace(scheduler=sched)
    conn = SystemConnector(state)
    data = conn.get_table("runtime", "operator_stats")
    names = [f.name for f in data.schema.fields]
    assert "strategy" in names
    # decode through the schema dictionary: the recorded value survives
    j = names.index("strategy")
    fld = data.schema.fields[j]
    code = int(data.columns[j][0])
    assert fld.dictionary[code] == "hash"


# -- hybrid hash join ------------------------------------------------------

def _join_rows(b):
    return rows_of(b)


def test_hash_join_kinds_bitexact_vs_sorted():
    from trino_tpu.ops.join import join_unique_build
    rng = np.random.default_rng(3)
    nb, npr = 400, 1500
    bkeys = rng.permutation(500000)[:nb].astype(np.int64)
    build = batch_from_numpy([bkeys, rng.integers(0, 99, nb)],
                             valids=[rng.random(nb) > 0.05, None])
    pkeys = np.concatenate([bkeys[:200],
                            rng.integers(0, 500000, npr - 200)])
    probe = batch_from_numpy([pkeys.astype(np.int64),
                              rng.integers(0, 9, npr)],
                             valids=[rng.random(npr) > 0.05, None])
    slots, fits = ph.join_table_slots(build.capacity)
    assert fits
    tkl, tkh, src, dup, esc = ph.build_join_table(build, (0,), slots,
                                                  "interpret")
    assert int(dup) == 0 and int(esc) == 0
    for kind in ("inner", "left", "semi", "anti"):
        got = ph.hash_join_probe(probe, build, tkl, tkh, src, (0,),
                                 (0,), kind, "off")
        ref, _ = join_unique_build(probe, build, (0,), (0,), kind)
        assert _join_rows(got) == _join_rows(ref), kind


def test_hash_join_detects_duplicate_build_keys():
    build = batch_from_numpy(
        [np.array([7, 7, 9, 11], dtype=np.int64),
         np.arange(4, dtype=np.int64)])
    tkl, tkh, src, dup, esc = ph.build_join_table(build, (0,), 1024,
                                                  "interpret")
    assert int(dup) == 1 and int(esc) == 0


def test_executor_hash_join_partitioned_degrade():
    """Build bigger than the pinned table: the hybrid path partitions
    both sides by the spill partitioner and joins per partition —
    bit-exact vs the sorted kernel, duplicates handled by expansion."""
    from trino_tpu.catalog import default_catalog
    from trino_tpu.exec.executor import Executor
    from trino_tpu.planner import logical as L
    from trino_tpu.types import BIGINT
    ex = Executor(default_catalog())
    ex.enable_pallas_hash = "true"
    ex.hash_table_slots = 1024
    rng = np.random.default_rng(4)
    nb, npr = 1500, 2000                    # 1500 > 640 load cap
    bkeys = rng.permutation(1 << 20)[:nb].astype(np.int64)
    build = batch_from_numpy([bkeys, rng.integers(0, 99, nb)])
    pkeys = np.concatenate([bkeys[:500],
                            rng.integers(0, 1 << 20, npr - 500)])
    probe = batch_from_numpy([pkeys.astype(np.int64),
                              rng.integers(0, 9, npr)])
    out_cols = tuple((f"c{i}", BIGINT) for i in range(4))
    vals = L.ValuesNode(arrays=(), valids=(), num_rows=0, fields=(),
                        output=out_cols[:2])
    node = L.JoinNode(kind="inner", left=vals, right=vals,
                      left_keys=(0,), right_keys=(0,), residual=None,
                      build_unique=True, output=out_cols)
    status, got = ex.try_hash_join(node, probe, build, allow_dup=False)
    assert status == "ok"
    assert ex.stats.hash_join_escapes == 1
    from trino_tpu.ops.join import join_unique_build
    ref, dup = join_unique_build(probe, build, (0,), (0,), "inner")
    assert int(dup) == 0
    assert _join_rows(got) == _join_rows(ref)


def test_session_membership_join_via_hash(hash_session):
    """Semi join whose build keys are too sparse for the dense LUT
    (values x100000 push past the domain cap): the hash path carries
    it; results match the sorted-fallback plan exactly."""
    s = hash_session
    s.execute("CREATE TABLE m.s.dim AS "
              "SELECT c_custkey * 100000 AS bk FROM customer")
    s.execute("CREATE TABLE m.s.f AS SELECT o_custkey * 100000 AS pk, "
              "o_totalprice AS v FROM orders WHERE o_orderkey <= 4000")
    q = ("SELECT count(*) FROM m.s.f "
         "WHERE EXISTS (SELECT 1 FROM m.s.dim WHERE bk = pk)")
    _hash_off(s)
    ref = s.execute(q).rows
    assert s.executor.strategy_decisions.get("JoinNode") == "sorted"
    s.execute("SET SESSION enable_pallas_hash = true")
    got = s.execute(q).rows
    joined_via = s.executor.strategy_decisions.get("JoinNode")
    _hash_off(s)
    assert got == ref
    assert joined_via == "hybrid-hash"


# -- bench harness ---------------------------------------------------------

def test_agg_micro_smoke_and_regression_series(tmp_path):
    """--agg-micro CPU smoke writes a parseable round; the regression
    gate reads agg-micro rounds as their own config series and flags an
    injected 3x hash-kernel slowdown."""
    import bench
    out = bench.agg_micro(cardinalities=[16], rows=1 << 11, runs=1,
                          out_path=str(tmp_path / "BENCH_agg_micro.json"))
    assert out["records"] and "sort_ms" in out["records"][0]
    parsed = bench.load_bench_round(str(tmp_path /
                                        "BENCH_agg_micro.json"))
    assert parsed and any(k.startswith("agg_micro_g") for k in parsed)
    # synthetic series: 3 healthy rounds, then a 3x regression
    base = {"metric": "agg_micro_ms",
            "records": [{"groups": 16, "rows": 2048, "sort_ms": 9.0,
                         "hash_ms": 3.0}]}
    paths = []
    for i in range(3):
        p = tmp_path / f"r{i}.json"
        p.write_text(json.dumps(base))
        paths.append(str(p))
    bad = {"metric": "agg_micro_ms",
           "records": [{"groups": 16, "rows": 2048, "sort_ms": 9.0,
                        "hash_ms": 9.5}]}
    pbad = tmp_path / "r3.json"
    pbad.write_text(json.dumps(bad))
    ok, report = bench.check_regressions(paths)
    assert ok
    ok2, report2 = bench.check_regressions(paths + [str(pbad)])
    assert not ok2
    assert "agg_micro_g16" in report2["regressions"]
