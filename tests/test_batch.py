"""Unit tests for the columnar Batch/Column data model (Trino Page/Block
analog; reference tests: core/trino-spi/src/test/.../TestPage.java)."""

import numpy as np

from trino_tpu.batch import (Batch, Field, Schema, batch_from_numpy,
                             batch_to_numpy, decode_column, pad_capacity)
from trino_tpu.types import BIGINT, VARCHAR, decimal


def test_pad_capacity_buckets():
    assert pad_capacity(1) == 1024
    assert pad_capacity(1024) == 1024
    assert pad_capacity(1025) == 2048


def test_roundtrip_with_padding():
    a = np.arange(10, dtype=np.int64)
    b = np.array([1.5, 2.5] * 5, dtype=np.float32)
    batch = batch_from_numpy([a, b])
    assert batch.capacity == 1024
    assert int(batch.live.sum()) == 10
    arrays, valids = batch_to_numpy(batch)
    np.testing.assert_array_equal(arrays[0], a)
    np.testing.assert_allclose(arrays[1], b)
    assert valids[0].all()


def test_null_mask_roundtrip():
    a = np.arange(4, dtype=np.int64)
    valid = np.array([True, False, True, False])
    batch = batch_from_numpy([a], valids=[valid])
    arrays, valids = batch_to_numpy(batch)
    np.testing.assert_array_equal(valids[0], valid)


def test_schema_lookup_and_decode():
    schema = Schema.of(
        Field("k", BIGINT),
        Field("s", VARCHAR, dictionary=("apple", "banana")),
        Field("d", decimal(12, 2)),
    )
    assert schema.index_of("s") == 1
    vals = decode_column(schema.field("s"),
                         np.array([1, 0]), np.array([True, True]))
    assert vals == ["banana", "apple"]
    from decimal import Decimal
    dec = decode_column(schema.field("d"),
                        np.array([12345, -50]), np.array([True, False]))
    assert dec == [Decimal("123.45"), None]
    # exactness beyond 2^53 (float would corrupt the low digits)
    big = decode_column(schema.field("d"),
                        np.array([9007199254740995]), np.array([True]))
    assert big == [Decimal("90071992547409.95")]
