"""ROLLUP / CUBE / GROUPING SETS tests.

sqlite has no ROLLUP, so the oracle runs the hand-expanded UNION ALL
equivalent (the same lowering Trino's GroupIdOperator performs).
"""

import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, engine_sql, oracle_sql, abs_tol=0.01):
    got = session.execute(engine_sql).rows
    want = oracle_query(oracle, oracle_sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol)


def test_rollup(session, oracle):
    check(session, oracle, """
        SELECT n_regionkey, n_nationkey, count(*) c
        FROM nation GROUP BY ROLLUP (n_regionkey, n_nationkey)
        ORDER BY n_regionkey NULLS FIRST, n_nationkey NULLS FIRST""", """
        SELECT n_regionkey, n_nationkey, count(*) c FROM nation
          GROUP BY n_regionkey, n_nationkey
        UNION ALL
        SELECT n_regionkey, NULL, count(*) FROM nation
          GROUP BY n_regionkey
        UNION ALL
        SELECT NULL, NULL, count(*) FROM nation
        ORDER BY n_regionkey, n_nationkey""")


def test_cube(session, oracle):
    check(session, oracle, """
        SELECT o_orderstatus, o_orderpriority, sum(o_totalprice) s
        FROM orders GROUP BY CUBE (o_orderstatus, o_orderpriority)
        ORDER BY o_orderstatus NULLS FIRST,
                 o_orderpriority NULLS FIRST""", """
        SELECT o_orderstatus, o_orderpriority, sum(o_totalprice) s
          FROM orders GROUP BY o_orderstatus, o_orderpriority
        UNION ALL
        SELECT o_orderstatus, NULL, sum(o_totalprice) FROM orders
          GROUP BY o_orderstatus
        UNION ALL
        SELECT NULL, o_orderpriority, sum(o_totalprice) FROM orders
          GROUP BY o_orderpriority
        UNION ALL
        SELECT NULL, NULL, sum(o_totalprice) FROM orders
        ORDER BY o_orderstatus, o_orderpriority""")


def test_grouping_sets_explicit(session, oracle):
    check(session, oracle, """
        SELECT o_orderstatus, o_orderpriority, count(*) c
        FROM orders
        GROUP BY GROUPING SETS ((o_orderstatus), (o_orderpriority), ())
        ORDER BY o_orderstatus NULLS FIRST,
                 o_orderpriority NULLS FIRST""", """
        SELECT o_orderstatus, NULL, count(*) c FROM orders
          GROUP BY o_orderstatus
        UNION ALL
        SELECT NULL, o_orderpriority, count(*) FROM orders
          GROUP BY o_orderpriority
        UNION ALL
        SELECT NULL, NULL, count(*) FROM orders
        ORDER BY 1, 2""")


def test_rollup_with_having(session, oracle):
    check(session, oracle, """
        SELECT o_orderstatus, count(*) c
        FROM orders GROUP BY ROLLUP (o_orderstatus)
        HAVING count(*) > 100
        ORDER BY o_orderstatus NULLS FIRST""", """
        SELECT * FROM (
          SELECT o_orderstatus, count(*) c FROM orders
            GROUP BY o_orderstatus
          UNION ALL
          SELECT NULL, count(*) FROM orders)
        WHERE c > 100 ORDER BY o_orderstatus""")


def test_rollup_varchar_key_decode(session):
    rows = session.execute("""
        SELECT n_name, count(*) FROM nation
        GROUP BY ROLLUP (n_name)
        ORDER BY n_name NULLS FIRST LIMIT 3""").rows
    assert rows[0] == (None, 25)
    assert rows[1][1] == 1
