"""Failure-recovery tests.

Reference pattern: BaseFailureRecoveryTest (testing/trino-testing/...
/BaseFailureRecoveryTest.java:85) — inject failures mid-query via the
engine's FailureInjector and assert the query still produces identical
results under the retry policy.

Two tiers: the HTTP-protocol cluster tests stay `slow`; the in-process
dispatcher subset below runs in tier-1 (the round-7 chaos PR's fast
gate — same injection points, no sockets).
"""

import time

import pytest

from trino_tpu.client.client import Client, QueryError
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer, Dispatcher
from trino_tpu.server.failureinjector import FailureInjector
from trino_tpu.server.statemachine import QueryTracker

SQL = ("SELECT n_regionkey, count(*) AS c FROM nation "
       "GROUP BY n_regionkey ORDER BY n_regionkey")


# ---------------------------------------------------------------------------
# fast tier: in-process dispatcher (no HTTP)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dispatcher():
    session = Session(default_schema="tiny")
    tracker = QueryTracker()
    d = Dispatcher(session, tracker, retry_policy="QUERY")
    d.failure_injector = FailureInjector()
    yield d
    d.pool.shutdown(wait=False)


@pytest.fixture(autouse=True)
def _clean_injector(request):
    if "dispatcher" not in request.fixturenames:
        yield
        return
    d = request.getfixturevalue("dispatcher")
    d.failure_injector.clear()
    yield
    d.failure_injector.clear()


def _run(dispatcher, sql, timeout_s=30.0):
    tq = dispatcher.submit(sql, "ft")
    deadline = time.time() + timeout_s
    while not tq.state_machine.is_done() and time.time() < deadline:
        time.sleep(0.01)
    assert tq.state_machine.is_done(), "query did not finish"
    return tq


def test_inprocess_baseline(dispatcher):
    tq = _run(dispatcher, SQL)
    assert tq.state == "FINISHED"
    assert [row[1] for row in tq.result.rows] == [5, 5, 5, 5, 5]


def test_inprocess_recovers_from_dispatch_failure(dispatcher):
    dispatcher.failure_injector.inject("DISPATCH", times=2,
                                       match_sql="n_regionkey")
    tq = _run(dispatcher, SQL)
    assert tq.state == "FINISHED"
    assert [row[1] for row in tq.result.rows] == [5, 5, 5, 5, 5]
    assert tq.retries == 2


def test_inprocess_recovers_from_execution_failure(dispatcher):
    dispatcher.failure_injector.inject("EXECUTION", times=1,
                                       match_sql="n_regionkey")
    tq = _run(dispatcher, SQL)
    assert tq.state == "FINISHED"
    assert tq.retries == 1


def test_inprocess_fails_after_retries_exhausted(dispatcher):
    dispatcher.failure_injector.inject("EXECUTION", times=100,
                                       match_sql="n_regionkey")
    tq = _run(dispatcher, SQL)
    assert tq.state == "FAILED"
    assert "injected" in tq.state_machine.error


def test_inprocess_user_errors_do_not_retry(dispatcher):
    tq = _run(dispatcher, "SELECT nope FROM nation")
    assert tq.state == "FAILED"
    assert tq.retries == 0


def test_inprocess_retry_attempts_are_backed_off(dispatcher):
    """QUERY retries wait between attempts (RetryPolicy jitter) instead
    of hammering the engine back-to-back."""
    dispatcher.failure_injector.inject("DISPATCH", times=2,
                                       match_sql="n_regionkey")
    t0 = time.monotonic()
    tq = _run(dispatcher, SQL)
    assert tq.state == "FINISHED" and tq.retries == 2
    # two backoff sleeps at base >= 0.05s each
    assert time.monotonic() - t0 >= 0.1


# ---------------------------------------------------------------------------
# slow tier: full HTTP statement protocol
# ---------------------------------------------------------------------------

pytest_http = pytest.mark.slow


@pytest.fixture(scope="module")
def cluster():
    coord = CoordinatorServer(Session(default_schema="tiny"),
                              retry_policy="QUERY").start()
    injector = FailureInjector()
    coord.state.dispatcher.failure_injector = injector
    yield coord, injector, Client(coord.uri, user="ft")
    coord.stop()


@pytest.fixture(autouse=True)
def clean_injector(request):
    if "cluster" not in request.fixturenames:
        yield
        return
    _, injector, _ = request.getfixturevalue("cluster")
    injector.clear()
    yield
    injector.clear()


@pytest_http
def test_no_failures_baseline(cluster):
    _, _, client = cluster
    r = client.execute(SQL)
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]


@pytest_http
def test_recovers_from_dispatch_failure(cluster):
    coord, injector, client = cluster
    injector.inject("DISPATCH", times=2, match_sql="n_regionkey")
    r = client.execute(SQL)
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]
    info = client.query_info(r.query_id)
    assert info["retries"] == 2
    assert injector.injected_count >= 2


@pytest_http
def test_recovers_from_execution_failure(cluster):
    coord, injector, client = cluster
    injector.inject("EXECUTION", times=1, match_sql="n_regionkey")
    r = client.execute(SQL)
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]
    assert client.query_info(r.query_id)["retries"] == 1


@pytest_http
def test_fails_after_retries_exhausted(cluster):
    coord, injector, client = cluster
    injector.inject("EXECUTION", times=100, match_sql="n_regionkey")
    with pytest.raises(QueryError) as ei:
        client.execute(SQL)
    assert "injected" in str(ei.value)


@pytest_http
def test_user_errors_do_not_retry(cluster):
    coord, injector, client = cluster
    with pytest.raises(QueryError):
        client.execute("SELECT nope FROM nation")
    # immediate failure: no retry attempts recorded
    queries = client.list_queries()
    failed = [q for q in queries if q["state"] == "FAILED"]
    assert failed
