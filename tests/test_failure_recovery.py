"""Failure-recovery tests.

Reference pattern: BaseFailureRecoveryTest (testing/trino-testing/...
/BaseFailureRecoveryTest.java:85) — inject failures mid-query via the
engine's FailureInjector and assert the query still produces identical
results under the retry policy.
"""

import pytest

pytestmark = pytest.mark.slow

from trino_tpu.client.client import Client, QueryError
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.failureinjector import FailureInjector

SQL = ("SELECT n_regionkey, count(*) AS c FROM nation "
       "GROUP BY n_regionkey ORDER BY n_regionkey")


@pytest.fixture(scope="module")
def cluster():
    coord = CoordinatorServer(Session(default_schema="tiny"),
                              retry_policy="QUERY").start()
    injector = FailureInjector()
    coord.state.dispatcher.failure_injector = injector
    yield coord, injector, Client(coord.uri, user="ft")
    coord.stop()


@pytest.fixture(autouse=True)
def clean_injector(cluster):
    _, injector, _ = cluster
    injector.clear()
    yield
    injector.clear()


def test_no_failures_baseline(cluster):
    _, _, client = cluster
    r = client.execute(SQL)
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]


def test_recovers_from_dispatch_failure(cluster):
    coord, injector, client = cluster
    injector.inject("DISPATCH", times=2, match_sql="n_regionkey")
    r = client.execute(SQL)
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]
    info = client.query_info(r.query_id)
    assert info["retries"] == 2
    assert injector.injected_count >= 2


def test_recovers_from_execution_failure(cluster):
    coord, injector, client = cluster
    injector.inject("EXECUTION", times=1, match_sql="n_regionkey")
    r = client.execute(SQL)
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]
    assert client.query_info(r.query_id)["retries"] == 1


def test_fails_after_retries_exhausted(cluster):
    coord, injector, client = cluster
    injector.inject("EXECUTION", times=100, match_sql="n_regionkey")
    with pytest.raises(QueryError) as ei:
        client.execute(SQL)
    assert "injected" in str(ei.value)


def test_user_errors_do_not_retry(cluster):
    coord, injector, client = cluster
    with pytest.raises(QueryError):
        client.execute("SELECT nope FROM nation")
    # immediate failure: no retry attempts recorded
    queries = client.list_queries()
    failed = [q for q in queries if q["state"] == "FAILED"]
    assert failed
