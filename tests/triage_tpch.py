"""Dev harness: run all 22 TPC-H queries, report pass/fail per query.

Not collected by pytest (no test_ prefix); run directly:
    python tests/triage_tpch.py [qnum...]
"""

import sys
import traceback

sys.path.insert(0, "tests")
import conftest  # noqa: F401  (forces CPU 8-device mesh)

from tpch_full import QUERIES
from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session


def main():
    wanted = [int(a) for a in sys.argv[1:]] or sorted(QUERIES)
    session = Session(default_schema="tiny")
    conn = session.catalog.connector("tpch")
    tables = ["region", "nation", "supplier", "customer", "part",
              "partsupp", "orders", "lineitem"]
    oracle = load_oracle([conn.get_table("tiny", t) for t in tables])
    results = {}
    for q in wanted:
        sql = QUERIES[q]
        try:
            got = session.execute(sql).rows
        except Exception as e:
            results[q] = f"ENGINE-ERROR {type(e).__name__}: {e}"
            if len(wanted) <= 3:
                traceback.print_exc()
            continue
        try:
            want = oracle_query(oracle, sql)
        except Exception as e:
            results[q] = f"ORACLE-ERROR {type(e).__name__}: {e}"
            continue
        try:
            assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.02,
                              ordered=True)
            results[q] = f"PASS ({len(got)} rows)"
        except AssertionError as e:
            results[q] = f"MISMATCH: {str(e)[:200]}"
    print()
    for q in sorted(results):
        print(f"q{q:02d}: {results[q]}")
    n_pass = sum(1 for v in results.values() if v.startswith("PASS"))
    print(f"\n{n_pass}/{len(results)} pass")


if __name__ == "__main__":
    main()
