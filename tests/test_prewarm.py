"""Round-16 cold-start elimination (exec/prewarm.py + friends).

The contracts under test:

- AOT pre-warming: warm a fingerprint once off the query path, then a
  query-path execution of the same statement performs ZERO fresh
  top-level compiles (CompileRecorder-verified, in a fresh process so
  in-process trace caches can't fake it) and credits prewarm hits +
  compile-seconds-saved.
- Shape canonicalization: `bucket_capacity` lands every data-dependent
  cardinality on the enumerable {2^k, 1.5*2^k} lattice, and a sweep of
  TPC-H-shaped statements adds only a bounded number of distinct
  compiled shapes per jit site.
- Shared persistent compile cache: the TRINO_TPU_COMPILE_CACHE gate —
  explicit opt-in persists programs even under JAX_PLATFORMS=cpu,
  explicit "off" wins, and cpu-only defaults to inactive.
- Compile-aware routing: a host-eligible statement routes to the
  bit-exact numpy interpreter while its device program is cold, and the
  SAME fingerprint routes to device once the background warm lands.
- Joining-worker handshake: a worker started with TRINO_TPU_PREWARM=1
  pulls the coordinator's warm-manifest and compiles the canonical
  shapes before announcing ACTIVE.
- The `bench.py --cold-start` regression series gates (median+MAD) and
  bites on an injected cold-wall blowup.
- Prewarm OFF is inert: no cold signal, no threads, no property flips.
"""

import json
import os
import subprocess
import sys
import time
from urllib.request import Request, urlopen

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from trino_tpu.batch import bucket_capacity, pad_capacity   # noqa: E402
from trino_tpu.client.client import Client                  # noqa: E402
from trino_tpu.exec.prewarm import (DEFAULT_MAX_SHAPE,      # noqa: E402
                                    PrewarmEngine,
                                    canonical_lattice,
                                    compile_cache_stats,
                                    prewarm_enabled_by_env)
from trino_tpu.exec.profiler import RECORDER                # noqa: E402
from trino_tpu.exec.session import Session                  # noqa: E402
from trino_tpu.server.coordinator import CoordinatorServer  # noqa: E402
from trino_tpu.server.history import (QueryHistoryStore,    # noqa: E402
                                      plan_fingerprint)
from trino_tpu.server.security import internal_headers      # noqa: E402
from trino_tpu.server.worker import WorkerServer            # noqa: E402


def _run_child(code: str, env_extra: dict, timeout=300):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("TRINO_TPU_COMPILE_CACHE", None)
    env.pop("TRINO_TPU_PREWARM", None)
    env.update(env_extra)
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env,
                          cwd=REPO_ROOT, timeout=timeout)


# ---------------------------------------------------------------------------
# capacity lattice
# ---------------------------------------------------------------------------

def test_bucket_capacity_edges():
    assert bucket_capacity(0) == 1024
    assert bucket_capacity(1) == 1024
    assert bucket_capacity(1023) == 1024
    assert bucket_capacity(1024) == 1024           # exact power stays
    assert bucket_capacity(1025) == 1536           # next half-step
    assert bucket_capacity(1536) == 1536           # exact 1.5*2^k stays
    assert bucket_capacity(1537) == 2048
    assert bucket_capacity(3072) == 3072
    assert bucket_capacity(3073) == 4096
    for k in range(10, 21):
        assert bucket_capacity(1 << k) == 1 << k
        assert bucket_capacity((1 << k) + 1) == 3 << (k - 1)
        assert bucket_capacity(3 << (k - 1)) == 3 << (k - 1)


def test_pad_capacity_edges():
    assert pad_capacity(0) == 1024
    assert pad_capacity(1) == 1024
    assert pad_capacity(1024) == 1024
    assert pad_capacity(1025) == 2048
    assert pad_capacity(5, multiple=4) == 8
    assert pad_capacity(0, multiple=4) == 4


def test_canonical_lattice_covers_every_bucket():
    lat = canonical_lattice(DEFAULT_MAX_SHAPE)
    assert lat[:4] == [1024, 1536, 2048, 3072]
    assert lat == sorted(lat)
    lat_set = set(lat)
    for n in (0, 1, 999, 1024, 1025, 5000, 123457, 999999):
        assert bucket_capacity(n) in lat_set, n


def test_odd_cardinalities_land_on_few_buckets():
    # 541 odd cardinalities collapse to the lattice points in range —
    # the whole point of canonicalization: an enumerable shape set
    ns = range(1, 20000, 37)
    caps = {bucket_capacity(n) for n in ns}
    assert caps <= set(canonical_lattice(1 << 15))
    assert len(caps) <= 10


# ---------------------------------------------------------------------------
# history ranking (top_fingerprints)
# ---------------------------------------------------------------------------

def _hist_rec(qid, sql, end, state="FINISHED"):
    return {"query_id": qid, "sql": sql, "state": state,
            "fingerprint": plan_fingerprint(sql), "end_time": end,
            "elapsed_s": 0.01}


def test_top_fingerprints_ranking():
    store = QueryHistoryStore(path="")
    now = time.time()
    # 3 recent runs beat 5 day-old runs under the 1h-half-life decay
    for i in range(3):
        store.record(_hist_rec(f"a{i}", "SELECT 1", now - 60))
    for i in range(5):
        store.record(_hist_rec(f"b{i}", "SELECT 2", now - 86400))
    store.record(_hist_rec("c0", "SELECT 3", now, state="FAILED"))
    top = store.top_fingerprints(5)
    fps = [e["fingerprint"] for e in top]
    assert fps[0] == plan_fingerprint("SELECT 1")
    assert plan_fingerprint("SELECT 2") in fps
    assert plan_fingerprint("SELECT 3") not in fps   # non-FINISHED
    assert top[0]["count"] == 3
    assert top[0]["sql"] == "SELECT 1"
    assert top[0]["score"] > top[1]["score"]
    assert len(store.top_fingerprints(1)) == 1
    assert store.top_fingerprints(0) == []


def test_top_fingerprints_keeps_latest_sql_per_fingerprint():
    store = QueryHistoryStore(path="")
    now = time.time()
    # same fingerprint, different raw text (normalization collapses
    # case/whitespace); the manifest should re-plan the latest text
    store.record(_hist_rec("x0", "SELECT count(*) FROM nation", now - 50))
    store.record(_hist_rec("x1", "select   COUNT(*) from NATION",
                           now - 10))
    top = store.top_fingerprints(1)
    assert top[0]["count"] == 2
    assert top[0]["sql"] == "select   COUNT(*) from NATION"


# ---------------------------------------------------------------------------
# AOT pre-warming (fresh process: no in-process trace cache can hide)
# ---------------------------------------------------------------------------

def test_fresh_process_aot_warm_then_zero_fresh_compiles():
    code = """
import json
from trino_tpu.exec.session import Session
from trino_tpu.exec.prewarm import PrewarmEngine
from trino_tpu.exec.profiler import RECORDER
from trino_tpu.server.history import plan_fingerprint
s = Session(default_schema="tiny")
eng = PrewarmEngine(session=s, enabled=True)
sql = "SELECT count(*), sum(s_acctbal) FROM supplier"
fp = plan_fingerprint(sql)
assert eng.device_cold(fp)
assert eng.warm_fingerprint(fp, sql)
assert not eng.device_cold(fp)
t0 = RECORDER.totals()
assert t0["compiles"] > 0            # the warm really compiled
res = s.execute(sql)
t1 = RECORDER.totals()
assert t1["compiles"] == t0["compiles"], (t0, t1)   # 0 fresh compiles
assert t1["prewarmHits"] > 0, t1
assert t1["compileSecondsSaved"] > 0, t1
print("PREWARM_OK", json.dumps(t1))
"""
    p = _run_child(code, {})
    assert p.returncode == 0 and "PREWARM_OK" in p.stdout, \
        p.stdout + p.stderr


def test_warm_all_respects_top_n_and_marks_warm():
    store = QueryHistoryStore(path="")
    now = time.time()
    store.record(_hist_rec("w0", "SELECT count(*) FROM region", now))
    store.record(_hist_rec("w1", "SELECT count(*) FROM nation", now - 5))
    s = Session(default_schema="tiny")
    eng = PrewarmEngine(session=s, history=store, enabled=True, top_n=1)
    assert eng.warm_all() == 1
    assert eng.warm_rounds == 1
    assert eng.is_warm(plan_fingerprint("SELECT count(*) FROM region"))
    assert eng.device_cold(plan_fingerprint("SELECT count(*) FROM nation"))


def test_warm_budget_exhaustion_stops_the_pass():
    store = QueryHistoryStore(path="")
    now = time.time()
    for i in range(4):
        store.record(_hist_rec(f"b{i}", f"SELECT {i} FROM region", now))
    s = Session(default_schema="tiny")
    eng = PrewarmEngine(session=s, history=store, enabled=True,
                        top_n=4, budget_s=0.0)
    assert eng.warm_all() == 0           # budget gone before the first


# ---------------------------------------------------------------------------
# shape canonicalization at the jit boundary
# ---------------------------------------------------------------------------

def test_warm_shapes_compiles_once_per_lattice_point():
    eng = PrewarmEngine(enabled=True)
    assert eng.warm_shapes([1024, 1536]) == 2
    c0 = RECORDER.site_shape_counts().get("prewarm.shape", 0)
    assert c0 >= 2
    # a second engine warming the same shapes adds no distinct shapes
    eng2 = PrewarmEngine(enabled=True)
    assert eng2.warm_shapes([1024, 1536]) == 2
    assert RECORDER.site_shape_counts().get("prewarm.shape", 0) == c0


def test_distinct_shapes_bounded_over_tpch_sweep():
    """The canonicalization lint: a sweep of TPC-H-shaped statements
    with varied constants/cardinalities may add only a bounded number
    of distinct compiled shapes per jit site (measured as growth so the
    lint is independent of what ran earlier in this process)."""
    s = Session(default_schema="tiny")
    before = RECORDER.site_shape_counts()
    sweep = [
        "SELECT count(*) FROM lineitem",
        "SELECT sum(l_extendedprice * l_discount) FROM lineitem "
        "WHERE l_quantity < 24",
        "SELECT sum(l_extendedprice * l_discount) FROM lineitem "
        "WHERE l_quantity < 10",
        "SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag ORDER BY l_returnflag",
        "SELECT l_linestatus, sum(l_quantity) FROM lineitem "
        "WHERE l_shipdate > DATE '1995-03-15' GROUP BY l_linestatus",
        "SELECT o_orderpriority, count(*) FROM orders "
        "GROUP BY o_orderpriority ORDER BY o_orderpriority",
        "SELECT count(*) FROM orders WHERE o_orderdate < DATE "
        "'1995-03-15'",
        "SELECT n_name, count(*) FROM nation, region "
        "WHERE n_regionkey = r_regionkey GROUP BY n_name "
        "ORDER BY n_name LIMIT 5",
    ]
    for sql in sweep:
        s.execute(sql)
    after = RECORDER.site_shape_counts()
    grown = {site: n - before.get(site, 0) for site, n in after.items()}
    # expression-keyed sites (filter/project) legitimately add a couple
    # of fingerprints per distinct statement; the lint is that no site
    # explodes past that
    for site, n in grown.items():
        assert n <= 2 * len(sweep), (site, n, grown)
    # the canonicalization property proper: once the adaptive strategy
    # decisions settle (one re-execution pass), further re-executions
    # add ZERO distinct shapes anywhere — every data-dependent
    # cardinality lands back on an already-compiled lattice program
    for sql in sweep:               # adaptation pass (strategy flips)
        s.execute(sql)
    settled = RECORDER.site_shape_counts()
    for sql in sweep:               # steady state: must be pure reuse
        s.execute(sql)
    again = RECORDER.site_shape_counts()
    assert again == settled, {k: again[k] - settled.get(k, 0)
                              for k in again
                              if again[k] != settled.get(k, 0)}


def test_jit_distinct_shapes_gauge_renders():
    from trino_tpu.metrics import REGISTRY
    text = REGISTRY.render()
    assert "# TYPE trino_tpu_jit_distinct_shapes gauge" in text
    assert 'trino_tpu_jit_distinct_shapes{site="exec.fused_chunk"}' \
        in text


# ---------------------------------------------------------------------------
# shared persistent compile cache (the TRINO_TPU_COMPILE_CACHE gate)
# ---------------------------------------------------------------------------

def test_compile_cache_default_inactive_on_cpu():
    if os.environ.get("TRINO_TPU_COMPILE_CACHE"):
        pytest.skip("operator forced a compile cache for this run")
    import trino_tpu
    assert trino_tpu.COMPILE_CACHE_DIR is None
    st = compile_cache_stats()
    assert st["active"] is False and st["dir"] is None


def test_compile_cache_explicit_optin_persists_on_cpu(tmp_path):
    cache = str(tmp_path / "cc")
    code = """
import os, trino_tpu
assert trino_tpu.COMPILE_CACHE_DIR == os.environ["TRINO_TPU_COMPILE_CACHE"]
import jax, jax.numpy as jnp
jax.jit(lambda x: x * 3 + 1)(jnp.arange(2048)).block_until_ready()
files = os.listdir(trino_tpu.COMPILE_CACHE_DIR)
assert files, "explicit CPU opt-in persisted nothing"
from trino_tpu.exec.prewarm import compile_cache_stats
st = compile_cache_stats()
assert st["active"] and st["files"] >= 1 and st["bytes"] > 0, st
print("CACHE_OK", len(files))
"""
    p = _run_child(code, {"TRINO_TPU_COMPILE_CACHE": cache})
    assert p.returncode == 0 and "CACHE_OK" in p.stdout, \
        p.stdout + p.stderr
    assert os.listdir(cache)        # visible to OTHER processes: shared


def test_compile_cache_explicit_off_wins(tmp_path):
    code = """
import trino_tpu
assert trino_tpu.COMPILE_CACHE_DIR is None
print("OFF_OK")
"""
    p = _run_child(code, {"TRINO_TPU_COMPILE_CACHE": "off"})
    assert p.returncode == 0 and "OFF_OK" in p.stdout, p.stdout + p.stderr


# ---------------------------------------------------------------------------
# compile-aware routing: cold -> host, warm -> device, bit-exact
# ---------------------------------------------------------------------------

@pytest.fixture
def coord():
    session = Session(default_schema="tiny")
    c = CoordinatorServer(session, max_concurrency=8).start()
    # deterministic router verdicts (same treatment as test_serving)
    c.state.dispatcher.serving.history = None
    session.history_store = None
    yield c
    c.stop()


def test_cold_routes_host_then_warm_routes_device(coord):
    eng = coord.state.prewarm
    assert eng is not None
    eng.enabled = True
    client = Client(coord.uri, user="prewarm", poll_interval_s=0.005)
    # rows-estimate alone would route this to device; only the cold
    # window may send it host
    client.execute("SET SESSION router_host_max_rows = 0")
    sql = "SELECT count(*) FROM region"
    fp = plan_fingerprint(sql)
    assert eng.device_cold(fp)
    r1 = client.execute(sql)
    assert client.query_info(r1.query_id)["route"] == "host"
    # the serving layer kicked a background warm; wait for it to land
    deadline = time.time() + 30
    while eng.device_cold(fp) and time.time() < deadline:
        time.sleep(0.05)
    assert not eng.device_cold(fp)
    r2 = client.execute(sql)
    assert client.query_info(r2.query_id)["route"] == "device"
    assert r1.rows == r2.rows        # bit-exact across the swap
    eng.enabled = False


def test_device_run_marks_fingerprint_warm(coord):
    eng = coord.state.prewarm
    eng.enabled = True
    client = Client(coord.uri, user="prewarm", poll_interval_s=0.005)
    # not host-eligible (grouped aggregation): runs on device even cold,
    # and the completed run itself closes the cold window
    sql = ("SELECT n_regionkey, count(*) FROM nation "
           "GROUP BY n_regionkey ORDER BY n_regionkey")
    fp = plan_fingerprint(sql)
    assert eng.device_cold(fp)
    r = client.execute(sql)
    assert client.query_info(r.query_id)["route"] == "device"
    assert not eng.device_cold(fp)
    eng.enabled = False


def test_status_and_jit_expose_prewarm_surface(coord):
    with urlopen(f"{coord.uri}/v1/status", timeout=10) as resp:
        status = json.loads(resp.read().decode())
    assert "compileCache" in status and "prewarm" in status
    assert status["prewarm"]["enabled"] is False
    assert status["compileCache"]["active"] in (True, False)
    with urlopen(f"{coord.uri}/v1/jit", timeout=10) as resp:
        jit = json.loads(resp.read().decode())
    assert "distinctShapes" in jit and "prewarm" in jit
    for k in ("prewarmedPrograms", "prewarmHits", "compileSecondsSaved"):
        assert k in jit["prewarm"]


def test_system_tables_expose_prewarm_columns(coord):
    client = Client(coord.uri, user="prewarm", poll_interval_s=0.005)
    r = client.execute("SELECT site, fingerprint, prewarmed, "
                       "prewarm_hits FROM system.runtime.jit_cache")
    assert r.columns[-2:] == ["prewarmed", "prewarm_hits"]
    r = client.execute("SELECT fingerprint, prewarm_rank, prewarm_score "
                       "FROM system.runtime.query_history")
    assert r.columns[-2:] == ["prewarm_rank", "prewarm_score"]


# ---------------------------------------------------------------------------
# joining-worker warm-manifest handshake
# ---------------------------------------------------------------------------

def test_joining_worker_pulls_manifest_and_warms(monkeypatch):
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    try:
        coord.state.prewarm.enabled = True
        monkeypatch.setenv("TRINO_TPU_PREWARM", "1")
        # a tight budget keeps the shape warm to a handful of lattice
        # points so the join isn't slow in CI
        monkeypatch.setenv("TRINO_TPU_PREWARM_BUDGET_S", "5")
        w = WorkerServer("prewarm-w0", coord.uri,
                         announce_interval_s=0.1,
                         catalog=session.catalog).start()
        try:
            deadline = time.time() + 15
            while not coord.state.active_nodes() and \
                    time.time() < deadline:
                time.sleep(0.05)
            assert coord.state.active_nodes(), "worker never ACTIVE"
            assert w.prewarm_manifest is not None
            assert w.prewarm_manifest["shapes"][:2] == [1024, 1536]
            assert w.prewarm is not None
            assert w.prewarm.shape_warms > 0
            # the worker's status heartbeat reports its warm state
            req = Request(f"{w.uri}/v1/status",
                          headers=internal_headers())
            with urlopen(req, timeout=10) as resp:
                st = json.loads(resp.read().decode())
            assert st["prewarm"]["shapeWarms"] == w.prewarm.shape_warms
            assert "compileCache" in st
        finally:
            w.kill()
    finally:
        coord.stop()


def test_manifest_shape(coord):
    m = coord.state.prewarm.manifest()
    assert set(m) == {"enabled", "fingerprints", "shapes", "budget_s"}
    assert m["shapes"] == canonical_lattice()


# ---------------------------------------------------------------------------
# prewarm OFF is today's behavior exactly
# ---------------------------------------------------------------------------

def test_prewarm_off_is_inert(monkeypatch):
    monkeypatch.delenv("TRINO_TPU_PREWARM", raising=False)
    assert prewarm_enabled_by_env() is False
    s = Session(default_schema="tiny")
    eng = PrewarmEngine(session=s)
    assert eng.enabled is False
    assert s.properties["prewarm_chunks"] is False   # no property flip
    assert eng.device_cold("deadbeef") is False      # no cold signal
    assert eng.maybe_start() is False                # no threads
    eng.ensure_warming("deadbeef", "SELECT 1")
    assert eng._threads == []


def test_prewarm_chunks_bit_exact():
    s = Session(default_schema="tiny")
    s.executor.enable_fact_cache = False
    s.execute("SET SESSION spill_chunk_rows = 8192")
    sql = ("SELECT l_returnflag, count(*), sum(l_extendedprice) "
           "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag")
    baseline = s.execute(sql).rows
    assert s.executor.chunk_spans["chunks"] > 1      # chunked path ran
    s.execute("SET SESSION prewarm_chunks = true")
    warmed = s.execute(sql).rows
    assert warmed == baseline


# ---------------------------------------------------------------------------
# bench --cold-start regression series
# ---------------------------------------------------------------------------

def _cold_round(tmp_path, name, q6_cold, q6_steady=50.0):
    recs = [{"query": q, "cold_ms": q6_cold, "steady_ms": q6_steady,
             "ratio": round(q6_cold / q6_steady, 2)}
            for q in ("q3", "q5", "q6")]
    (tmp_path / name).write_text(json.dumps(
        {"metric": "cold_start", "records": recs, "passed": True}))


def test_load_bench_round_parses_cold_record(tmp_path):
    import bench
    _cold_round(tmp_path, "BENCH_cold_r01.json", 120.0, 60.0)
    cfg = bench.load_bench_round(str(tmp_path / "BENCH_cold_r01.json"))
    assert cfg["cold_q6"] == 120.0
    assert cfg["cold_q6_ratio"] == 2.0
    assert cfg["cold_q3"] == 120.0 and cfg["cold_q5"] == 120.0


def test_check_regressions_gates_cold_series(tmp_path, monkeypatch):
    import bench
    _cold_round(tmp_path, "BENCH_cold_r01.json", 100.0)
    _cold_round(tmp_path, "BENCH_cold_r02.json", 110.0)
    _cold_round(tmp_path, "BENCH_cold_r03.json", 95.0)
    monkeypatch.chdir(tmp_path)
    assert bench.main(["--check-regressions"]) == 0
    # injected regression: the cold wall blows up 9x in a new round
    _cold_round(tmp_path, "BENCH_cold_r04.json", 900.0)
    assert bench.main(["--check-regressions"]) == 1
