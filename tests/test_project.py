"""Expression evaluation tests — the PageProcessor-equivalent layer.

Reference tests: core/trino-main/src/test/.../operator/project/ and
QueryAssertions expression assertions (SURVEY.md §4.1)."""

import numpy as np
import pytest

from trino_tpu import ir
from trino_tpu.batch import batch_from_numpy
from trino_tpu.ops.project import (apply_filter, civil_from_days, eval_expr,
                                   filter_project, rescale)
from trino_tpu.types import BIGINT, BOOLEAN, DATE, DOUBLE, decimal


def make_batch():
    a = np.array([1, 2, 3, 4], dtype=np.int64)
    b = np.array([10, 20, 30, 40], dtype=np.int64)
    return batch_from_numpy([a, b], pad_multiple=4)


def col(i, dtype=BIGINT, name=""):
    return ir.ColumnRef(i, dtype, name)


def lit(v, dtype=BIGINT):
    return ir.Literal(v, dtype)


def evaluate(expr, batch, n=4):
    d, v = eval_expr(expr, batch)
    return np.asarray(d)[:n], np.asarray(v)[:n]


def test_arith_and_compare():
    batch = make_batch()
    d, v = evaluate(ir.arith('+', col(0), col(1)), batch)
    np.testing.assert_array_equal(d, [11, 22, 33, 44])
    assert v.all()
    d, v = evaluate(ir.Compare('>', col(1), lit(20)), batch)
    np.testing.assert_array_equal(d, [False, False, True, True])


def test_decimal_arith_scales():
    # 1.50 * 0.10 -> scale 4; 1.50 + 0.1 (scale1) -> scale 2
    a = np.array([150, 250], dtype=np.int64)   # decimal(12,2)
    batch = batch_from_numpy([a], pad_multiple=2)
    c = col(0, decimal(12, 2))
    prod = ir.arith('*', c, ir.Literal(10, decimal(2, 2)))  # 0.10
    assert prod.dtype.scale == 4
    d, _ = evaluate(prod, batch, n=2)
    np.testing.assert_array_equal(d, [1500, 2500])  # 0.1500, 0.2500

    s = ir.arith('+', c, ir.Literal(1, decimal(2, 1)))  # 0.1
    assert s.dtype.scale == 2
    d, _ = evaluate(s, batch, n=2)
    np.testing.assert_array_equal(d, [160, 260])


def test_rescale_half_up():
    import jax.numpy as jnp
    x = jnp.array([125, 135, -125, -135], dtype=jnp.int64)
    out = np.asarray(rescale(x, 2, 1))
    np.testing.assert_array_equal(out, [13, 14, -13, -14])


def test_kleene_and_with_nulls():
    a = np.array([1, 1, 0, 0], dtype=np.bool_)
    valid = np.array([True, False, True, False])
    batch = batch_from_numpy([a, a], valids=[valid, None], pad_multiple=4)
    e = ir.Logical('and', (col(0, BOOLEAN), col(1, BOOLEAN)))
    d, v = evaluate(e, batch)
    # row0: T and T = T; row1: NULL and T = NULL; row2: F and F = F;
    # row3: NULL and F = F (false dominates)
    np.testing.assert_array_equal(v, [True, False, True, True])
    np.testing.assert_array_equal(d & v, [True, False, False, False])


def test_filter_nulls_excluded():
    a = np.array([5, 6, 7, 8], dtype=np.int64)
    valid = np.array([True, True, False, True])
    batch = batch_from_numpy([a], valids=[valid], pad_multiple=4)
    out = apply_filter(batch, ir.Compare('>', col(0), lit(5)))
    np.testing.assert_array_equal(np.asarray(out.live)[:4],
                                  [False, True, False, True])


def test_between_and_in():
    batch = make_batch()
    d, _ = evaluate(ir.Between(col(0), lit(2), lit(3)), batch)
    np.testing.assert_array_equal(d, [False, True, True, False])
    d, _ = evaluate(ir.InList(col(0), (lit(1), lit(4))), batch)
    np.testing.assert_array_equal(d, [True, False, False, True])


def test_case_first_match_wins():
    batch = make_batch()
    e = ir.Case(
        whens=(
            (ir.Compare('<', col(0), lit(3)), lit(100)),
            (ir.Compare('<', col(0), lit(4)), lit(200)),
        ),
        default=lit(300), dtype=BIGINT)
    d, _ = evaluate(e, batch)
    np.testing.assert_array_equal(d, [100, 100, 200, 300])


def test_civil_from_days():
    import jax.numpy as jnp
    import datetime
    days = []
    expect = []
    for s in ["1970-01-01", "1992-02-29", "1998-12-01", "2000-03-01",
              "1995-01-27", "1900-01-01"]:
        dt = datetime.date.fromisoformat(s)
        days.append((dt - datetime.date(1970, 1, 1)).days)
        expect.append((dt.year, dt.month, dt.day))
    y, m, d = civil_from_days(jnp.asarray(days, dtype=jnp.int32))
    for i, (ey, em, ed) in enumerate(expect):
        assert (int(y[i]), int(m[i]), int(d[i])) == (ey, em, ed)


def test_dict_predicate():
    codes = np.array([0, 1, 2, 1], dtype=np.int32)
    batch = batch_from_numpy([codes], pad_multiple=4)
    from trino_tpu.types import VARCHAR
    e = ir.DictPredicate(col(0, VARCHAR), (False, True, False))
    d, _ = evaluate(e, batch)
    np.testing.assert_array_equal(d, [False, True, False, True])


def test_filter_project_jit_caches():
    batch = make_batch()
    f = ir.Compare('>=', col(0), lit(2))
    p = (ir.arith('*', col(0), col(1)),)
    out = filter_project(batch, f, p)
    live = np.asarray(out.live)[:4]
    np.testing.assert_array_equal(live, [False, True, True, True])
    np.testing.assert_array_equal(np.asarray(out.columns[0].data)[:4],
                                  [10, 40, 90, 160])


def test_integer_division_truncates_toward_zero():
    a = np.array([-7, 7, -7, 7], dtype=np.int64)
    b = np.array([2, -2, -2, 2], dtype=np.int64)
    batch = batch_from_numpy([a, b], pad_multiple=4)
    d, v = evaluate(ir.arith('/', col(0), col(1)), batch)
    np.testing.assert_array_equal(d, [-3, -3, 3, 3])
    assert v.all()


def test_division_by_zero_is_null():
    a = np.array([7, 7, 7, 7], dtype=np.int64)
    b = np.array([0, 2, 0, 1], dtype=np.int64)
    batch = batch_from_numpy([a, b], pad_multiple=4)
    d, v = evaluate(ir.arith('/', col(0), col(1)), batch)
    np.testing.assert_array_equal(v, [False, True, False, True])


def test_between_kleene_false_dominates_null():
    # 5 BETWEEN 10 AND NULL -> FALSE (not NULL)
    a = np.array([5], dtype=np.int64)
    batch = batch_from_numpy([a], pad_multiple=1)
    e = ir.Between(col(0), lit(10), ir.Literal(None, BIGINT))
    d, v = evaluate(e, batch, n=1)
    assert v[0] and not d[0]


def test_cast_double_to_decimal_half_up():
    import jax.numpy as jnp
    a = np.array([2.5, -2.5, 2.4], dtype=np.float32)
    batch = batch_from_numpy([a], pad_multiple=4)
    e = ir.Cast(col(0, DOUBLE), decimal(4, 0))
    d, _ = evaluate(e, batch, n=3)
    np.testing.assert_array_equal(d, [3, -3, 2])


def test_decimal_compare_no_int64_overflow():
    # TPC-H q11's HAVING: decimal(p,2) sums compared against a scale-12
    # threshold.  Upscaling the column by 1e10 wraps int64 for values
    # >= ~9.2e8 scaled; the split (hi, lo) comparison must stay exact.
    # threshold = 800000.000000123456 at scale 12 (8.0e17 scaled);
    # column at scale 2: 2e9 scaled (= 2e7) would wrap to 2e19 if upscaled
    big = np.array([2_000_000_000, 90_000_000, 70_000_000],
                   dtype=np.int64)
    batch = batch_from_numpy([big], pad_multiple=4)
    threshold = 800_000 * 10 ** 12 + 123_456    # scale-12 scaled int
    e = ir.Compare('>', col(0, decimal(12, 2)),
                   lit(threshold, decimal(18, 12)))
    d, v = evaluate(e, batch, n=3)
    np.testing.assert_array_equal(d, [True, True, False])
    assert v.all()
    # flipped orientation and the remaining operators
    for op, want in [('<', [False, False, True]), ('=', [False] * 3),
                     ('<>', [True] * 3), ('>=', [True, True, False]),
                     ('<=', [False, False, True])]:
        d, _ = evaluate(ir.Compare(op, col(0, decimal(12, 2)),
                                   lit(threshold, decimal(18, 12))),
                        batch, n=3)
        np.testing.assert_array_equal(d, want, err_msg=op)
        # flipped operand order must agree
        d2, _ = evaluate(ir.Compare(op, lit(threshold, decimal(18, 12)),
                                    col(0, decimal(12, 2))), batch, n=3)
        flip = {'<': '>', '>': '<', '<=': '>=', '>=': '<=',
                '=': '=', '<>': '<>'}[op]
        d3, _ = evaluate(ir.Compare(flip, col(0, decimal(12, 2)),
                                    lit(threshold, decimal(18, 12))),
                         batch, n=3)
        np.testing.assert_array_equal(d2, d3, err_msg=f"flip {op}")
    # exact equality across scales (lo == 0), both orientations
    exact = 900_000 * 10 ** 12                  # 900000.000000000000
    eq = np.array([90_000_000], dtype=np.int64)  # 900000.00 at scale 2
    b2 = batch_from_numpy([eq], pad_multiple=4)
    d, _ = evaluate(ir.Compare('=', col(0, decimal(12, 2)),
                               lit(exact, decimal(18, 12))), b2, n=1)
    assert d[0]
    d, _ = evaluate(ir.Compare('=', lit(exact, decimal(18, 12)),
                               col(0, decimal(12, 2))), b2, n=1)
    assert d[0]
