"""Critical-path wall-time attribution + cluster flight recorder tests.

The two invariants this file defends:
- timeline phases ALWAYS sum exactly to elapsed wall (asserted on live
  distributed queries, on admission-held queries, and on synthetic
  inputs), with the blocking critical path charging the slower of two
  concurrent stages;
- the flight-recorder ring is byte-bounded no matter how long it runs,
  scrapes incrementally via `?since=`, federates worker rings into the
  coordinator's cluster series, and adds zero threads and zero spans
  when telemetry/tracing are off.
"""

import threading
import time
from urllib.error import HTTPError
from urllib.request import Request, urlopen

import pytest

from trino_tpu.client.client import Client
from trino_tpu.events import EventListener
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.security import internal_headers
from trino_tpu.server.telemetry import (FlightRecorder, histogram_deltas,
                                        percentile_from_buckets)
from trino_tpu.server.timeline import (PHASES, attribute_phases,
                                       breakdown_line, critical_path,
                                       dominant_phase)
from trino_tpu.server.worker import WorkerServer
from trino_tpu.utils.tracing import Tracer


# ---------------------------------------------------------------------------
# pure helpers: critical path, attribution, formatting
# ---------------------------------------------------------------------------

def test_critical_path_picks_slower_parallel_stage():
    # source(1s) ; then build-A(1s) || build-B(3s) ; then final(1s):
    # the path charges B (the blocker), never A, never A+B
    ivs = [{"name": "source-stage", "start": 0.0, "end": 1.0},
           {"name": "build-stage[f1]", "start": 1.0, "end": 2.0},
           {"name": "build-stage[f2]", "start": 1.0, "end": 4.0},
           {"name": "final-stage", "start": 4.0, "end": 5.0}]
    total, picks = critical_path(ivs)
    assert total == pytest.approx(5.0)
    assert [p["name"] for p in picks] == \
        ["source-stage", "build-stage[f2]", "final-stage"]
    assert picks[1]["seconds"] == pytest.approx(3.0)


def test_critical_path_transitive_overlap_forms_one_group():
    # A overlaps B, B overlaps C, A does not overlap C — still ONE
    # concurrency group (transitive), charged its longest member
    ivs = [{"name": "a", "start": 0.0, "end": 2.0},
           {"name": "b", "start": 1.0, "end": 5.0},
           {"name": "c", "start": 4.0, "end": 6.0}]
    total, picks = critical_path(ivs)
    assert [p["name"] for p in picks] == ["b"]
    assert total == pytest.approx(4.0)


def test_critical_path_empty():
    assert critical_path([]) == (0.0, [])


def test_attribute_phases_sums_exactly_synthetic():
    ph = attribute_phases(2.0, 0.5, None, None)
    assert ph["queued"] == 0.5
    assert sum(ph.values()) == 2.0
    assert set(ph) == set(PHASES)
    # estimates overrunning the budget scale down, never break the sum
    spans = [{"name": "plan", "durationMs": 5000.0,
              "startTimeUnixNano": 0}]
    ph = attribute_phases(1.0, 0.0, spans, None)
    assert sum(ph.values()) == 1.0
    assert ph["plan"] <= 1.0
    # degenerate walls stay well-formed
    assert sum(attribute_phases(0.0, 0.0, None, None).values()) == 0.0


def test_attribute_phases_write_commit_fallback():
    # untraced writes attribute commit wall from the scheduler's
    # recorded commit_s instead of spans
    ph = attribute_phases(1.0, 0.0, None, None,
                          write_stats={"commit_s": 0.25})
    assert ph["write-commit"] == pytest.approx(0.25)
    assert sum(ph.values()) == 1.0


def test_dominant_phase_prefers_attributed_over_other():
    assert dominant_phase({"queued": 0.4, "other": 0.4, "plan": 0.1}) \
        == "queued"
    assert dominant_phase({"queued": 0.1, "other": 0.5}) == "other"
    assert dominant_phase({}) == ""


def test_breakdown_line_format():
    ph = {p: 0.0 for p in PHASES}
    ph["queued"], ph["device"] = 0.5, 0.25
    line = breakdown_line(ph, 0.75)
    assert line.startswith("critical path: ")
    assert "queued 500.0ms" in line and "device 250.0ms" in line
    assert "plan" not in line            # zero phases elided
    assert "other 0.0ms" in line         # except the residual
    assert line.endswith("= 750.0ms")


# ---------------------------------------------------------------------------
# clock skew: adopt() rebasing + announce-time estimation
# ---------------------------------------------------------------------------

def test_adopt_rebases_remote_spans_by_clock_offset():
    t = Tracer()
    now = time.time()
    remote = {"name": "worker-task",
              "startTimeUnixNano": int((now + 5.0) * 1e9),
              "durationMs": 10.0}
    t.adopt([remote], offset_s=5.0)
    (got,) = t.export()
    assert abs(got["startTimeUnixNano"] / 1e9 - now) < 0.001
    # the caller's dict was copied, not mutated
    assert remote["startTimeUnixNano"] == int((now + 5.0) * 1e9)
    # zero offset adopts verbatim
    t2 = Tracer()
    t2.adopt([remote])
    assert t2.export()[0]["startTimeUnixNano"] == \
        remote["startTimeUnixNano"]


def test_skewed_intervals_normalize_onto_one_clock():
    """A worker 5s in the future must not produce a stage interval that
    starts before the coordinator span that dispatched it."""
    t = Tracer()
    with t.span("source-stage"):
        skewed = {"name": "worker-task",
                  "startTimeUnixNano": int((time.time() + 5.0) * 1e9),
                  "durationMs": 1.0}
        t.adopt([skewed], offset_s=5.0)
    spans = t.export()
    stage = next(s for s in spans if s["name"] == "source-stage")
    task = next(s for s in spans if s["name"] == "worker-task")
    assert task["startTimeUnixNano"] >= stage["startTimeUnixNano"]


# ---------------------------------------------------------------------------
# flight recorder: bounded ring, delta encoding, incremental scrape
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_is_byte_bounded():
    from trino_tpu.metrics import (TELEMETRY_RING_EVICTIONS,
                                   MetricsRegistry)
    reg = MetricsRegistry()
    c = reg.counter("t_events_total", "test counter")
    rec = FlightRecorder("t", interval_s=0, max_bytes=512, registry=reg)
    ev0 = TELEMETRY_RING_EVICTIONS.value()
    for i in range(300):
        c.inc()
        rec.sample_once(now=1000.0 + i)
    assert rec.ring_bytes() <= 512
    assert 1 <= rec.sample_count() < 300
    assert TELEMETRY_RING_EVICTIONS.value() > ev0
    # the oldest samples were the ones evicted
    assert rec.since(0.0)[0]["ts"] > 1000.0


def test_flight_recorder_delta_encoding_and_since():
    from trino_tpu.metrics import MetricsRegistry
    reg = MetricsRegistry()
    c = reg.counter("t_events_total", "test counter")
    g = reg.gauge("t_depth", "test gauge")
    rec = FlightRecorder("t", interval_s=0, registry=reg)
    c.inc(3)
    g.set(7)
    rec.sample_once(now=10.0)
    c.inc(2)                         # gauge unchanged
    s2 = rec.sample_once(now=11.0)
    assert s2["values"] == {"t_events_total": 2.0}   # delta, no gauge
    assert s2["interval_s"] == pytest.approx(1.0)
    g.set(9)                         # counter unchanged
    s3 = rec.sample_once(now=12.0)
    assert s3["values"] == {"t_depth": 9.0}
    # incremental scrape: strictly after the cursor
    assert [s["ts"] for s in rec.since(10.0)] == [11.0, 12.0]
    assert rec.since(12.0) == []


def test_percentile_from_buckets():
    # 50 obs <= 0.1, 50 more in (0.1, 0.5]: the median sits at the
    # first bucket's bound, p99 interpolates inside the second
    buckets = [(0.1, 50.0), (0.5, 100.0), ("+Inf", 100.0)]
    assert percentile_from_buckets(buckets, 0.5) == pytest.approx(0.1)
    p99 = percentile_from_buckets(buckets, 0.99)
    assert 0.1 < p99 <= 0.5
    assert percentile_from_buckets([], 0.5) is None
    assert percentile_from_buckets([(0.1, 0.0)], 0.5) is None
    # everything past the last finite bound reports that bound
    assert percentile_from_buckets([(0.1, 0.0), ("+Inf", 10.0)], 0.99) \
        == pytest.approx(0.1)


def test_histogram_deltas_parses_recorder_samples():
    fam = "trino_tpu_tenant_query_seconds"
    samples = [{"ts": 1.0, "interval_s": 1.0, "values": {
        f"{fam}|alpha_bucket|le=0.1": 5.0,
        f"{fam}|alpha_bucket|le=+Inf": 6.0,
        f"{fam}|alpha_count": 6.0,
        f"{fam}|alpha_sum": 0.9,
        f"{fam}|beta_count": 3.0}}]
    out = histogram_deltas(samples, fam, labelval="alpha")
    assert len(out) == 1
    assert out[0]["count"] == 6.0
    assert ("0.1", 5.0) in out[0]["buckets"]
    p = percentile_from_buckets(out[0]["buckets"], 0.5)
    assert 0.0 < p <= 0.1


# ---------------------------------------------------------------------------
# cluster: end-to-end timelines, telemetry federation, system tables
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"tl-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(2)]
    deadline = time.time() + 15
    while len(coord.state.active_nodes()) < 2 and \
            time.time() < deadline:
        time.sleep(0.05)
    assert len(coord.state.active_nodes()) >= 2
    yield coord, workers, session
    for w in workers:
        w.stop(graceful=False)
    coord.stop()


DIST_SQL = ("SELECT l_returnflag, count(*) AS c FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag")


def test_distributed_timeline_sums_exactly_to_wall(cluster):
    coord, workers, session = cluster
    # cold spool: a durable-exchange hit would skip task dispatch
    coord.state.scheduler.spool.clear()
    client = Client(coord.uri, user="tl")
    client.execute("SET SESSION enable_tracing = true")
    try:
        r = client.execute(DIST_SQL)
        info = client.query_info(r.query_id)
        assert info["distributed"], info["fallbackReason"]
        tq = coord.state.tracker.get(r.query_id)
        tl = tq.timeline
        assert tl is not None
        # THE invariant: phases sum to elapsed wall, exactly
        assert sum(tl["phases"].values()) == tl["wall_s"]
        assert all(v >= 0.0 for v in tl["phases"].values())
        assert set(tl["phases"]) == set(PHASES)
        assert tl["dominant"] in PHASES
        # the stage spans produced a real blocking path made of stages
        assert tl["criticalPathSeconds"] > 0.0
        names = [p["name"] for p in tl["criticalPath"]]
        assert names
        assert all(n.startswith(("source-stage", "build-stage",
                                 "partitioned-exchange", "final-stage",
                                 "distributed-write"))
                   for n in names), names
        assert tl["breakdown"].startswith("critical path: ")
        # ... and the HTTP surface serves the same doc, sum intact
        doc = client._request(
            "GET", f"{coord.uri}/v1/query/{r.query_id}/timeline")
        assert sum(doc["phases"].values()) == doc["wall_s"]
        assert doc["breakdown"] == tl["breakdown"]
    finally:
        client.execute("SET SESSION enable_tracing = false")


def test_timeline_http_404_on_unknown_query(cluster):
    coord, workers, session = cluster
    client = Client(coord.uri, user="tl")
    with pytest.raises(HTTPError):
        client._request("GET", f"{coord.uri}/v1/query/nope_1/timeline")


def test_untraced_timeline_still_sums_and_adds_no_spans(cluster):
    coord, workers, session = cluster
    coord.state.scheduler.spool.clear()
    client = Client(coord.uri, user="tl")
    r = client.execute(DIST_SQL)
    tq = coord.state.tracker.get(r.query_id)
    tl = tq.timeline
    assert tl is not None
    assert sum(tl["phases"].values()) == tl["wall_s"]
    # tracing off: zero spans collected anywhere
    assert (tq.trace or []) == []
    assert session.tracer.export() == []


def test_queued_phase_under_soft_memory_admission_hold():
    from trino_tpu.server.resourcegroups import (ResourceGroupConfig,
                                                 ResourceGroupManager)
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    try:
        disp = coord.state.dispatcher
        # warm the compile caches so the released run is fast enough
        # that the admission hold dominates the wall deterministically
        warm = disp.submit("SELECT count(*) FROM nation", "held")
        deadline = time.time() + 30
        while not warm.state_machine.is_done() and time.time() < deadline:
            time.sleep(0.01)
        rgm = ResourceGroupManager(ResourceGroupConfig(
            "root", hard_concurrency_limit=4,
            soft_memory_limit_bytes=1000))
        disp.resource_groups = rgm
        rgm.set_cluster_memory(5000)       # over the soft limit: hold
        tq = disp.submit("SELECT count(*) FROM nation", "held")
        time.sleep(0.6)
        assert tq.state == "QUEUED"
        for runnable in rgm.set_cluster_memory(100):   # release
            runnable()
        deadline = time.time() + 30
        while not tq.state_machine.is_done() and time.time() < deadline:
            time.sleep(0.01)
        assert tq.state == "FINISHED"
        tl = tq.timeline
        assert tl["phases"]["queued"] >= 0.5
        assert sum(tl["phases"].values()) == tl["wall_s"]
        # the hold dominates this trivial query's wall
        assert tl["dominant"] == "queued"
    finally:
        coord.stop()


def test_announce_now_estimates_clock_offset(cluster):
    coord, workers, session = cluster
    try:
        coord.state.announce("tl-skewed", "http://127.0.0.1:1",
                             state="DRAINING", now=time.time() + 5.0)
        node = coord.state.nodes["tl-skewed"]
        assert 4.5 < node.clock_offset < 5.5
        # refresh updates the estimate
        coord.state.announce("tl-skewed", "http://127.0.0.1:1",
                             state="DRAINING", now=time.time() - 2.0)
        assert -2.5 < coord.state.nodes["tl-skewed"].clock_offset < -1.5
        # a real worker's offset is ~zero (same host clock)
        real = coord.state.nodes[workers[0].node_id]
        assert abs(real.clock_offset) < 1.0
    finally:
        coord.state.announce("tl-skewed", "", state="LEFT")


def test_worker_telemetry_endpoint_incremental_scrape(cluster):
    coord, workers, session = cluster
    w = workers[0]
    w.telemetry.sample_once()
    req = Request(f"{w.uri}/v1/telemetry?since=0",
                  headers=internal_headers())
    import json as _json
    with urlopen(req, timeout=10) as resp:
        doc = _json.loads(resp.read().decode())
    assert doc["nodeId"] == w.node_id
    assert doc["samples"]
    last = doc["samples"][-1]["ts"]
    req = Request(f"{w.uri}/v1/telemetry?since={last}",
                  headers=internal_headers())
    with urlopen(req, timeout=10) as resp:
        doc2 = _json.loads(resp.read().decode())
    assert doc2["samples"] == []          # nothing new since the cursor


def test_cluster_federation_spans_coordinator_and_workers(cluster):
    coord, workers, session = cluster
    for w in workers:
        w.telemetry.sample_once()
    coord.state.telemetry.collect()
    nodes = {r[1] for r in coord.state.telemetry.rows()}
    assert "coordinator" in nodes
    assert any(n.startswith("tl-w") for n in nodes)
    # family-prefix filtering works on the federated rows
    rows = coord.state.telemetry.rows(
        metric="trino_tpu_telemetry_samples_total")
    assert rows and all(
        r[2].startswith("trino_tpu_telemetry_samples_total")
        for r in rows)


def test_system_runtime_metrics_history(cluster):
    coord, workers, session = cluster
    for w in workers:
        w.telemetry.sample_once()
    client = Client(coord.uri, user="tl")
    r = client.execute("SELECT node_id, metric, ts, value "
                       "FROM system.runtime.metrics_history")
    assert r.rows
    nodes = {row[0] for row in r.rows}
    assert "coordinator" in nodes
    assert any(n.startswith("tl-w") for n in nodes), nodes
    assert all(row[2] > 0 for row in r.rows)          # real timestamps


def test_system_runtime_query_timeline(cluster):
    coord, workers, session = cluster
    client = Client(coord.uri, user="tl")
    target = client.execute(DIST_SQL)
    r = client.execute("SELECT query_id, phase, seconds, wall_seconds "
                       "FROM system.runtime.query_timeline")
    mine = [row for row in r.rows if row[0] == target.query_id]
    assert {row[1] for row in mine} == set(PHASES)
    wall = mine[0][3]
    assert abs(sum(row[2] for row in mine) - wall) < 1e-9
    assert all(row[2] >= 0.0 for row in mine)


def test_explain_analyze_prints_critical_path(cluster):
    coord, workers, session = cluster
    coord.state.scheduler.spool.clear()
    client = Client(coord.uri, user="tl")
    r = client.execute("EXPLAIN ANALYZE " + DIST_SQL)
    assert client.query_info(r.query_id)["distributed"]
    text = "\n".join(row[0] for row in r.rows)
    assert "critical path: " in text
    line = next(ln for ln in text.splitlines()
                if ln.startswith("critical path: "))
    assert line.rstrip().endswith("ms")
    assert "other" in line               # the residual always prints


def test_telemetry_off_means_zero_threads(cluster):
    coord, workers, session = cluster
    # no interval configured anywhere in this module: no sampler or
    # federation threads may exist
    assert coord.state.telemetry.recorder.sampling is False
    assert coord.state.telemetry.collecting is False
    assert all(w.telemetry.sampling is False for w in workers)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("telemetry")]


def test_dominant_phase_reaches_history_and_events(cluster):
    coord, workers, session = cluster

    class Sink(EventListener):
        def __init__(self):
            self.completed = []

        def query_completed(self, ev):
            self.completed.append(ev)

    sink = Sink()
    coord.state.dispatcher.event_listeners.register(sink)
    client = Client(coord.uri, user="tl")
    r = client.execute("SELECT count(*) FROM nation")
    # the completion event fires after the client sees the result
    deadline = time.time() + 10
    while not any(e.query_id == r.query_id for e in sink.completed) \
            and time.time() < deadline:
        time.sleep(0.02)
    ev = next(e for e in sink.completed if e.query_id == r.query_id)
    assert ev.dominant_phase in PHASES
    hist = [h for h in coord.state.history.snapshot()
            if h.get("query_id") == r.query_id]
    assert hist and hist[0].get("dominant_phase") == ev.dominant_phase


def test_timeline_metrics_account_every_phase(cluster):
    from trino_tpu.metrics import (CRITICAL_PATH_SECONDS,
                                   TIMELINE_QUERIES)
    coord, workers, session = cluster
    before = TIMELINE_QUERIES.value()
    Client(coord.uri, user="tl").execute("SELECT 1")
    assert TIMELINE_QUERIES.value() > before
    for p in PHASES:
        assert CRITICAL_PATH_SECONDS.has_sample(phase=p), p
