"""Fused multiway star-join tests (ops/pallas_hash.multiway_probe +
planner star detector + exec run_multijoin), interpret mode on CPU so
tier-1 exercises the real kernel logic.

Property: the fused single-pass star probe must be bit-exact vs the
pairwise join ladder it replaces — across TPC-DS star queries (vs the
sqlite oracle), TPC-H join spines, partial and full VMEM-budget
degrades, duplicate build keys, crafted probe-chain escapes, and the
mesh executor's wholesale degrade. The EXPLAIN surface prints the star
verdict whether or not the kernel is on, and every degrade is counted
by reason.

Shapes stay small (<= 4k fact rows, 1k-4k table slots): the interpreter
runs the per-row probe loop in XLA CPU, so cost scales with rows x dims.
"""

import numpy as np
import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from tpcds_queries import ORACLE, QUERIES as DS_QUERIES
from tpch_full import QUERIES as H_QUERIES
from trino_tpu.connectors.tpcds.connector import TABLE_NAMES
from trino_tpu.exec.session import Session
from trino_tpu.metrics import (MULTIJOIN_DEGRADES,
                               MULTIJOIN_FUSED_PROBES)
from trino_tpu.ops import pallas_hash as ph


def _np_splitmix64(x):
    with np.errstate(over="ignore"):
        z = x + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _degrades():
    return {r: MULTIJOIN_DEGRADES.value(reason=r)
            for r in ("kernel_off", "vmem", "dup", "escape", "dtype",
                      "mesh", "spill")}


def _delta(before):
    return {k: v - before[k] for k, v in _degrades().items()
            if v != before[k]}


# ---- synthetic star harness ----------------------------------------------

def star_session(tables):
    import bench
    from trino_tpu.catalog import Catalog
    cat = Catalog()
    cat.register("bench", bench.BenchConnector(tables, "star"))
    return Session(catalog=cat, default_cat="bench",
                   default_schema="star")


def star_sql(k, agg=False):
    joins = " ".join(f"JOIN dim{i} ON f_d{i}key = d{i}_key"
                     for i in range(k))
    if agg:
        exprs = "".join(f" + d{i}_attr" for i in range(k))
        return f"SELECT sum(f_value{exprs}) FROM fact {joins}"
    cols = ", ".join(f"d{i}_attr" for i in range(k))
    return f"SELECT f_value, {cols} FROM fact {joins}"


def default_star(k=3, fact_rows=1 << 12, dim_rows=256, hit_rate=0.7):
    import bench
    return bench._star_tables(k, fact_rows, dim_rows, hit_rate)


def on_off(s, sql):
    """Run fused-on then fused-off; return both row lists."""
    s.execute("SET SESSION enable_multiway_join = 'true'")
    on = s.execute(sql).rows
    s.execute("SET SESSION enable_multiway_join = 'false'")
    off = s.execute(sql).rows
    return on, off


# ---- TPC-DS star corpus vs the sqlite oracle -----------------------------

@pytest.fixture(scope="module")
def ds_session():
    return Session(default_cat="tpcds", default_schema="tiny")


@pytest.fixture(scope="module")
def ds_oracle(ds_session):
    conn = ds_session.catalog.connector("tpcds")
    return load_oracle([conn.get_table("tiny", t) for t in TABLE_NAMES])


def _ds_check(ds_session, ds_oracle, qid):
    sql = DS_QUERIES[qid]
    on, off = on_off(ds_session, sql)
    want = oracle_query(ds_oracle, ORACLE.get(qid, sql))
    assert_rows_match(on, want, rel_tol=1e-9, abs_tol=0.02,
                      ordered=True)
    assert_rows_match(off, want, rel_tol=1e-9, abs_tol=0.02,
                      ordered=True)


def test_tpcds_q7_fused_bitexact(ds_session, ds_oracle):
    """q7 is the canonical 4-dim star: the fused kernel must engage
    and match both the pairwise ladder and the oracle."""
    before = MULTIJOIN_FUSED_PROBES.value()
    _ds_check(ds_session, ds_oracle, 7)
    assert MULTIJOIN_FUSED_PROBES.value() > before


@pytest.mark.slow
@pytest.mark.parametrize("qid", [19, 26])
def test_tpcds_star_fused_bitexact(ds_session, ds_oracle, qid):
    _ds_check(ds_session, ds_oracle, qid)


# ---- TPC-H spines: fused on == off ---------------------------------------

@pytest.fixture(scope="module")
def h_session():
    return Session(default_schema="tiny")


@pytest.mark.parametrize("qid", [3, 10])
def test_tpch_star_on_off(h_session, qid):
    on, off = on_off(h_session, H_QUERIES[qid])
    assert on == off


@pytest.mark.slow
@pytest.mark.parametrize("qid", sorted(H_QUERIES))
def test_tpch_full_sweep_on_off(h_session, qid):
    """Acceptance: the fused path (with every degrade it takes) is
    bit-exact vs the pairwise ladder on all 22 TPC-H queries."""
    on, off = on_off(h_session, H_QUERIES[qid])
    assert on == off


# ---- VMEM-budget degrades ------------------------------------------------

def test_partial_fuse_vmem_degrade():
    """A 3-dim star whose largest dim blows the VMEM budget: the big
    dim degrades to the pairwise path (reason=vmem), the other two
    still fuse, and the output is bit-exact vs the full ladder."""
    from trino_tpu.connectors.tpch.datagen import TableData
    tables = default_star(k=3)
    # re-key dim2 to 2048 rows -> table_slots 4096 vs 1024 for the rest
    rng = np.random.default_rng(5)
    tables["dim2"] = TableData(
        "dim2", tables["dim2"].schema,
        [np.arange(2048, dtype=np.int64),
         rng.integers(0, 1000, 2048).astype(np.int64)],
        primary_key=("d2_key",))
    s = star_session(tables)
    # dims pad to capacity 1024 -> 2048 slots (24 KB each); dim2 pads
    # to 2048 -> 4096 slots. 56 KB holds the two small tables (48 KB)
    # but not the 4096-slot stack (3 x 48 KB), so only dim2 sheds
    s.execute("SET SESSION multiway_vmem_kb = 56")
    before = _degrades()
    s.execute("SET SESSION enable_multiway_join = 'true'")
    on = s.execute(star_sql(3)).rows
    assert s.executor.strategy_decisions.get("MultiJoinNode") == \
        "multiway[k=2]"
    assert _delta(before) == {"vmem": 1}
    s.execute("SET SESSION enable_multiway_join = 'false'")
    off = s.execute(star_sql(3)).rows
    assert on == off


def test_vmem_full_ladder_degrade():
    """Budget too small for even one resident table: every dim sheds
    (reason=vmem), the node runs as the reconstructed ladder, and the
    output still matches."""
    s = star_session(default_star(k=3))
    s.execute("SET SESSION multiway_vmem_kb = 8")
    before = _degrades()
    s.execute("SET SESSION enable_multiway_join = 'true'")
    on = s.execute(star_sql(3)).rows
    assert s.executor.strategy_decisions.get("MultiJoinNode") == "ladder"
    assert _delta(before) == {"vmem": 3}
    s.execute("SET SESSION enable_multiway_join = 'false'")
    off = s.execute(star_sql(3)).rows
    assert on == off


def test_kernel_off_counts_degrades():
    from trino_tpu.sql.parser import parse
    s = star_session(default_star(k=3))
    # plan with the kernel ON so a MultiJoinNode exists, then flip the
    # executor knob off underneath it: the wholesale kernel_off degrade
    s.execute("SET SESSION enable_multiway_join = 'true'")
    ref = s.execute(star_sql(3)).rows
    rel = s.planner().plan_query(parse(star_sql(3)))
    before = _degrades()
    s.executor.enable_multiway_join = "false"
    try:
        s.executor.execute(rel.node)
    finally:
        s.executor.enable_multiway_join = "true"
    assert _delta(before) == {"kernel_off": 3}
    assert ref  # the fused reference run produced rows


# ---- duplicate build keys + crafted escapes ------------------------------

def test_dup_dim_degrades_bitexact():
    """A dim whose primary_key metadata lies (duplicated keys): the
    planner fuses on the metadata, the executor detects the dup at
    build time, degrades that dim to the pairwise expand path
    (reason=dup), and the expansion matches the full ladder's."""
    from trino_tpu.connectors.tpch.datagen import TableData
    tables = default_star(k=3, hit_rate=0.9)
    dup = tables["dim1"]
    keys = np.asarray(dup.columns[0]).copy()
    keys[1::2] = keys[0::2]                      # every key twice
    tables["dim1"] = TableData("dim1", dup.schema,
                               [keys, np.asarray(dup.columns[1])],
                               primary_key=("d1_key",))
    s = star_session(tables)
    before = _degrades()
    on, off = on_off(s, star_sql(3))
    assert on == off
    assert _delta(before) == {"dup": 1}


def test_escape_dim_degrades_bitexact():
    """Keys crafted so > MAX_PROBES distinct dim keys share one home
    slot: the build's insert chain escapes, the dim degrades
    (reason=escape), and results still match the ladder."""
    from trino_tpu.connectors.tpch.datagen import TableData
    # every dim here pads to the batch lattice floor (capacity 1024),
    # so the SHARED table the stack builds with has
    # join_table_slots(1024) slots — craft the collisions against that
    slots, fits = ph.join_table_slots(1024)
    assert fits
    cands = np.arange(1, 500_000, dtype=np.int64)
    home = (_np_splitmix64(cands.view(np.uint64) + ph._SLOT_SEED)
            % np.uint64(slots)).astype(np.int64)
    target = home[0]
    colliders = cands[home == target]
    assert len(colliders) > ph.MAX_PROBES + 2   # the craft collided
    colliders = colliders[:ph.MAX_PROBES + 4]
    tables = default_star(k=3, dim_rows=64, hit_rate=0.9)
    esc = tables["dim2"]
    rng = np.random.default_rng(7)
    tables["dim2"] = TableData(
        "dim2", esc.schema,
        [colliders,
         rng.integers(0, 1000, len(colliders)).astype(np.int64)],
        primary_key=("d2_key",))
    # fact keys for dim2 must reference the crafted key space
    fact = tables["fact"]
    fcols = [np.asarray(c) for c in fact.columns]
    fcols[2] = rng.choice(colliders, len(fcols[2]))
    tables["fact"] = TableData("fact", fact.schema, fcols)
    s = star_session(tables)
    before = _degrades()
    on, off = on_off(s, star_sql(3))
    assert on == off
    assert _delta(before) == {"escape": 1}


# ---- fact side authoritative ---------------------------------------------

def test_mis_sized_fact_stays_probe():
    """A fact smaller than its dims must NOT flip into the VMEM build
    (the pairwise path re-derives sides per hop; MultiJoinNode's fact
    is authoritative). Output still matches the ladder."""
    s = star_session(default_star(k=3, fact_rows=64, dim_rows=512,
                                  hit_rate=0.9))
    on, off = on_off(s, star_sql(3))
    assert on == off


# ---- mesh executor: wholesale degrade ------------------------------------

def test_mesh_degrades_to_ladder():
    from trino_tpu.parallel.dist_executor import MeshExecutor
    from trino_tpu.parallel.mesh import make_mesh
    s = star_session(default_star(k=3))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    ref = s.execute(star_sql(3, agg=True)).rows
    m = star_session(default_star(k=3))
    m.executor = MeshExecutor(m.catalog, make_mesh(8))
    m.execute("SET SESSION enable_multiway_join = 'true'")
    before = _degrades()
    got = m.execute(star_sql(3, agg=True)).rows
    assert got == ref
    assert _delta(before) == {"mesh": 3}


# ---- EXPLAIN surface ------------------------------------------------------

def test_explain_star_verdict_and_strategy():
    s = star_session(default_star(k=3))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    on = "\n".join(r[0] for r in s.execute(
        "EXPLAIN " + star_sql(3)).rows)
    assert "MultiJoin[star, k=3" in on
    assert "join strategy: multiway[k=3]" in on
    s.execute("SET SESSION enable_multiway_join = 'false'")
    off = "\n".join(r[0] for r in s.execute(
        "EXPLAIN " + star_sql(3)).rows)
    assert "MultiJoin" not in off.replace("multiway star", "")
    assert "multiway star: fusable k=3" in off


def test_explain_analyze_ran_divergence():
    """After a full VMEM degrade, EXPLAIN ANALYZE appends the executed
    strategy to the multiway prediction ([ran: ladder])."""
    s = star_session(default_star(k=3))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    s.execute("SET SESSION multiway_vmem_kb = 8")
    text = "\n".join(r[0] for r in s.execute(
        "EXPLAIN ANALYZE " + star_sql(3)).rows)
    assert "join strategy: multiway[k=3] [ran: ladder]" in text


def test_explain_declined_star():
    """A non-inner hop keeps the ladder and EXPLAIN says why."""
    s = star_session(default_star(k=2))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    sql = ("SELECT f_value FROM fact "
           "JOIN dim0 ON f_d0key = d0_key "
           "LEFT JOIN dim1 ON f_d1key = d1_key")
    text = "\n".join(r[0] for r in s.execute("EXPLAIN " + sql).rows)
    assert "MultiJoin" not in text
    assert "multiway star: declined" in text


def test_multiway_max_dims_cap():
    s = star_session(default_star(k=3))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    s.execute("SET SESSION multiway_max_dims = 2")
    text = "\n".join(r[0] for r in s.execute(
        "EXPLAIN " + star_sql(3)).rows)
    assert "MultiJoin[star, k=2" in text
    on, off = on_off(s, star_sql(3))
    assert on == off


# ---- shape-lattice compliance --------------------------------------------

def test_repeated_star_zero_new_shapes():
    """Lattice lint: once the star's decisions settle, re-executions of
    the same fused query add ZERO distinct compiled shapes anywhere."""
    from trino_tpu.exec.profiler import RECORDER
    s = star_session(default_star(k=3))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    sql = star_sql(3, agg=True)
    s.execute(sql)                  # cold: compiles + decision fetches
    s.execute(sql)                  # adaptation pass (decisions settle)
    settled = RECORDER.site_shape_counts()
    s.execute(sql)
    s.execute(sql)
    again = RECORDER.site_shape_counts()
    assert again == settled, {k: again[k] - settled.get(k, 0)
                              for k in again
                              if again[k] != settled.get(k, 0)}


# ---- metrics surface ------------------------------------------------------

def test_multijoin_metric_families_render_cold():
    from trino_tpu.metrics import REGISTRY
    text = REGISTRY.render()
    assert "# TYPE trino_tpu_multijoin_fused_probes_total" in text
    for reason in ("kernel_off", "vmem", "dup", "escape", "dtype",
                   "mesh", "spill"):
        assert f'reason="{reason}"' in text


def test_operator_stats_strategy_column():
    s = star_session(default_star(k=3))
    s.execute("SET SESSION enable_multiway_join = 'true'")
    s.execute(star_sql(3))
    assert s.executor.strategy_decisions.get("MultiJoinNode") == \
        "multiway[k=3]"


# ---- bench harness --------------------------------------------------------

def test_star_micro_smoke_and_regression_series(tmp_path):
    """--star-micro CPU smoke writes a parseable round; the regression
    gate reads star-micro rounds as their own config series and flags
    an injected 3x fused-kernel slowdown."""
    import json

    import bench
    out = bench.star_micro(shapes=[(2, 0.9)], fact_rows=1 << 11,
                           dim_rows=128, runs=1,
                           out_path=str(tmp_path /
                                        "BENCH_star_micro.json"))
    assert out["records"] and out["records"][0]["fused_engaged"]
    parsed = bench.load_bench_round(str(tmp_path /
                                        "BENCH_star_micro.json"))
    assert parsed and any(k.startswith("star_micro_k") for k in parsed)
    base = {"metric": "star_micro_ms",
            "records": [{"dims": 2, "hit_rate": 0.9, "fused_ms": 3.0,
                         "pairwise_ms": 9.0}]}
    paths = []
    for i in range(3):
        p = tmp_path / f"r{i}.json"
        p.write_text(json.dumps(base))
        paths.append(str(p))
    bad = {"metric": "star_micro_ms",
           "records": [{"dims": 2, "hit_rate": 0.9, "fused_ms": 9.5,
                        "pairwise_ms": 9.0}]}
    pbad = tmp_path / "r3.json"
    pbad.write_text(json.dumps(bad))
    ok, _ = bench.check_regressions(paths)
    assert ok
    ok2, report2 = bench.check_regressions(paths + [str(pbad)])
    assert not ok2
    assert "star_micro_k2_h0.9_fused" in report2["regressions"]
