"""Coordinator crash recovery (server/ledger.py + warm-standby failover).

Round-20 acceptance surface: the durable query ledger replays
idempotently from every byte prefix (torn tail included) and under
double replay; a coordinator killed at each query lifecycle state
(QUEUED / PLANNING / RUNNING / FINISHING / write-commit) is replaced by
a promoted standby that resumes every non-terminal query under its
ORIGINAL id; the polling client fails over across the coordinator
address list and finishes with bit-exact rows and no client-visible
error; epoch fencing stops a resurrected old primary from split-brain;
workers buffer terminal task reports while no coordinator listens and
re-deliver them after re-announcing.
"""

import json
import os
import threading
import time

import pytest

from trino_tpu.client.client import Client
from trino_tpu.connectors.orcdir import OrcConnector
from trino_tpu.exec.session import Session
from trino_tpu.server import ledger as led
from trino_tpu.server import writeprotocol as wp
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.exchange_spool import ExchangeSpool
from trino_tpu.server.failureinjector import FailureInjector
from trino_tpu.server.ledger import LedgerView, QueryLedger, replay_path
from trino_tpu.server.statemachine import QueryStateMachine
from trino_tpu.server.worker import WorkerServer

SQL = ("SELECT n_regionkey, count(*) AS c FROM nation "
       "GROUP BY n_regionkey ORDER BY n_regionkey")
EXPECT = [[0, 5], [1, 5], [2, 5], [3, 5], [4, 5]]


# ---------------------------------------------------------------------------
# ledger: framing, prefix/torn-tail replay, double-replay idempotence
# ---------------------------------------------------------------------------

def _scripted_records(qid="20260101_000000_00001_tpu"):
    """A representative record sequence: admission through terminal,
    with assignments and a spool pointer in between."""
    return [
        {"rec": "admit", "query": qid, "sql": SQL, "user": "alice",
         "tenant": "root", "fingerprint": "fp1", "properties": {},
         "ts": 1.0},
        {"rec": "state", "query": qid, "state": "PLANNING", "ts": 2.0},
        {"rec": "state", "query": qid, "state": "RUNNING", "ts": 3.0},
        {"rec": "assign", "query": qid, "task": f"{qid}.0.0",
         "node": "w1", "stage": "partial", "ts": 3.5},
        {"rec": "spool", "query": qid, "key": "k" * 32, "ts": 4.0},
        {"rec": "state", "query": qid, "state": "FINISHING", "ts": 5.0},
        {"rec": "terminal", "query": qid, "state": "FINISHED", "ts": 6.0,
         "error": None, "error_name": None, "error_code": 0, "rows": 5,
         "elapsed_s": 1.25, "catalog_version": 2},
    ]


def test_ledger_byte_prefix_replay_idempotent(tmp_path):
    """Every byte prefix of the ledger replays without error, torn
    tails are flagged, and each complete-frame boundary yields exactly
    the fold of the records before it (mirrors the write journal's
    prefix test)."""
    records = _scripted_records()
    frames = [wp._frame(r) for r in records]
    blob = b"".join(frames)
    boundaries = {0: 0}
    off = 0
    for i, fr in enumerate(frames):
        off += len(fr)
        boundaries[off] = i + 1
    for cut in range(len(blob) + 1):
        p = str(tmp_path / f"cut{cut:04d}.ledger")
        with open(p, "wb") as f:
            f.write(blob[:cut])
        view, torn = replay_path(p)
        if cut in boundaries:
            assert not torn, cut
            want = LedgerView()
            for r in records[:boundaries[cut]]:
                want.apply(r)
            assert view.fingerprint() == want.fingerprint(), cut
        else:
            # mid-frame cut: replay stops at the last whole frame
            assert torn, cut
        # replay is a pure function of the bytes: run it again
        again, _ = replay_path(p)
        assert again.fingerprint() == view.fingerprint(), cut


def test_ledger_double_replay_converges():
    """Applying the whole record stream twice (a standby that tailed,
    then replayed at promotion) equals applying it once."""
    records = _scripted_records()
    once = LedgerView()
    for r in records:
        once.apply(r)
    twice = LedgerView()
    for r in records + records:
        twice.apply(r)
    assert twice.fingerprint() == once.fingerprint()
    q = once.queries["20260101_000000_00001_tpu"]
    assert q["terminal"] == "FINISHED" and q["rows"] == 5
    assert q["state_times"]["QUEUED"] == 1.0
    assert list(q["assigned"]) == ["20260101_000000_00001_tpu.0.0"]
    assert once.catalog_version == 2


def test_ledger_view_state_is_monotonic():
    """Late/duplicate state records (re-delivered after a resume) never
    regress the view; the first terminal wins over a later one."""
    qid = "q"
    v = LedgerView()
    v.apply({"rec": "state", "query": qid, "state": "RUNNING", "ts": 3.0})
    v.apply({"rec": "state", "query": qid, "state": "PLANNING", "ts": 9.0})
    assert v.queries[qid]["state"] == "RUNNING"
    assert v.queries[qid]["state_times"]["PLANNING"] == 9.0
    v.apply({"rec": "terminal", "query": qid, "state": "FAILED",
             "ts": 4.0, "error": "boom", "error_name": "E", "rows": 0})
    v.apply({"rec": "terminal", "query": qid, "state": "FINISHED",
             "ts": 5.0, "rows": 7})
    assert v.queries[qid]["terminal"] == "FAILED"
    assert v.queries[qid]["error"] == "boom"


def test_ledger_append_replay_roundtrip(tmp_path):
    lg = QueryLedger(str(tmp_path / "q.ledger"), node_id="c1")
    lg.admit("q1", SQL, "alice", "root", "fp", {"p": 1, "obj": {"x": 1}})
    lg.state("q1", "RUNNING", 3.0)
    lg.assign("q1", "q1.0.0", "w1", "partial")
    lg.spool("q1", "abc")
    lg.terminal("q1", "FINISHED", 4.0, rows=5, elapsed_s=0.5,
                catalog_version=1)
    view, torn = lg.replay()
    assert not torn
    q = view.queries["q1"]
    assert q["sql"] == SQL and q["user"] == "alice"
    # non-scalar session properties are filtered at append time
    assert q["properties"] == {"p": 1}
    assert q["terminal"] == "FINISHED" and q["spooled"] == ["abc"]


# ---------------------------------------------------------------------------
# epoch fencing
# ---------------------------------------------------------------------------

def test_epoch_fences_deposed_writer(tmp_path):
    path = str(tmp_path / "q.ledger")
    a = QueryLedger(path, node_id="c1")
    a.claim_epoch()
    assert a.append({"rec": "state", "query": "q1", "state": "RUNNING",
                     "ts": 1.0})
    b = QueryLedger(path, node_id="c2")
    assert not b.owns_epoch()         # c1 holds the epoch
    epoch = b.claim_epoch()
    assert epoch == 2 and b.owns_epoch()
    # the deposed writer's cached ownership expires within the TTL and
    # its appends become no-ops — never an exception
    time.sleep(QueryLedger.EPOCH_TTL_S + 0.05)
    assert not a.append({"rec": "state", "query": "q1",
                         "state": "FINISHING", "ts": 2.0})
    view, _ = replay_path(path)
    assert "FINISHING" not in view.queries["q1"]["state_times"]
    assert view.epoch == 2


def test_sealed_ledger_refuses_appends(tmp_path):
    lg = QueryLedger(str(tmp_path / "q.ledger"), node_id="c1")
    assert lg.admit("q1", "SELECT 1", "u", "root", "fp", {})
    lg.seal()
    assert not lg.admit("q2", "SELECT 2", "u", "root", "fp", {})
    view, _ = lg.replay()
    assert list(view.queries) == ["q1"]


# ---------------------------------------------------------------------------
# statemachine: CANCELED parity with FAILED (satellite 3)
# ---------------------------------------------------------------------------

def test_cancel_records_timeline_and_taxonomy():
    sm = QueryStateMachine("q1")
    sm.transition("PLANNING")
    sm.transition("RUNNING")
    assert sm.cancel()
    assert sm.state == "CANCELED"
    assert "CANCELED" in sm.state_times          # timeline attribution
    assert sm.error_name == "USER_CANCELED" and sm.error_code == 2


def test_restored_statemachine_matches_original():
    """Ledger replay reconstructs a terminal state machine with the
    recorded stamps and error taxonomy — the timeline phases sum the
    same before and after, for CANCELED exactly like FAILED."""
    for final in ("CANCELED", "FAILED", "FINISHED"):
        sm = QueryStateMachine("q1")
        sm.transition("PLANNING")
        sm.transition("RUNNING")
        if final == "CANCELED":
            sm.cancel()
        elif final == "FAILED":
            sm.fail("boom", error_name="E", error_code=9)
        else:
            sm.transition("FINISHING")
            sm.transition("FINISHED")
        back = QueryStateMachine.restored(
            "q1", sm.state, dict(sm.state_times), error=sm.error,
            error_name=sm.error_name, error_code=sm.error_code)
        assert back.state == sm.state
        assert back.state_times == sm.state_times
        assert back.error_name == sm.error_name
        assert back.error_code == sm.error_code
        assert back.is_done()
        # restored terminal machines are settled from birth: there is
        # no completion pipeline left to wait for
        assert back.settled.is_set()


def test_terminal_page_waits_for_completion_pipeline():
    """A fast poller must never observe a terminal state before the
    terminal listeners (completion event, ledger record, metrics) have
    run: `settled` flips only after the listener sweep finishes."""
    sm = QueryStateMachine("q_settle")
    hits = []

    def slow_listener(state):
        if state == "FINISHED":
            time.sleep(0.2)
            hits.append(state)

    sm.add_listener(slow_listener)
    t = threading.Thread(target=lambda: [
        sm.transition(s)
        for s in ("PLANNING", "RUNNING", "FINISHING", "FINISHED")])
    t.start()
    deadline = time.time() + 5.0
    while sm.state != "FINISHED" and time.time() < deadline:
        time.sleep(0.002)
    # state is visible but the pipeline is still draining
    assert sm.state == "FINISHED"
    assert sm.settled.wait(2.0)
    assert hits == ["FINISHED"]
    t.join()
    # failed/canceled queries settle too — error pages are gated the
    # same way as result pages
    for ender in (lambda m: m.fail("boom"), lambda m: m.cancel()):
        m = QueryStateMachine("q_e")
        ender(m)
        assert m.settled.is_set()


# ---------------------------------------------------------------------------
# kill-at-each-state: a fresh coordinator resumes a forged ledger
# ---------------------------------------------------------------------------

def _forge_ledger(path, qid, sql, upto):
    """Write the ledger a primary killed at lifecycle state `upto`
    would leave behind."""
    old = QueryLedger(path, node_id="old")
    old.admit(qid, sql, "alice", "root", "fp", {})
    ts = 1.0
    for st in ("PLANNING", "RUNNING", "FINISHING"):
        if led._rank(st) <= led._rank(upto) and upto != "QUEUED":
            old.state(qid, st, ts)
            ts += 1.0
        if st == upto:
            break
    old.seal()


@pytest.mark.parametrize("upto,mode", [
    ("QUEUED", "replayed"), ("PLANNING", "replayed"),
    ("RUNNING", "reexecuted"), ("FINISHING", "reexecuted")])
def test_boot_replay_resumes_killed_query(tmp_path, upto, mode):
    """A coordinator booting over the dead primary's ledger resumes the
    in-flight query under its ORIGINAL id, classifies the resumption
    mode, and finishes it with the right answer."""
    from trino_tpu.metrics import QUERIES_RESUMED
    path = str(tmp_path / "q.ledger")
    qid = "20260101_000000_00007_tpu"
    _forge_ledger(path, qid, SQL, upto)
    before = QUERIES_RESUMED.value(mode=mode)
    coord = CoordinatorServer(Session(default_schema="tiny"),
                              ledger_path=path, node_id="new")
    try:
        tq = coord.state.tracker.get(qid)
        assert tq is not None, "replay did not resume the query"
        assert tq.resumed == mode
        assert QUERIES_RESUMED.value(mode=mode) == before + 1
        deadline = time.time() + 30
        while not tq.state_machine.is_done() and time.time() < deadline:
            time.sleep(0.02)
        assert tq.state == "FINISHED"
        assert [list(r) for r in tq.result.rows] == EXPECT
        # the resumed run's ledger records landed under the new epoch
        view, _ = coord.state.ledger.replay()
        assert view.queries[qid]["terminal"] == "FINISHED"
        # double replay on the live coordinator is a no-op
        assert coord.state._replay_ledger() == 0
    finally:
        coord.state.dispatcher.pool.shutdown(wait=False)
        coord.stop()


def test_boot_replay_restores_terminal_queries(tmp_path):
    """Terminal queries replay byte-for-byte into the registry — state,
    stamps, error taxonomy, row counts — without re-executing."""
    path = str(tmp_path / "q.ledger")
    old = QueryLedger(path, node_id="old")
    old.admit("q_ok", SQL, "alice", "root", "fp", {})
    old.state("q_ok", "RUNNING", 2.0)
    old.terminal("q_ok", "FINISHED", 3.0, rows=5, elapsed_s=0.5)
    old.admit("q_bad", "SELECT nope", "bob", "root", "fp", {})
    old.terminal("q_bad", "FAILED", 2.5, error="column nope",
                 error_name="COLUMN_NOT_FOUND", error_code=47)
    old.admit("q_cxl", SQL, "eve", "root", "fp", {})
    old.state("q_cxl", "RUNNING", 2.0)
    old.terminal("q_cxl", "CANCELED", 2.7, error="Query was canceled",
                 error_name="USER_CANCELED", error_code=2)
    old.seal()
    coord = CoordinatorServer(Session(default_schema="tiny"),
                              ledger_path=path, node_id="new")
    try:
        ok = coord.state.tracker.get("q_ok")
        assert ok.state == "FINISHED" and ok.rows_returned == 5
        assert ok.resumed == "restored" and ok.result is None
        bad = coord.state.tracker.get("q_bad")
        assert bad.state == "FAILED"
        assert bad.state_machine.error_name == "COLUMN_NOT_FOUND"
        assert bad.state_machine.error_code == 47
        cxl = coord.state.tracker.get("q_cxl")
        assert cxl.state == "CANCELED"
        assert cxl.state_machine.error_name == "USER_CANCELED"
        # CANCELED lands in state_times exactly like FAILED: the
        # replayed timeline still sums (satellite 3)
        assert cxl.state_machine.state_times["CANCELED"] == 2.7
        assert cxl.state_machine.state_times["RUNNING"] == 2.0
    finally:
        coord.state.dispatcher.pool.shutdown(wait=False)
        coord.stop()


def test_restored_finished_query_reexecutes_on_data_poll(tmp_path):
    """A ledger-restored FINISHED query holds no result pages; the
    first data poll lazily re-executes it under the original id (reads
    are pure, so the client sees the exact rows it would have)."""
    path = str(tmp_path / "q.ledger")
    old = QueryLedger(path, node_id="old")
    old.admit("q_ok", SQL, "alice", "root", "fp", {})
    old.terminal("q_ok", "FINISHED", 3.0, rows=5, elapsed_s=0.5)
    old.seal()
    coord = CoordinatorServer(Session(default_schema="tiny"),
                              ledger_path=path, node_id="new").start()
    try:
        client = Client(coord.uri, user="alice")
        info = client.query_info("q_ok")
        assert info["state"] == "FINISHED"
        # polling the executing route re-runs the restored query
        r = client._request(
            "GET", f"{coord.uri}/v1/statement/executing/q_ok/0")
        deadline = time.time() + 30
        rows = r.get("data") or []
        while r.get("nextUri") and time.time() < deadline:
            r = client._poll(r["nextUri"])
            rows.extend(r.get("data") or [])
        assert [list(x) for x in rows] == EXPECT
    finally:
        coord.state.dispatcher.pool.shutdown(wait=False)
        coord.stop()


def test_resumed_committed_write_is_exactly_once(tmp_path):
    """A CTAS whose pre-crash attempt already published parts must NOT
    write again when its query resumes on the promoted coordinator: the
    resumed attempt short-circuits to the committed row count (the
    coordinator-death twin of round-18's duplicate-attempt dedup)."""
    root = str(tmp_path / "orc")
    os.makedirs(os.path.join(root, "out"))
    path = str(tmp_path / "q.ledger")
    src = ("SELECT o_orderkey, o_custkey, o_orderstatus, o_totalprice "
           "FROM tpch.tiny.orders")
    ctas = f"CREATE TABLE orc.out.t1 AS {src}"
    table_dir = os.path.join(root, "out", "t1")

    session1 = Session(default_schema="tiny")
    session1.catalog.register("orc", OrcConnector(root))
    first = CoordinatorServer(session1, ledger_path=path,
                              node_id="c1").start()
    first.state.scheduler.split_rows = 4096
    workers = [WorkerServer(f"wx{i}", first.uri, announce_interval_s=0.1,
                            catalog=session1.catalog).start()
               for i in range(2)]
    try:
        deadline = time.time() + 10
        while len(first.state.active_nodes()) < 2 and \
                time.time() < deadline:
            time.sleep(0.02)
        tq = first.state.dispatcher.submit(ctas, "alice")
        deadline = time.time() + 60
        while not tq.state_machine.is_done() and time.time() < deadline:
            time.sleep(0.02)
        assert tq.state == "FINISHED"
        assert tq.distributed, tq.fallback_reason
        committed = wp.published_rows_for(table_dir, tq.query_id)
        assert committed == 15000
        parts_before = wp.list_parts(table_dir)
        qid = tq.query_id
    finally:
        for w in workers:
            w.kill()
        first.kill()
        first.state.dispatcher.pool.shutdown(wait=False)

    # forge the crash: rewrite the ledger WITHOUT the terminal record,
    # as if the primary died between commit-publish and the ledger
    # terminal append — the worst double-write window
    records, _ = wp.replay_journal(path)
    with open(path, "wb") as f:
        for rec in records:
            if rec.get("rec") == "terminal":
                continue
            f.write(wp._frame(rec))
    os.unlink(path + ".epoch")

    session2 = Session(default_schema="tiny")
    session2.catalog.register("orc", OrcConnector(root))
    second = CoordinatorServer(session2, ledger_path=path, node_id="c2")
    try:
        tq2 = second.state.tracker.get(qid)
        assert tq2 is not None and tq2.resumed == "reexecuted"
        deadline = time.time() + 60
        while not tq2.state_machine.is_done() and time.time() < deadline:
            time.sleep(0.02)
        assert tq2.state == "FINISHED"
        # the resumed attempt deduped: same parts, same rows, no second
        # write — and the table reads back exactly once
        assert wp.list_parts(table_dir) == parts_before
        assert wp.published_rows_for(table_dir, qid) == 15000
        got = session2.execute(
            "SELECT count(*) FROM orc.out.t1").rows[0][0]
        assert got == 15000
    finally:
        second.kill()
        second.state.dispatcher.pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# spool sweep
# ---------------------------------------------------------------------------

def test_spool_sweep_keeps_live_keys(tmp_path):
    spool = ExchangeSpool(root=str(tmp_path / "spool"))
    spool.put("live1", [b"page"])
    spool.put("dead1", [b"page"])
    spool.put("dead2", [b"page"])
    with open(os.path.join(spool.root, "torn.spool.tmp"), "wb") as f:
        f.write(b"partial")
    removed = spool.sweep(keep={"live1"})
    assert removed == 2
    names = set(os.listdir(spool.root))
    assert "live1.spool" in names
    assert "dead1.spool" not in names and "dead2.spool" not in names
    assert not any(f.endswith(".tmp") for f in names)


# ---------------------------------------------------------------------------
# two-coordinator + two-worker cluster: the e2e failover surface
# ---------------------------------------------------------------------------

@pytest.fixture()
def ha_cluster(tmp_path):
    ledger = str(tmp_path / "query.ledger")
    spool = str(tmp_path / "spool")
    primary = CoordinatorServer(Session(default_schema="tiny"),
                                ledger_path=ledger, node_id="c1",
                                spool_root=spool).start()
    standby = CoordinatorServer(Session(default_schema="tiny"),
                                ledger_path=ledger, node_id="c2",
                                role="standby", peer_uri=primary.uri,
                                spool_root=spool,
                                standby_interval_s=0.1).start()
    workers = [WorkerServer(f"w{i}", primary.uri,
                            announce_interval_s=0.15).start()
               for i in (1, 2)]
    deadline = time.time() + 10
    while len(primary.state.active_nodes()) < 2 and \
            time.time() < deadline:
        time.sleep(0.02)
    # one announce round so workers learn the standby address
    for w in workers:
        w.announce_once()
    yield primary, standby, workers, ledger
    for w in workers:
        w.kill()
    for c in (primary, standby):
        try:
            c.state.dispatcher.pool.shutdown(wait=False)
            c.stop()
        except Exception:  # noqa: BLE001 — killed servers die twice
            pass


def test_standby_boots_passive_and_rejects_statements(ha_cluster):
    primary, standby, workers, _ = ha_cluster
    assert primary.state.role == "PRIMARY"
    assert standby.state.role == "PASSIVE"
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen
    req = Request(f"{standby.uri}/v1/statement", data=b"SELECT 1",
                  headers={"X-Trino-User": "t"})
    with pytest.raises(HTTPError) as ei:
        urlopen(req, timeout=5)
    assert ei.value.code == 503
    body = json.loads(ei.value.read().decode())
    assert body["error"]["errorName"] == "COORDINATOR_UNAVAILABLE"
    assert body["error"]["retryable"] is True


def test_announce_response_carries_address_list(ha_cluster):
    primary, standby, workers, _ = ha_cluster
    assert workers[0].coordinators == [primary.uri, standby.uri]
    # a single-address client keeps working (shape unchanged for old
    # deployments: ok/role/coordinators/epoch)
    info = json.loads(__import__("urllib.request", fromlist=["urlopen"])
                      .urlopen(f"{primary.uri}/v1/info/state",
                               timeout=5).read().decode())
    assert info["state"] == "PRIMARY" and info["epoch"] >= 1
    assert info["coordinators"][0] == primary.uri


def test_client_failover_midquery_bit_exact(ha_cluster):
    """Kill the primary while the query executes; the polling client
    finishes through the promoted standby: same rows, same query id,
    failovers surfaced, no client-visible error."""
    primary, standby, workers, _ = ha_cluster
    inj = FailureInjector()
    primary.state.dispatcher.failure_injector = inj
    inj.inject("EXECUTION", times=1, fault="DELAY", delay_s=3.0,
               match_sql="n_regionkey")
    client = Client([primary.uri, standby.uri], user="ha", timeout_s=60)
    res = {}

    def run():
        res["r"] = client.execute(SQL)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(1.0)                 # admitted + RUNNING inside the delay
    primary.kill()
    t.join(timeout=60)
    assert not t.is_alive(), "client never finished after failover"
    r = res["r"]
    assert [list(x) for x in r.rows] == EXPECT
    assert r.failovers >= 1
    assert standby.state.role == "PRIMARY"
    tq = standby.state.tracker.get(r.query_id)
    assert tq is not None and tq.state == "FINISHED"
    from trino_tpu.metrics import COORDINATOR_FAILOVERS
    assert COORDINATOR_FAILOVERS.value() >= 1


def test_admin_promotion_and_double_promotion_fencing(ha_cluster, tmp_path):
    """PUT /v1/info/state promotes the standby; the old primary is
    fenced — its ledger appends no-op, its statement route 503s, and a
    resurrected instance under its node id boots PASSIVE."""
    primary, standby, workers, ledger = ha_cluster
    from trino_tpu.server.security import internal_headers
    from urllib.request import Request, urlopen
    req = Request(f"{standby.uri}/v1/info/state",
                  data=json.dumps({"state": "PRIMARY"}).encode(),
                  headers={"Content-Type": "application/json",
                           **internal_headers()}, method="PUT")
    with urlopen(req, timeout=10) as r:
        doc = json.loads(r.read().decode())
    assert doc["promoted"] and doc["role"] == "PRIMARY"
    # the deposed primary self-demotes on its serving path
    time.sleep(QueryLedger.EPOCH_TTL_S + 0.1)
    assert not primary.state.accepting()
    assert primary.state.role == "PASSIVE"
    assert not primary.state.ledger.append(
        {"rec": "state", "query": "qx", "state": "RUNNING", "ts": 1.0})
    # a resurrected old primary must boot fenced, not split-brain
    ghost = CoordinatorServer(Session(default_schema="tiny"),
                              ledger_path=ledger, node_id="c1")
    try:
        assert ghost.state.role == "PASSIVE"
    finally:
        ghost.state.dispatcher.pool.shutdown(wait=False)
        ghost.stop()
    # the promoted standby serves queries
    r = Client(standby.uri, user="ha").execute(SQL)
    assert [list(x) for x in r.rows] == EXPECT


# ---------------------------------------------------------------------------
# worker terminal-status buffering
# ---------------------------------------------------------------------------

def test_worker_buffers_terminal_reports_until_announce(tmp_path):
    """A worker whose coordinator is unreachable buffers terminal task
    reports instead of dropping them, and re-delivers after the next
    successful announce (satellite 2)."""
    from trino_tpu.server.tasks import encode_fragment
    w = WorkerServer("wbuf", "http://127.0.0.1:9",       # nothing there
                     announce_interval_s=3600)
    try:
        session = Session(default_schema="tiny")
        _stmt, pr = session.plan(SQL)
        frag = encode_fragment({"root": pr.node, "driver": None})
        # run a task directly; terminal push fails -> buffered
        task = w.task_manager.create_or_update("t-buf", frag, [])
        deadline = time.time() + 30
        while task.state in ("PENDING", "RUNNING") and \
                time.time() < deadline:
            time.sleep(0.02)
        deadline = time.time() + 5
        while not w._pending_reports and time.time() < deadline:
            time.sleep(0.02)
        assert len(w._pending_reports) == 1
        report = w._pending_reports[0]
        assert report["taskId"] == "t-buf"
        # now a coordinator appears: announce succeeds and flushes
        coord = CoordinatorServer(Session(default_schema="tiny")).start()
        try:
            w.coordinator_uri = coord.uri
            w.coordinators = [coord.uri]
            w.announce_once(attempts=2)
            assert not w._pending_reports
            assert "t-buf" in coord.state.task_reports
            assert coord.state.task_reports["t-buf"]["state"] == \
                report["state"]
        finally:
            coord.state.dispatcher.pool.shutdown(wait=False)
            coord.stop()
    finally:
        w.kill()


def test_orphan_reaper_fenced_during_failover_reattachment():
    """Round-22 x round-20 composition: the worker announce loop must
    NEVER reap tasks while its coordinator answers as a non-PRIMARY (a
    promotee still reconciling our inventory against its replayed
    ledger) — and after the coordinator is PRIMARY again, the fence
    lapses and the reaper resumes, so a genuinely orphaned task is
    still eventually abandoned."""
    from trino_tpu.server.tasks import encode_fragment
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    w = WorkerServer("fence-w", coord.uri, announce_interval_s=0.1,
                     catalog=session.catalog).start()
    try:
        deadline = time.time() + 5
        while not coord.state.active_nodes() and time.time() < deadline:
            time.sleep(0.05)
        _stmt, pr = session.plan(SQL)
        frag = encode_fragment({"root": pr.node, "driver": None})
        task = w.task_manager.create_or_update("t-fence", frag, [])
        deadline = time.time() + 30
        while task.state in ("PENDING", "RUNNING") and \
                time.time() < deadline:
            time.sleep(0.02)
        assert task.state == "FINISHED"
        # make the task reapable: stale far past a tiny abandonment
        # timeout, with a short post-failover fence so the test can see
        # the reaper resume
        w.task_manager.task_abandonment_timeout_s = 0.2
        w.reap_fence_s = 0.3
        task.last_referenced = time.monotonic() - 100
        # mid-failover: the coordinator answers announces as a
        # still-reconciling promotee — several announce/reap rounds
        # pass and the stale task must survive every one of them
        coord.state.role = "RECONCILING"
        time.sleep(0.8)
        assert task.state == "FINISHED", \
            "reaper fired during failover reattachment"
        # promotion settles: announces say PRIMARY again, the fence
        # lapses, and the orphan is finally reaped
        coord.state.role = "PRIMARY"
        deadline = time.time() + 10
        while task.state != "ABANDONED" and time.time() < deadline:
            time.sleep(0.05)
        assert task.state == "ABANDONED"
    finally:
        w.kill()
        coord.state.dispatcher.pool.shutdown(wait=False)
        coord.stop()
