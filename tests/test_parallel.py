"""Distributed execution tests on the virtual 8-device CPU mesh.

Reference pattern: DistributedQueryRunner boots coordinator+workers in one
JVM and asserts distributed results equal single-node results
(SURVEY.md §4.3). Here: the same kernels run single-device and as SPMD
stage programs over the mesh; results must be identical.
"""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from trino_tpu import ir
from trino_tpu.batch import batch_from_numpy
from trino_tpu.ops.aggregate import AggSpec, direct_group_aggregate
from trino_tpu.parallel.mesh import make_mesh, replicate, shard_rows
from trino_tpu.parallel.stages import (broadcast_join_step,
                                       sharded_agg_step,
                                       sharded_join_agg_step)
from trino_tpu.types import BIGINT, decimal


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return make_mesh(8)


def make_fact(n=8192, seed=3):
    rng = np.random.default_rng(seed)
    group = rng.integers(0, 6, n).astype(np.int32)       # dict codes
    key = rng.integers(1, 500, n).astype(np.int64)       # fk
    val = rng.integers(-10_000, 10_000, n).astype(np.int64)
    return group, key, val


def test_sharded_agg_matches_single_device(mesh):
    group, key, val = make_fact()
    batch = batch_from_numpy([group, key, val], pad_multiple=8192)

    flt = ir.Compare(">", ir.ColumnRef(2, BIGINT), ir.Literal(0, BIGINT))
    aggs = (AggSpec("sum", 2), AggSpec("count_star", None),
            AggSpec("min", 2), AggSpec("max", 2))

    # single-device reference
    from trino_tpu.ops.project import apply_filter
    single = direct_group_aggregate(apply_filter(batch, flt), (0,), (6,),
                                    aggs)

    sharded = shard_rows(batch, mesh)
    step = sharded_agg_step(mesh, flt, None, (0,), (6,), aggs)
    dist = step(sharded)

    np.testing.assert_array_equal(np.asarray(single.live),
                                  np.asarray(dist.live))
    for c_s, c_d in zip(single.columns, dist.columns):
        np.testing.assert_array_equal(np.asarray(c_s.data),
                                      np.asarray(c_d.data))
        np.testing.assert_array_equal(np.asarray(c_s.valid),
                                      np.asarray(c_d.valid))


def np_join_agg(group, key, val, bkey, bval):
    lookup = dict(zip(bkey.tolist(), bval.tolist()))
    sums = {}
    for g, k, v in zip(group, key, val):
        if k in lookup:
            sums.setdefault(int(g), 0)
            sums[int(g)] += v * lookup[k]
    return sums


def test_sharded_join_agg_matches_numpy(mesh):
    group, key, val = make_fact()
    bkey = np.arange(1, 401, dtype=np.int64)     # build: keys 1..400 unique
    bval = (bkey % 7 + 1).astype(np.int64)
    probe = batch_from_numpy([group, key, val], pad_multiple=8192)
    build = batch_from_numpy([bkey, bval], pad_multiple=8192)

    post = (ir.ColumnRef(0, BIGINT, "group"),
            ir.arith("*", ir.ColumnRef(2, BIGINT), ir.ColumnRef(4, BIGINT)))
    aggs = (AggSpec("sum", 1),)

    step = sharded_join_agg_step(mesh, 8, None, 1, None, 0,
                                 post, (0,), (6,), aggs)
    dist, dups = step(shard_rows(probe, mesh), shard_rows(build, mesh))
    assert int(dups) == 0          # unique build keys: no silent drops

    want = np_join_agg(group, key, val, bkey, bval)
    live = np.asarray(dist.live)
    got_keys = np.asarray(dist.columns[0].data)[live]
    got_sums = np.asarray(dist.columns[1].data)[live]
    assert set(got_keys.tolist()) == set(want)
    for k, s in zip(got_keys, got_sums):
        assert s == want[int(k)], (k, s, want[int(k)])


def test_broadcast_join_matches(mesh):
    group, key, val = make_fact(n=4096)
    bkey = np.arange(1, 500, dtype=np.int64)
    bval = (bkey * 3).astype(np.int64)
    probe = batch_from_numpy([group, key, val], pad_multiple=4096)
    build = batch_from_numpy([bkey, bval], pad_multiple=1024)

    step = broadcast_join_step(mesh, None, (1,), (0,), None)
    out = step(shard_rows(probe, mesh), replicate(build, mesh))

    live = np.asarray(out.live)
    got_val = np.asarray(out.columns[4].data)[live]
    got_key = np.asarray(out.columns[1].data)[live]
    np.testing.assert_array_equal(got_val, got_key * 3)
    assert live.sum() == len(key)  # all probe keys 1..499 match


def test_repartition_preserves_all_rows(mesh):
    from jax.sharding import PartitionSpec as P
    from trino_tpu.parallel.exchange import repartition_by_key
    group, key, val = make_fact(n=2048)
    batch = batch_from_numpy([group, key, val], pad_multiple=2048)
    sharded = shard_rows(batch, mesh)

    def body(local):
        return repartition_by_key(local, 1, 8)

    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P("workers"),),
                                out_specs=P("workers")))(sharded)
    live = np.asarray(out.live)
    assert live.sum() == 2048          # no row lost or duplicated
    # value multiset preserved
    got = np.sort(np.asarray(out.columns[2].data)[live])
    np.testing.assert_array_equal(got, np.sort(val))
    # co-location: each key now lives on exactly one shard
    keys_out = np.asarray(out.columns[1].data)
    shard_of = {}
    per_shard = keys_out.reshape(8, -1)
    live_s = live.reshape(8, -1)
    for s in range(8):
        for k in np.unique(per_shard[s][live_s[s]]):
            assert shard_of.setdefault(int(k), s) == s


def test_2d_mesh_distributed_query():
    """hosts x chips mesh: rows shard over both axes, GSPMD keeps global
    SQL semantics (the multi-host layout on the virtual device set)."""
    from trino_tpu.exec.session import Session
    from trino_tpu.parallel.dist_executor import MeshExecutor
    from trino_tpu.parallel.mesh import make_mesh_2d
    mesh = make_mesh_2d(2, 4)
    assert mesh.axis_names == ("hosts", "chips")
    s = Session(default_schema="tiny")
    s.executor = MeshExecutor(s.catalog, mesh)
    r = s.execute("SELECT n_regionkey, count(*) FROM nation "
                  "GROUP BY n_regionkey ORDER BY n_regionkey")
    assert [row[1] for row in r.rows] == [5, 5, 5, 5, 5]
    r = s.execute("SELECT count(*) FROM lineitem, orders "
                  "WHERE l_orderkey = o_orderkey AND o_totalprice > 100")
    assert r.rows[0][0] > 0


def test_sharded_join_detects_duplicate_build_keys(mesh):
    """The mesh fast path assumes unique build keys; a duplicate must be
    SURFACED (dups > 0), not silently dropped (the round-1 _dup hole)."""
    import numpy as np
    from trino_tpu.batch import batch_from_numpy
    from trino_tpu.ops.aggregate import AggSpec
    from trino_tpu.parallel.mesh import shard_rows
    from trino_tpu.parallel.stages import sharded_join_agg_step

    group = np.zeros(8192, dtype=np.int32)
    key = np.arange(8192, dtype=np.int64) % 100 + 1
    val = np.ones(8192, dtype=np.int64)
    probe = batch_from_numpy([group, key, val], pad_multiple=8192)
    bkey = np.concatenate([np.arange(1, 401, dtype=np.int64),
                           np.array([7], dtype=np.int64)])  # dup key 7
    bval = np.ones(len(bkey), dtype=np.int64)
    build = batch_from_numpy([bkey, bval], pad_multiple=8192)
    step = sharded_join_agg_step(mesh, 8, None, 1, None, 0,
                                 None, (0,), (6,), (AggSpec("sum", 2),))
    _out, dups = step(shard_rows(probe, mesh), shard_rows(build, mesh))
    assert int(dups) >= 1
