"""Parser tests (reference: core/trino-parser test suite, TestSqlParser)."""

import pytest

from trino_tpu.sql import ast_nodes as A
from trino_tpu.sql.parser import parse
from trino_tpu.sql.tokenizer import SqlSyntaxError

TPCH_Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

TPCH_Q3 = """
SELECT l_orderkey,
       sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""


def test_parse_q1_shape():
    q = parse(TPCH_Q1)
    assert isinstance(q, A.Query)
    assert len(q.select) == 10
    assert q.select[2].alias == "sum_qty"
    assert isinstance(q.relation, A.TableRef)
    assert len(q.group_by) == 2
    assert len(q.order_by) == 2
    assert q.limit is None
    # WHERE: l_shipdate <= DATE - INTERVAL
    w = q.where
    assert isinstance(w, A.BinaryOp) and w.op == "<="
    assert isinstance(w.right, A.BinaryOp) and w.op == "<="
    assert isinstance(w.right.left, A.DateLit)
    assert isinstance(w.right.right, A.IntervalLit)
    assert w.right.right.unit == "day" and w.right.right.value == 90


def test_parse_q3_comma_joins_and_limit():
    q = parse(TPCH_Q3)
    assert q.limit == 10
    assert isinstance(q.relation, A.Join) and q.relation.kind == "cross"
    assert not q.order_by[0].ascending
    assert q.order_by[1].ascending


def test_explicit_join_on():
    q = parse("SELECT a FROM t1 JOIN t2 ON t1.x = t2.y "
              "LEFT JOIN t3 ON t2.z = t3.z")
    r = q.relation
    assert isinstance(r, A.Join) and r.kind == "left"
    assert isinstance(r.left, A.Join) and r.left.kind == "inner"
    assert r.left.condition is not None


def test_precedence_and_or_not():
    q = parse("SELECT 1 FROM t WHERE a = 1 OR b = 2 AND NOT c = 3")
    w = q.where
    assert isinstance(w, A.BinaryOp) and w.op == "or"
    rhs = w.right
    assert isinstance(rhs, A.BinaryOp) and rhs.op == "and"
    assert isinstance(rhs.right, A.UnaryOp) and rhs.right.op == "not"


def test_arith_precedence():
    q = parse("SELECT 1 + 2 * 3 - 4 FROM t")
    e = q.select[0].expr
    # ((1 + (2*3)) - 4)
    assert isinstance(e, A.BinaryOp) and e.op == "-"
    assert isinstance(e.left, A.BinaryOp) and e.left.op == "+"
    assert isinstance(e.left.right, A.BinaryOp) and e.left.right.op == "*"


def test_case_cast_extract_functions():
    q = parse("""
      SELECT CASE WHEN x > 0 THEN 'pos' ELSE 'neg' END,
             CAST(x AS decimal(10,2)),
             EXTRACT(YEAR FROM d),
             count(DISTINCT y),
             count(*),
             substring(s, 1, 3)
      FROM t""")
    case, cast, ext, cntd, cnt, sub = [i.expr for i in q.select]
    assert isinstance(case, A.CaseExpr) and case.default is not None
    assert isinstance(cast, A.CastExpr) and cast.type_name == "decimal(10,2)"
    assert isinstance(ext, A.ExtractExpr) and ext.part == "year"
    assert isinstance(cntd, A.FunctionCall) and cntd.distinct
    assert isinstance(cnt, A.FunctionCall) and cnt.is_star
    assert isinstance(sub, A.FunctionCall) and len(sub.args) == 3


def test_predicates():
    q = parse("SELECT 1 FROM t WHERE a BETWEEN 1 AND 10 "
              "AND b NOT IN (1, 2) AND c LIKE '%x%' AND d IS NOT NULL")
    conj = []
    def flatten(e):
        if isinstance(e, A.BinaryOp) and e.op == "and":
            flatten(e.left); flatten(e.right)
        else:
            conj.append(e)
    flatten(q.where)
    assert isinstance(conj[0], A.BetweenPredicate)
    assert isinstance(conj[1], A.InPredicate) and conj[1].negated
    assert isinstance(conj[2], A.LikePredicate)
    assert isinstance(conj[3], A.IsNullPredicate) and conj[3].negated


def test_subqueries():
    q = parse("SELECT x FROM (SELECT a AS x FROM t) s "
              "WHERE x IN (SELECT y FROM u) AND EXISTS (SELECT 1 FROM v)")
    assert isinstance(q.relation, A.SubqueryRef) and q.relation.alias == "s"
    # scalar subquery
    q2 = parse("SELECT (SELECT max(a) FROM t) FROM u")
    assert isinstance(q2.select[0].expr, A.ScalarSubquery)


def test_string_escape_and_quoted_ident():
    q = parse("SELECT 'it''s', \"Weird Col\" FROM t")
    assert q.select[0].expr.value == "it's"
    assert q.select[1].expr.parts == ("Weird Col",)


def test_errors_have_position():
    with pytest.raises(SqlSyntaxError, match="line 1"):
        parse("SELECT FROM t")
    with pytest.raises(SqlSyntaxError):
        parse("SELECT a FROM t WHERE")
    with pytest.raises(SqlSyntaxError, match="trailing"):
        parse("SELECT a FROM t garbage garbage")


def test_explain_and_show():
    e = parse("EXPLAIN ANALYZE SELECT 1 FROM t")
    assert isinstance(e, A.Explain) and e.analyze
    s = parse("SHOW TABLES FROM tpch.tiny")
    assert s.catalog == "tpch" and s.schema == "tiny"
