"""Live query observability (round 21): streaming task heartbeats,
split-weighted progress, stuck/skew diagnosis, host/device utilization.

Covers the acceptance vectors: mid-flight system.runtime surfaces on a
live 2-worker query, monotonic progress reaching 1.0 at FINISHED through
the client protocol, failover progress re-derivation, stuck diagnosis on
a chaos-frozen worker task, the zero-overhead-off contract (no threads,
byte-identical announce/terminal wire format), and delta-heartbeat byte
bounds under a 100-task fanout.
"""

import json
import threading
import time

import pytest

from trino_tpu.client.cli import ProgressLine, progress_enabled
from trino_tpu.client.client import Client
from trino_tpu.exec.session import Session
from trino_tpu.metrics import REGISTRY
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.failureinjector import DELAY, FailureInjector
from trino_tpu.server.livestats import LiveStatsStore
from trino_tpu.server.tasks import TaskManager, WorkerTask
from trino_tpu.server.worker import WorkerServer


def _counter_value(name: str) -> float:
    m = REGISTRY.render()
    for line in m.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    return 0.0


# ---------------------------------------------------------------------------
# store unit tests (no cluster)
# ---------------------------------------------------------------------------


def _entry(tid, state="RUNNING", done=0, total=4, rows=0, nbytes=0,
           wall=0.0, dev=0.0, host=0.0, comp=0.0):
    return {"taskId": tid, "state": state, "splitsDone": done,
            "splitsTotal": total, "rowsOut": rows, "bytesOut": nbytes,
            "wallMs": wall, "deviceMs": dev, "hostMs": host,
            "compileMs": comp}


def test_store_progress_split_weighted():
    ls = LiveStatsStore()
    ls.begin("q1")
    ls.register_task("q1", "q1.0.0", stage="source", node="w0",
                     splits_total=4)
    ls.register_task("q1", "q1.0.1", stage="source", node="w1",
                     splits_total=4)
    assert ls.progress("q1") == 0.0
    ls.fold("w0", {"seq": 1, "tasks": [_entry("q1.0.0", done=2)]})
    ls.fold("w1", {"seq": 1, "tasks": [_entry("q1.0.1", done=4,
                                              state="FINISHED")]})
    # (2 + 4) of 8 splits
    assert ls.progress("q1") == pytest.approx(0.75)
    # a late-registered task lowers the instantaneous ratio (6/9) but
    # the high-water clamp keeps the surfaced progress at 0.75
    ls.register_task("q1", "q1.1.0", stage="partitioned", node="w0")
    ls.fold("w0", {"seq": 2, "tasks": [_entry("q1.1.0", total=0,
                                              state="RUNNING")]})
    assert ls.progress("q1") == pytest.approx(0.75)
    # splitless tasks (exchange consumers) weigh one split, done at
    # FINISHED
    ls.begin("q2")
    ls.register_task("q2", "q2.0.0", stage="source", node="w0",
                     splits_total=4)
    ls.register_task("q2", "q2.1.0", stage="partitioned", node="w0")
    ls.fold("w0", {"seq": 3, "tasks": [
        _entry("q2.0.0", done=4, state="FINISHED"),
        _entry("q2.1.0", total=0, state="RUNNING")]})
    assert ls.progress("q2") == pytest.approx(4 / 5)
    ls.fold("w0", {"seq": 4, "tasks": [_entry("q2.1.0", total=0,
                                              state="FINISHED")]})
    assert ls.progress("q2") == 1.0


def test_store_progress_monotonic_high_water():
    ls = LiveStatsStore()
    ls.begin("q1")
    ls.register_task("q1", "t0", stage="source", splits_total=4)
    ls.fold("w0", {"seq": 1, "tasks": [_entry("t0", done=3)]})
    assert ls.progress("q1") == pytest.approx(0.75)
    # a replayed/stale delta folding lower counters must never move the
    # surfaced progress backwards (the high-water clamp)
    ls.fold("w0", {"seq": 2, "tasks": [_entry("t0", done=1)]})
    assert ls.progress("q1") == pytest.approx(0.75)
    ls.finish("q1")
    assert ls.progress("q1") == 1.0


def test_store_failover_rederives_progress_from_heartbeats():
    """A promoted coordinator re-registers ledger-assigned (query, task)
    pairs with NO stage/split attribution; the next heartbeat's entries
    carry splitsTotal and refill the counters — progress must be
    re-derivable from that alone."""
    ls = LiveStatsStore()
    ls.begin("q9")
    # failover reattach: ids only, like CoordinatorServer._replay_ledger
    ls.register_task("q9", "q9.0.0")
    ls.register_task("q9", "q9.0.1")
    assert ls.progress("q9") == 0.0
    ls.fold("w0", {"seq": 7, "tasks": [
        _entry("q9.0.0", done=4, total=4, state="FINISHED"),
        _entry("q9.0.1", done=1, total=4)]})
    assert ls.progress("q9") == pytest.approx(5 / 8)


def test_store_stuck_diagnosis_names_stage_and_task():
    class TQ:
        live_diagnosis = None

    tq = TQ()
    ls = LiveStatsStore(tracked_lookup=lambda qid: tq, stuck_after=3)
    ls.begin("q2")
    ls.register_task("q2", "q2.0.0", stage="source", node="w0",
                     splits_total=4)
    ls.register_task("q2", "q2.0.1", stage="source", node="w1",
                     splits_total=4)
    ls.register_task("q2", "q2.0.2", stage="source", node="w1",
                     splits_total=4)
    before = _counter_value("trino_tpu_stuck_queries_diagnosed_total")
    # w1's tasks finish; w0's task stalls mid-split with pathological
    # per-split wall (skew vs the finished peers' median)
    ls.fold("w1", {"seq": 1, "tasks": [
        _entry("q2.0.1", done=4, wall=40, state="FINISHED"),
        _entry("q2.0.2", done=4, wall=44, state="FINISHED")]})
    ls.fold("w0", {"seq": 1, "tasks": [_entry("q2.0.0", done=1, wall=400,
                                              host=400.0)]})
    assert tq.live_diagnosis is None
    # identical heartbeats from the node holding the live work: the
    # stale counter climbs to stuck_after and the diagnosis fires once
    for i in range(2, 6):
        ls.fold("w0", {"seq": i, "tasks": [_entry("q2.0.0", done=1,
                                                  wall=400, host=400.0)]})
    d = tq.live_diagnosis
    assert d is not None
    assert d["queryId"] == "q2"
    assert d["stage"] == "source"
    assert d["taskId"] == "q2.0.0"
    assert d["node"] == "w0"
    assert d["phase"] == "host"
    # 400ms/split vs the 10ms/split peer median -> huge skew ratio
    assert d["skewRatio"] > 4.0
    assert d["staleHeartbeats"] >= 3
    after = _counter_value("trino_tpu_stuck_queries_diagnosed_total")
    assert after == before + 1
    # advancing counters reset the stall and re-arm the diagnoser
    ls.fold("w0", {"seq": 9, "tasks": [_entry("q2.0.0", done=2,
                                              wall=500)]})
    with ls._lock:
        assert ls._queries["q2"]["stale_folds"] == 0
        assert not ls._queries["q2"]["diagnosed"]


def test_store_straggler_feed_flags_slow_running_task():
    ls = LiveStatsStore()
    ls.begin("q3")
    for i, (done, wall, state) in enumerate(
            [(4, 40, "FINISHED"), (4, 44, "FINISHED"), (1, 400,
                                                        "RUNNING")]):
        tid = f"q3.0.{i}"
        ls.register_task("q3", tid, stage="source", node=f"w{i}",
                         splits_total=4)
        ls.fold(f"w{i}", {"seq": 1, "tasks": [_entry(tid, done=done,
                                                     wall=wall,
                                                     state=state)]})
    assert ls.straggler_task_ids("q3", 4.0) == {"q3.0.2"}
    # finished tasks never hedge, and multiplier<=0 disables the feed
    assert ls.straggler_task_ids("q3", 0) == set()
    assert ls.straggler_task_ids("missing", 4.0) == set()


def test_store_utilization_rows_per_node_and_tier():
    ls = LiveStatsStore()
    ls.fold("w0", {"seq": 1, "tasks": [],
                   "busy": {"deviceMs": 120.0, "hostMs": 80.0},
                   "utilization": {"device": 0.6, "host": 0.4}})
    rows = ls.utilization()
    assert {(r["node_id"], r["tier"]) for r in rows} == \
        {("w0", "device"), ("w0", "host")}
    dev = next(r for r in rows if r["tier"] == "device")
    assert dev["busy_fraction"] == pytest.approx(0.6)
    assert dev["busy_ms"] == pytest.approx(120.0)


# ---------------------------------------------------------------------------
# delta heartbeats: byte-bounded under fanout
# ---------------------------------------------------------------------------


def test_delta_heartbeat_bounded_under_100_task_fanout():
    session = Session(default_schema="tiny")
    tm = TaskManager(session.catalog, node_id="fanout")
    for i in range(100):
        t = WorkerTask(task_id=f"qf.0.{i}", fragment_blob="", splits=[])
        t.state = "RUNNING"
        t.splits_done = i % 4
        t.rows_out = i * 10
        tm.tasks[t.task_id] = t
        tm._note_live_change(t)
    cursor, entries = tm.live_delta(0)
    assert len(entries) == 100
    # each entry is a bounded scalar record — no operators, spans or
    # manifests ride the heartbeat
    for e in entries:
        assert len(json.dumps(e)) < 256
        assert set(e) == {"taskId", "state", "splitsDone", "splitsTotal",
                          "rowsOut", "bytesOut", "wallMs", "deviceMs",
                          "hostMs", "compileMs", "seq"}
    # absolute values: idempotent folds
    by_id = {e["taskId"]: e for e in entries}
    assert by_id["qf.0.7"]["splitsDone"] == 3
    assert by_id["qf.0.7"]["rowsOut"] == 70
    # nothing changed since the cursor -> the idle heartbeat is empty
    cursor2, entries2 = tm.live_delta(cursor)
    assert entries2 == [] and cursor2 == cursor
    # only the tasks that moved ship on the next delta
    for tid in ("qf.0.3", "qf.0.42", "qf.0.99"):
        t = tm.tasks[tid]
        t.splits_done += 1
        tm._note_live_change(t)
    _, entries3 = tm.live_delta(cursor)
    assert {e["taskId"] for e in entries3} == \
        {"qf.0.3", "qf.0.42", "qf.0.99"}


# ---------------------------------------------------------------------------
# zero-overhead-off contract
# ---------------------------------------------------------------------------


def test_heartbeat_off_no_threads_and_identical_wire_format(monkeypatch):
    import trino_tpu.server.worker as worker_mod

    session = Session(default_schema="tiny")
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    bodies = {}
    real_urlopen = worker_mod.urlopen

    def spy(req, timeout=5):
        url = getattr(req, "full_url", str(req))
        if url.endswith("/v1/announce"):
            doc = json.loads(req.data.decode())
            bodies[doc["nodeId"]] = doc
        return real_urlopen(req, timeout=timeout)

    monkeypatch.setattr(worker_mod, "urlopen", spy)
    w_off = WorkerServer("zo-off", coord.uri, announce_interval_s=30.0,
                         catalog=session.catalog).start()
    w_on = WorkerServer("zo-on", coord.uri, announce_interval_s=30.0,
                        heartbeat_interval_s=0.05,
                        catalog=session.catalog).start()
    try:
        # identical thread footprint: the heartbeat rides the announcer,
        # it never gets a thread of its own — and with the interval
        # unset nothing new runs at all
        assert len(w_off._threads) == 2
        assert len(w_on._threads) == 2
        assert not any("heartbeat" in th.name.lower()
                       for th in threading.enumerate())
        w_off.announce_once()
        w_on.announce_once()
        # heartbeats off -> the announce body is byte-identical to the
        # legacy wire format: exactly the five pre-round-21 keys
        assert set(bodies["zo-off"]) == \
            {"nodeId", "uri", "state", "now", "tasks"}
        # heartbeats on -> same keys plus the live piggyback
        assert set(bodies["zo-on"]) == \
            {"nodeId", "uri", "state", "now", "tasks", "liveStats",
             "memory"}
        assert set(bodies["zo-on"]["liveStats"]) == \
            {"seq", "tasks", "busy", "utilization"}
    finally:
        w_on.stop()
        w_off.stop()
        coord.stop()


def test_terminal_status_ignores_live_fields():
    """The live stamps (live_seq, started_at, tier ms) must never leak
    into the terminal status wire format: a task that streamed live
    stats serializes byte-identically to one that never did."""
    session = Session(default_schema="tiny")
    tm = TaskManager(session.catalog, node_id="n")

    def mk():
        t = WorkerTask(task_id="t0", fragment_blob="", splits=[])
        t.state = "FINISHED"
        t.rows_out, t.bytes_out, t.splits_done = 5, 100, 2
        t.stats = {"rowsOut": 5, "bytesOut": 100, "splitsDone": 2,
                   "wallMs": 1.5}
        return t

    plain, lived = mk(), mk()
    lived.live_seq = 999
    lived.started_at = 123.0
    lived.device_ms, lived.host_ms, lived.compile_ms = 9.0, 8.0, 7.0
    assert json.dumps(tm.status_json(plain), sort_keys=True) == \
        json.dumps(tm.status_json(lived), sort_keys=True)


# ---------------------------------------------------------------------------
# CLI progress line
# ---------------------------------------------------------------------------


class _Out:
    def __init__(self, atty=True):
        self.buf = []
        self.atty = atty

    def write(self, s):
        self.buf.append(s)

    def flush(self):
        pass

    def isatty(self):
        return self.atty


def test_progress_line_monotonic_and_cleared():
    out = _Out()
    pl = ProgressLine(out=out)
    pl.update({"state": "RUNNING", "progressRatio": 0.5,
               "stage": "source"})
    assert pl.ratio == 0.5
    # a re-derived (post-failover) lower ratio never moves the bar back
    pl.update({"state": "RUNNING", "progressRatio": 0.2})
    assert pl.ratio == 0.5
    pl.update({"state": "FINISHED"})
    assert pl.ratio == 1.0
    assert "100%" in out.buf[-2] + out.buf[-1]
    pl.clear()
    assert out.buf[-1].endswith("\r")


def test_progress_enabled_tty_pipe_dumb(monkeypatch):
    monkeypatch.setenv("TERM", "xterm-256color")
    assert progress_enabled("always", out=_Out(atty=False))
    assert not progress_enabled("never", out=_Out(atty=True))
    assert progress_enabled("auto", out=_Out(atty=True))
    assert not progress_enabled("auto", out=_Out(atty=False))
    monkeypatch.setenv("TERM", "dumb")
    assert not progress_enabled("auto", out=_Out(atty=True))


# ---------------------------------------------------------------------------
# cluster: mid-flight surfaces, progress through the protocol, stuck
# diagnosis on a frozen worker
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"ls-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            heartbeat_interval_s=0.05,
                            catalog=session.catalog).start()
               for i in range(2)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 2 and time.time() < deadline:
        time.sleep(0.05)
    yield coord, workers, session
    for w in workers:
        w.stop()
    coord.stop()


@pytest.fixture(autouse=True)
def _clean(request):
    if "cluster" not in request.fixturenames:
        yield
        return
    coord, workers, _ = request.getfixturevalue("cluster")
    coord.state.scheduler.spool.clear()
    yield
    for w in workers:
        w.task_manager.injector = None
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 2 and time.time() < deadline:
        time.sleep(0.05)


DIST_SQL = ("SELECT l_returnflag, count(*) AS c FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag")


def _run_async(uri, sql):
    box = {}

    def go():
        try:
            box["result"] = Client(uri, user="live").execute(sql)
        except Exception as e:             # noqa: BLE001 — surfaced below
            box["error"] = e

    th = threading.Thread(target=go, daemon=True)
    th.start()
    return th, box


def test_midflight_live_surfaces_populated(cluster):
    coord, workers, session = cluster
    want = session.execute(DIST_SQL).rows
    # warm worker-side fragments so the in-flight window is dominated by
    # the injected delays, not XLA compile
    Client(coord.uri, user="live").execute(DIST_SQL)
    coord.state.scheduler.spool.clear()
    ls = coord.state.livestats
    folds_before = ls.folds
    hb_before = _counter_value("trino_tpu_task_heartbeats_total")
    inj = FailureInjector(seed=211)
    # per-split delays on one worker hold the query observably in flight
    inj.inject("WORKER_TASK_RUN", times=8, fault=DELAY, delay_s=0.35)
    workers[0].task_manager.injector = inj
    th, box = _run_async(coord.uri, DIST_SQL)
    try:
        # wait until heartbeats have folded live task state for the query
        qid = None
        deadline = time.time() + 6
        while time.time() < deadline and qid is None:
            for rec in ls.live_queries():
                if rec["state"] == "RUNNING" and rec["tasks"] > 0:
                    qid = rec["query_id"]
                    break
            time.sleep(0.02)
        assert qid, "no live query surfaced while in flight"

        sys_client = Client(coord.uri, user="live-observer")
        # system.runtime.live_queries reflects the in-flight query
        r = sys_client.execute(
            "SELECT query_id, state, progress, tasks, splits_total, "
            "rows FROM system.runtime.live_queries")
        rows = {row[0]: row for row in r.rows}
        assert qid in rows
        _, state, progress, tasks, splits_total, _ = rows[qid]
        assert state in ("RUNNING", "FINISHED")
        assert tasks >= 1
        assert 0.0 <= progress <= 1.0

        # system.runtime.tasks carries the heartbeat-streamed live rows
        r = sys_client.execute(
            "SELECT query_id, task_id, state, splits FROM "
            "system.runtime.tasks")
        live_rows = [row for row in r.rows if row[0] == qid]
        assert live_rows, "no live task rows for the in-flight query"

        # /v1/query/{id} folds the live rollup mid-flight
        info = sys_client.query_info(qid)
        assert info["liveStats"] is not None
        assert info["liveStats"]["stages"], info["liveStats"]
        assert 0.0 <= info["progressRatio"] <= 1.0
    finally:
        th.join(timeout=30)
    assert "error" not in box, box.get("error")
    assert box["result"].state == "FINISHED"
    assert [tuple(r) for r in box["result"].rows] == \
        [tuple(r) for r in want]
    # the streams actually flowed
    assert ls.folds > folds_before
    assert _counter_value("trino_tpu_task_heartbeats_total") > hb_before
    # terminal view: forced to exactly 1.0
    info = Client(coord.uri, user="live").query_info(box["result"].query_id)
    assert info["progressRatio"] == 1.0


def test_progress_monotonic_through_protocol_pages(cluster):
    coord, workers, session = cluster
    inj = FailureInjector(seed=212)
    inj.inject("WORKER_TASK_RUN", times=6, fault=DELAY, delay_s=0.2)
    workers[1].task_manager.injector = inj
    seen = []
    client = Client(coord.uri, user="live", poll_interval_s=0.02,
                    on_progress=lambda s: seen.append(dict(s)))
    r = client.execute(DIST_SQL)
    assert r.state == "FINISHED"
    ratios = [s["progressRatio"] for s in seen if "progressRatio" in s]
    assert ratios, "protocol stats pages carried no progressRatio"
    assert all(0.0 <= x <= 1.0 for x in ratios)
    assert all(b >= a for a, b in zip(ratios, ratios[1:])), ratios
    assert ratios[-1] == 1.0
    assert seen[-1]["state"] == "FINISHED"


def test_stuck_diagnosis_fires_on_frozen_worker_task(cluster):
    coord, workers, session = cluster
    ls = coord.state.livestats
    sched = coord.state.scheduler
    # warm fragments so the freeze is the only thing holding the query
    Client(coord.uri, user="live").execute(DIST_SQL)
    sched.spool.clear()
    # hedging OFF: the live-skew feed would otherwise hedge the frozen
    # task away within a few heartbeats (test_live_skew_evidence_hedges
    # covers that) and the stall would never reach the stuck threshold
    old_multiplier = sched.hedge_multiplier
    sched.hedge_multiplier = 0
    inj = FailureInjector(seed=213)
    # freeze the first task that starts anywhere, mid-RUNNING (shared
    # times=1 rule: exactly one freeze, whichever worker hits it first)
    inj.inject("WORKER_TASK_RUN", times=1, fault=DELAY, delay_s=1.8)
    for w in workers:
        w.task_manager.injector = inj
    stuck_before = _counter_value("trino_tpu_stuck_queries_diagnosed_total")
    old_stuck_after = ls.stuck_after
    ls.stuck_after = 3
    # earlier queries in this module may carry their own diagnoses —
    # only a diagnosis on THIS test's query counts
    pre = {r["query_id"] for r in ls.live_queries()}
    th, box = _run_async(coord.uri, DIST_SQL)
    try:
        d = None
        deadline = time.time() + 10
        while time.time() < deadline and d is None:
            for rec in ls.live_queries():
                if rec["query_id"] in pre or not rec["stuck"]:
                    continue
                q = coord.state.tracker.get(rec["query_id"])
                d = getattr(q, "live_diagnosis", None)
                break
            time.sleep(0.02)
        assert inj.events, "the freeze never fired"
        frozen_task = inj.events[0][3].split(":")[0]
        frozen_node = next(
            w.node_id for w in workers
            if frozen_task in w.task_manager.tasks)
        assert d is not None, "no stuck diagnosis while a task was frozen"
        # the diagnosis names the frozen task, its node and its stage
        assert d["taskId"] == frozen_task
        assert d["node"] == frozen_node
        assert d["stage"]
        roll = ls.query_rollup(d["queryId"])
        assert d["taskId"] in {t["task_id"] for t in roll["tasks"]}
        assert d["staleHeartbeats"] >= 3
        assert d["phase"] in ("compile", "device", "host",
                              "exchange-wait")
        # ...and is surfaced on /v1/query/{id}
        info = Client(coord.uri, user="live").query_info(d["queryId"])
        assert info["diagnosis"] is not None
        assert info["diagnosis"]["taskId"] == d["taskId"]
    finally:
        ls.stuck_after = old_stuck_after
        sched.hedge_multiplier = old_multiplier
        th.join(timeout=30)
    assert "error" not in box, box.get("error")
    assert box["result"].state == "FINISHED"
    assert _counter_value("trino_tpu_stuck_queries_diagnosed_total") > \
        stuck_before


def test_live_skew_evidence_hedges_frozen_task(cluster):
    """The straggler feed in action: a task frozen mid-RUNNING is
    flagged by heartbeat-observed pace skew and its unit hedges on a
    survivor IMMEDIATELY — well before the wall-clock hedge threshold
    (hedge_min_s, default 2s) would fire — so the query finishes fast
    with exact rows."""
    coord, workers, session = cluster
    sched = coord.state.scheduler
    want = [tuple(r) for r in session.execute(DIST_SQL).rows]
    Client(coord.uri, user="live").execute(DIST_SQL)
    sched.spool.clear()
    inj = FailureInjector(seed=214)
    inj.inject("WORKER_TASK_RUN", times=1, fault=DELAY, delay_s=3.0)
    for w in workers:
        w.task_manager.injector = inj
    hedged_before = sched.stats["hedged_tasks"]
    t0 = time.monotonic()
    r = Client(coord.uri, user="live").execute(DIST_SQL)
    wall = time.monotonic() - t0
    assert r.state == "FINISHED"
    assert [tuple(row) for row in r.rows] == want
    assert sched.stats["hedged_tasks"] > hedged_before
    # live evidence beat both the 3s freeze and the 2s hedge_min_s
    assert wall < 1.8, \
        f"hedge waited for the wall-clock threshold: {wall:.2f}s"


def test_utilization_table_and_memory_refresh(cluster):
    coord, workers, session = cluster
    Client(coord.uri, user="live").execute(DIST_SQL)
    # heartbeats carried busy fractions for both workers
    deadline = time.time() + 3
    while time.time() < deadline:
        util = coord.state.livestats.utilization()
        if {r["node_id"] for r in util} >= {w.node_id for w in workers}:
            break
        time.sleep(0.05)
    r = Client(coord.uri, user="live").execute(
        "SELECT node_id, tier, busy_fraction FROM "
        "system.runtime.utilization")
    nodes = {row[0] for row in r.rows}
    assert {w.node_id for w in workers} <= nodes
    tiers = {row[1] for row in r.rows}
    assert tiers == {"device", "host"}
    assert all(0.0 <= row[2] <= 1.0 for row in r.rows)
    # satellite: heartbeat pool snapshots refresh node memory inventory
    # between announces
    with coord.state.nodes_lock:
        mems = [n.memory for n in coord.state.nodes.values()
                if n.node_id in {w.node_id for w in workers}]
    assert mems and all(m for m in mems)
