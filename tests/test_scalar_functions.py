"""Scalar function tests against the sqlite oracle.

Reference pattern: Trino's QueryAssertions expression tests over the
operator/scalar/ built-ins (SURVEY.md §4.1).
"""

import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, sql, abs_tol=0.01):
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol)


def test_abs_round_floor_ceil(session, oracle):
    check(session, oracle, """
        SELECT abs(1 - n_nationkey), round(n_nationkey / 7.0, 2),
               floor(n_nationkey / 7.0), ceil(n_nationkey / 7.0)
        FROM nation ORDER BY n_nationkey""")


def test_mod(session, oracle):
    check(session, oracle, """
        SELECT n_nationkey % 7, mod(n_nationkey, 4), mod(-7, 4)
        FROM nation ORDER BY n_nationkey""")


def test_coalesce_nullif(session, oracle):
    check(session, oracle, """
        SELECT coalesce(nullif(n_regionkey, 0), 99),
               nullif(n_nationkey, 5)
        FROM nation ORDER BY n_nationkey""")


def test_greatest_least(session, oracle):
    # sqlite max/min scalar functions = greatest/least
    got = session.execute("""
        SELECT greatest(n_nationkey, n_regionkey * 5),
               least(n_nationkey, n_regionkey * 5)
        FROM nation ORDER BY n_nationkey""").rows
    want = oracle_query(oracle, """
        SELECT max(n_nationkey, n_regionkey * 5),
               min(n_nationkey, n_regionkey * 5)
        FROM nation ORDER BY n_nationkey""")
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.01)


def test_math_doubles(session, oracle):
    check(session, oracle, """
        SELECT sqrt(n_nationkey), power(n_nationkey, 2),
               exp(n_regionkey / 10.0)
        FROM nation ORDER BY n_nationkey""", abs_tol=0.001)


def test_decimal_round(session, oracle):
    check(session, oracle, """
        SELECT round(o_totalprice, 1), round(o_totalprice)
        FROM orders ORDER BY o_orderkey LIMIT 100""")


def test_upper_lower_length(session, oracle):
    check(session, oracle, """
        SELECT lower(n_name), upper(n_name), length(n_name)
        FROM nation ORDER BY n_nationkey""")


def test_concat(session, oracle):
    # sqlite (pre-3.44) has no concat() function; oracle side uses ||
    got = session.execute("""
        SELECT 'nation: ' || n_name, concat(n_name, '!')
        FROM nation ORDER BY n_nationkey""").rows
    want = oracle_query(oracle, """
        SELECT 'nation: ' || n_name, n_name || '!'
        FROM nation ORDER BY n_nationkey""")
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)


def test_year_month_day_functions(session, oracle):
    # sqlite lacks year(); compare against strftime via EXTRACT translation
    got = session.execute("""
        SELECT o_orderkey, year(o_orderdate), month(o_orderdate),
               day(o_orderdate)
        FROM orders ORDER BY o_orderkey LIMIT 50""").rows
    want = oracle_query(oracle, """
        SELECT o_orderkey, CAST(strftime('%Y', o_orderdate) AS INTEGER),
               CAST(strftime('%m', o_orderdate) AS INTEGER),
               CAST(strftime('%d', o_orderdate) AS INTEGER)
        FROM orders ORDER BY o_orderkey LIMIT 50""")
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)


def test_scalar_func_nulls(session):
    rows = session.execute(
        "SELECT coalesce(NULL, 7), nullif(3, 3)").rows
    assert rows == [(7, None)]


def test_replace_starts_with_strpos(session, oracle):
    got = session.execute("""
        SELECT replace(n_name, 'A', '@'), strpos(n_name, 'AN')
        FROM nation ORDER BY n_nationkey LIMIT 5""").rows
    want = oracle_query(oracle, """
        SELECT replace(n_name, 'A', '@'), instr(n_name, 'AN')
        FROM nation ORDER BY n_nationkey LIMIT 5""")
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)
    got = session.execute(
        "SELECT n_name FROM nation WHERE starts_with(n_name, 'I') "
        "ORDER BY n_name").rows
    assert got == [("INDIA",), ("INDONESIA",), ("IRAN",), ("IRAQ",)]


def test_date_trunc(session):
    r = session.execute("""
        SELECT date_trunc('year', DATE '1994-07-15'),
               date_trunc('quarter', DATE '1994-07-15'),
               date_trunc('month', DATE '1994-07-15'),
               date_trunc('week', DATE '1994-07-15'),
               date_trunc('day', DATE '1994-07-15')""").rows[0]
    assert [str(x) for x in r] == ["1994-01-01", "1994-07-01",
                                   "1994-07-01", "1994-07-11",
                                   "1994-07-15"]
    grouped = session.execute("""
        SELECT date_trunc('month', o_orderdate) m, count(*) c
        FROM orders GROUP BY date_trunc('month', o_orderdate)
        ORDER BY m LIMIT 3""").rows
    assert all(str(m).endswith("-01") for m, _ in grouped)


def test_split_part_and_regexp_like(session):
    r = session.execute("""
        SELECT count(*) FROM customer
        WHERE split_part(c_phone, '-', 1) = '25'""").rows[0][0]
    r2 = session.execute("""
        SELECT count(*) FROM customer
        WHERE regexp_like(c_phone, '^25-')""").rows[0][0]
    assert r == r2
    assert r > 0


def test_approx_distinct_and_bool_aggs(session):
    rows = session.execute("""
        SELECT approx_distinct(o_custkey),
               count(DISTINCT o_custkey),
               bool_and(o_totalprice > 0),
               bool_or(o_totalprice > 100000000),
               bool_and(o_orderkey > 0)
        FROM orders""").rows[0]
    assert rows[0] == rows[1]
    assert rows[2] is True and rows[3] is False and rows[4] is True
    grouped = session.execute("""
        SELECT o_orderstatus, approx_distinct(o_clerk),
               bool_or(o_totalprice > 200000)
        FROM orders GROUP BY o_orderstatus ORDER BY o_orderstatus""").rows
    assert len(grouped) >= 2


def test_coalesce_varchar_unseen_literal_keeps_pool_sorted():
    """Regression (round-3 advisor, high): coalesce(varchar_col, 'lit')
    with a literal absent from the pool must INSERT it at its sorted
    position — appending breaks code-order == string-order, silently
    corrupting range compares / ORDER BY on the result."""
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    s.execute("CREATE TABLE m.s.t (id bigint, v varchar)")
    s.execute("INSERT INTO m.s.t VALUES (1, 'apple'), (2, NULL),"
              " (3, 'zebra'), (4, NULL), (5, 'mango')")
    # literal sorts strictly between existing pool entries
    rows = s.execute(
        "SELECT id, coalesce(v, 'banana') FROM m.s.t "
        "ORDER BY coalesce(v, 'banana'), id").rows
    assert rows == [(1, "apple"), (2, "banana"), (4, "banana"),
                    (5, "mango"), (3, "zebra")]
    # range compare across the inserted code
    n = s.execute("SELECT count(*) FROM m.s.t "
                  "WHERE coalesce(v, 'banana') < 'mango'").rows
    assert n == [(3,)]
    # literal sorts before everything (null_code = 0, all codes shift)
    rows = s.execute("SELECT id FROM m.s.t "
                     "ORDER BY coalesce(v, 'aaa') DESC, id").rows
    assert rows == [(3,), (5,), (1,), (2,), (4,)]
