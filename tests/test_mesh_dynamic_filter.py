"""Mesh-partitioned join + batched dynamic filtering (tier-1, 8 devices).

The quick-tier guards for the round-13 surface: the partitioned hash
join (all_to_all repartition + per-shard VMEM hash kernel inside one
shard_map program) must be bit-exact against the single-chip executor
with dynamic filtering on AND off, the TPC-DS q77 shape that used to
deadlock the mesh (rendezvous.cc "only 7 of 8 arrived" — one tiny
cross-module all-reduce per filter bound) must complete with filtering
ON, and the pruned-row observability surface must light up. Reference
pattern: TestDynamicFiltering / AbstractTestJoinQueries on a
DistributedQueryRunner.
"""

import numpy as np
import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session
from trino_tpu.parallel.dist_executor import MeshExecutor
from trino_tpu.parallel.mesh import make_mesh

JOIN_AGG = """
    SELECT n_name, count(*) AS c
    FROM customer, nation
    WHERE c_nationkey = n_nationkey
    GROUP BY n_name ORDER BY c DESC, n_name"""

# selective build side: the dynamic filter's min/max bounds prune most
# probe rows before the exchange
SELECTIVE = """
    SELECT count(*) FROM lineitem, orders
    WHERE l_orderkey = o_orderkey AND o_totalprice > 500000"""

PROBE_ROWS = """
    SELECT l_orderkey, l_linenumber, o_totalprice
    FROM lineitem, orders
    WHERE l_orderkey = o_orderkey AND o_totalprice > 400000
    ORDER BY l_orderkey, l_linenumber"""


def mesh_session(n_devices=8, **props):
    s = Session(default_schema="tiny")
    s.executor = MeshExecutor(s.catalog, make_mesh(n_devices))
    s.execute("SET SESSION join_distribution_type = 'partitioned'")
    # 'auto' resolves the hash kernel OFF on CPU; force interpret mode
    # so the tier-1 mesh exercises the same partitioned program TPUs run
    s.execute("SET SESSION enable_pallas_hash = true")
    for k, v in props.items():
        s.properties[k] = v
    return s


@pytest.fixture(scope="module")
def ref():
    return Session(default_schema="tiny")


def test_partitioned_join_bit_exact_vs_single_chip(ref):
    """Forced-partitioned mesh join == single-chip executor, row for
    row, with dynamic filtering on — and the partitioned path actually
    ran (not a silent broadcast demote)."""
    s = mesh_session()
    for sql in (JOIN_AGG, PROBE_ROWS):
        assert s.execute(sql).rows == ref.execute(sql).rows
    assert s.executor.stats.mesh_partitioned_joins >= 1


def test_probe_rows_bit_exact_filtering_on_vs_off(ref):
    """Distributed probe output must be IDENTICAL with the batched
    filter collectives on vs off — pruning is an optimization, never a
    semantics change (and off is the session escape hatch)."""
    on = mesh_session()
    off = mesh_session(mesh_dynamic_filtering=False)
    want = ref.execute(PROBE_ROWS).rows
    rows_on = on.execute(PROBE_ROWS).rows
    rows_off = off.execute(PROBE_ROWS).rows
    assert rows_on == want
    assert rows_off == want
    assert on.executor.stats.dynamic_filter_rows_pruned > 0
    assert off.executor.stats.dynamic_filter_rows_pruned == 0


def test_pruned_row_counters_nonzero_on_selective_join(ref):
    """The observability satellite: a selective join must move both the
    executor stat and the prometheus family."""
    from trino_tpu.metrics import DYNAMIC_FILTER_ROWS_PRUNED
    before = DYNAMIC_FILTER_ROWS_PRUNED.value()
    s = mesh_session()
    assert s.execute(SELECTIVE).rows == ref.execute(SELECTIVE).rows
    pruned = s.executor.stats.dynamic_filter_rows_pruned
    assert pruned > 0
    assert DYNAMIC_FILTER_ROWS_PRUNED.value() - before >= pruned


def test_explain_surfaces_join_distribution():
    s = mesh_session()
    s.execute(JOIN_AGG)
    text = "\n".join(r[0] for r in s.execute("EXPLAIN " + JOIN_AGG).rows)
    assert "join distribution: partitioned" in text


def test_run_scan_pads_odd_capacity_to_shard_multiple():
    """Satellite: a mesh whose size does not divide the 1024-row padding
    buckets (6 on the virtual 8-device host) must PAD and shard rather
    than silently staying single-device."""
    s = Session(default_schema="tiny")
    s.executor = MeshExecutor(s.catalog, make_mesh(6))
    ref_count = Session(default_schema="tiny").execute(
        "SELECT count(*) FROM lineitem").rows
    assert s.execute("SELECT count(*) FROM lineitem").rows == ref_count
    # the cached scan batch must be an exact shard multiple and actually
    # laid out across all 6 devices
    (batch,) = [b for b in s.executor._scan_cache.values()]
    assert batch.capacity % 6 == 0
    assert len(batch.live.sharding.device_set) == 6


def test_q77_completes_on_mesh_with_filtering_on():
    """The deadlock-class repro: TPC-DS q77 (five CTE join+agg arms,
    LEFT JOINs, ROLLUP) used to hang the virtual mesh when each filter
    bound dispatched its own collective. With the bounds batched into
    one program per join it must just run — filtering stays ON."""
    from tpcds_queries import QUERIES

    s = Session(default_cat="tpcds", default_schema="tiny")
    s.executor = MeshExecutor(s.catalog, make_mesh(8))
    assert s.executor.enable_dynamic_filtering
    assert s.executor.mesh_dynamic_filtering
    rows = s.execute(QUERIES[77]).rows
    assert 0 < len(rows) <= 100
    assert s.executor.stats.dynamic_filter_rows_pruned > 0
