"""End-to-end TPC-H query tests against the sqlite oracle.

Reference pattern: AbstractTestQueries + H2QueryRunner — every query runs on
both the engine and an independent SQL engine loaded with identical data,
and results must match (SURVEY.md §4.3-4.4). Decimal columns compare with
abs_tol 0.01 (engine decimals are exact scaled-int64; the oracle sums
REALs, and Trino-semantics avg(decimal) rounds at the argument scale).
"""

import numpy as np
import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.connectors.tpch.connector import TpchConnector
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, sql, ordered=True, abs_tol=0.01):
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol,
                      ordered=ordered)
    return got


Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey,
       sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate, l_orderkey
LIMIT 10
"""

Q5 = """
SELECT n_name,
       sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey
  AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey
  AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-01-01' + INTERVAL '1' YEAR
GROUP BY n_name
ORDER BY revenue DESC, n_name
"""

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""


def test_q1(session, oracle):
    rows = check(session, oracle, Q1)
    assert len(rows) == 4


def test_q6(session, oracle):
    rows = check(session, oracle, Q6)
    assert len(rows) == 1 and rows[0][0] > 0


def test_q3(session, oracle):
    rows = check(session, oracle, Q3)
    assert len(rows) == 10


def test_q5(session, oracle):
    rows = check(session, oracle, Q5)
    # at tiny scale not every ASIA nation has 1994 revenue; the oracle
    # match above is the real assertion
    assert 1 <= len(rows) <= 5
    revs = [r[1] for r in rows]
    assert revs == sorted(revs, reverse=True)


def test_simple_select_filter(session, oracle):
    check(session, oracle,
          "SELECT n_name, n_regionkey FROM nation "
          "WHERE n_regionkey = 3 ORDER BY n_name")


def test_projection_arith(session, oracle):
    check(session, oracle,
          "SELECT o_orderkey, o_totalprice * 2 FROM orders "
          "ORDER BY o_orderkey LIMIT 20")


def test_inner_join_explicit(session, oracle):
    check(session, oracle,
          "SELECT n_name, r_name FROM nation JOIN region "
          "ON n_regionkey = r_regionkey ORDER BY n_name")


def test_global_agg(session, oracle):
    check(session, oracle,
          "SELECT count(*), sum(o_totalprice), min(o_orderdate), "
          "max(o_orderdate) FROM orders")


def test_group_by_bigint_sort_strategy(session, oracle):
    check(session, oracle,
          "SELECT o_custkey, count(*), sum(o_totalprice) FROM orders "
          "GROUP BY o_custkey ORDER BY o_custkey LIMIT 50")


def test_distinct(session, oracle):
    check(session, oracle,
          "SELECT DISTINCT o_orderpriority FROM orders "
          "ORDER BY o_orderpriority")


def test_like_predicate(session, oracle):
    check(session, oracle,
          "SELECT count(*) FROM orders WHERE o_comment LIKE '%special%'")


def test_in_list(session, oracle):
    check(session, oracle,
          "SELECT count(*) FROM lineitem "
          "WHERE l_shipmode IN ('AIR', 'MAIL')")


def test_explain_renders(session):
    r = session.execute("EXPLAIN " + Q3)
    text = "\n".join(row[0] for row in r.rows)
    assert "Join" in text and "TableScan" in text and "TopN" in text


def test_show_tables(session):
    r = session.execute("SHOW TABLES FROM tpch.tiny")
    assert ("lineitem",) in r.rows


def test_one_to_many_join_device_expansion(session, oracle):
    # probe=orders (unique), build=lineitem (N per orderkey): forces the
    # planner to probe lineitem/build orders OR expansion; either way the
    # row count must match
    check(session, oracle,
          "SELECT count(*), sum(l_extendedprice) FROM orders, lineitem "
          "WHERE o_orderkey = l_orderkey AND o_orderdate >= DATE '1998-01-01'")


def test_q19_style_or_across_tables(session, oracle):
    check(session, oracle, """
        SELECT sum(l_extendedprice * (1 - l_discount))
        FROM lineitem, part
        WHERE p_partkey = l_partkey
          AND ((p_brand = 'Brand#11' AND l_quantity <= 11)
            OR (p_brand = 'Brand#22' AND l_quantity > 5))""")


def test_left_join(session, oracle):
    check(session, oracle,
          "SELECT count(*), count(o_orderkey) FROM customer "
          "LEFT JOIN orders ON c_custkey = o_custkey")


def test_pruned_plan_still_correct(session, oracle):
    # one narrow column out of the 16-column lineitem
    check(session, oracle,
          "SELECT max(l_shipdate) FROM lineitem")
    r = session.execute("EXPLAIN SELECT max(l_shipdate) FROM lineitem")
    text = "\n".join(row[0] for row in r.rows)
    assert "l_shipdate" in text
    assert "l_comment" not in text  # pruned from the scan
