"""Adaptive fused-chunk re-optimization (AdaptivePlanner.java:87's role,
replayed through the cross-run decision cache): a plain first run
measures per-join probe-key spans and post-join live counts; later runs
compile a windowed-gather + compacted variant sized by those
measurements, with in-program correctness flags that force a plain
rerun when new data violates the guesses.
"""

import numpy as np
import pytest

from trino_tpu.exec.session import Session

Q = """
SELECT o_orderpriority, count(*) AS c, sum(l_quantity) AS q
FROM lineitem, orders
WHERE l_orderkey = o_orderkey AND o_orderdate >= DATE '1996-01-01'
GROUP BY o_orderpriority ORDER BY o_orderpriority
"""


@pytest.fixture
def chunked_session():
    s = Session(default_schema="tiny")
    s.properties["spill_chunk_rows"] = 8192
    s.executor.spill_chunk_rows = 8192
    return s


def test_adaptation_records_then_applies(chunked_session):
    s = chunked_session
    ex = s.executor
    want = s.execute(Q).rows
    assert ex.stats.fused_chunk_pipelines >= 1
    skey = None
    recs = [k for k in ex._decision_cache if k[0] == "fusedadapt"]
    assert recs, "plain run must record span/live measurements"
    rec = ex._decision_cache[recs[0]]
    assert len(rec) >= 2 and all(v >= 0 for v in rec)

    # second run compiles the adapted program and must match exactly
    got = s.execute(Q).rows
    assert got == want


def test_violation_falls_back_to_plain(chunked_session):
    """Poison the recorded measurements so the adapted program's window
    and compaction are far too small: the in-program flags must catch it
    and the plain rerun must still produce correct results."""
    s = chunked_session
    ex = s.executor
    want = s.execute(Q).rows
    recs = [k for k in ex._decision_cache if k[0] == "fusedadapt"]
    assert recs
    key = recs[0]
    n = len(ex._decision_cache[key])
    ex._decision_cache[key] = tuple([8] * n)     # absurdly small
    got = s.execute(Q).rows
    assert got == want
    # the poisoned record was dropped (plain rerun re-measures next run)
    rec = ex._decision_cache.get(key)
    assert rec is None or rec != tuple([8] * n)


def test_mid_query_data_is_not_recorded_for_mutable_catalogs():
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    s.properties["spill_chunk_rows"] = 1024
    s.executor.spill_chunk_rows = 1024
    s.execute("CREATE TABLE m.s.f (k bigint, v bigint)")
    s.execute("CREATE TABLE m.s.d (k bigint, w bigint)")
    rows = ", ".join(f"({i % 97}, {i})" for i in range(3000))
    s.execute(f"INSERT INTO m.s.f VALUES {rows}")
    s.execute("INSERT INTO m.s.d SELECT DISTINCT k, k * 2 FROM m.s.f")
    q = ("SELECT sum(v + w) FROM m.s.f, m.s.d WHERE f.k = d.k")
    r1 = s.execute(q).rows
    r2 = s.execute(q).rows
    assert r1 == r2
    assert not [k for k in s.executor._decision_cache
                if k[0] == "fusedadapt"]


def test_direct_agg_cutoff_is_stats_driven():
    """Sparse groups (few rows per group) take the sort kernel even when
    the domain product fits the direct bound; dense groups keep the
    direct strategy. Session property direct_agg_max_groups tunes the
    bound (GroupByHash.java:82-93's strategy choice)."""
    from trino_tpu.sql.parser import parse
    s = Session(default_schema="tiny")

    def strategy_of(sql):
        rel = s.planner().plan_query(parse(sql))
        from trino_tpu.planner import logical as L

        def find(n):
            if isinstance(n, L.AggregateNode):
                return n
            for c in L.children(n):
                f = find(c)
                if f is not None:
                    return f
            return None
        return find(rel.node).strategy

    # lineitem tiny = 60k rows over 3 flags -> dense: direct
    assert strategy_of(
        "SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag") == "direct"
    # region: 5 rows over a 5-value dictionary -> 1 row/group: sort
    assert strategy_of(
        "SELECT r_name, count(*) FROM region GROUP BY r_name") == "sort"
    # property forces the bound down
    s.properties["direct_agg_max_groups"] = 1
    assert strategy_of(
        "SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag") == "sort"
    s.properties["direct_agg_max_groups"] = 64


def test_transfer_encodings_roundtrip():
    """Delta/plane transfer encodings decode to the original narrow
    column on device (device_cache ingest path)."""
    import numpy as np

    from trino_tpu.exec.device_cache import (decode_transfer,
                                             encode_transfer)
    rng = np.random.default_rng(3)
    cases = [
        np.sort(rng.integers(0, 1 << 30, 100_000)).astype(np.int32),
        rng.integers(-1 << 40, 1 << 40, 50_000).astype(np.int64),
        rng.integers(0, 120, 10_000).astype(np.int8),
        rng.integers(0, 1 << 15, 30_000).astype(np.int16),
        np.arange(100_000, dtype=np.int32) * 3,          # pure delta
        rng.normal(size=1000),                           # float: raw
        np.asarray([7], dtype=np.int32),                 # size<2: raw
    ]
    import jax
    for arr in cases:
        enc, payload, meta = encode_transfer(arr)
        meta = dict(meta, enc=enc, dtype=str(arr.dtype))
        dev = decode_transfer(enc, jax.device_put(
            np.ascontiguousarray(payload)), meta)
        got = np.asarray(dev)
        assert got.dtype == arr.dtype, (enc, got.dtype, arr.dtype)
        assert np.array_equal(got, arr), enc


def test_fact_cache_disk_tier_detects_changed_table(tmp_path, monkeypatch):
    """A regenerated table (same name, new contents) must not serve the
    stale narrowed cache (fingerprint check)."""
    import numpy as np

    from trino_tpu.batch import Field, Schema
    from trino_tpu.exec.device_cache import FactTableCache
    from trino_tpu.types import BIGINT
    monkeypatch.setenv("TRINO_TPU_DATA_CACHE", str(tmp_path))

    class T:
        def __init__(self, vals):
            self.columns = [np.asarray(vals, dtype=np.int64)]
            self.valids = None
            self.num_rows = len(vals)
            self.schema = Schema.of(Field("x", BIGINT))

    fc = FactTableCache()
    key = ("bench", "s", "t", (0,))
    t1 = T(np.arange(10_000))
    c1 = fc.load(key, t1, [0], persist_ok=True)
    assert np.asarray(c1[0].data)[5] == 5
    fc.invalidate()
    t2 = T(np.arange(10_000) * 7)       # regenerated, same shape
    c2 = fc.load(key, t2, [0], persist_ok=True)
    assert np.asarray(c2[0].data)[5] == 35
