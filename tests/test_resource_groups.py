"""Resource group admission tests (InternalResourceGroup semantics)."""

import threading
import time

import pytest

from trino_tpu.server.resourcegroups import (QueryQueueFullError,
                                             ResourceGroupConfig,
                                             ResourceGroupManager,
                                             Selector)


def test_concurrency_limit_and_queue():
    rgm = ResourceGroupManager(
        ResourceGroupConfig("root", hard_concurrency_limit=1,
                            max_queued=10))
    order = []
    release = threading.Event()

    def slow():
        order.append("first-started")

    def queued():
        order.append("second-started")

    rgm.submit("u", slow)          # runs immediately, holds the slot
    rgm.submit("u", queued)        # must queue
    assert order == ["first-started"]
    assert rgm.info()[0]["queued"] == 1
    nxt = rgm.finished("root")
    assert nxt is not None
    nxt()
    assert order == ["first-started", "second-started"]


def test_queue_full_rejects():
    rgm = ResourceGroupManager(
        ResourceGroupConfig("root", hard_concurrency_limit=1,
                            max_queued=1))
    rgm.submit("u", lambda: None)       # occupies the slot
    rgm.submit("u", lambda: None)       # queues
    with pytest.raises(QueryQueueFullError):
        rgm.submit("u", lambda: None)


def test_selectors_and_subgroups():
    rgm = ResourceGroupManager(
        ResourceGroupConfig("root", hard_concurrency_limit=10,
                            sub_groups=(
                                ResourceGroupConfig(
                                    "etl", hard_concurrency_limit=1),
                                ResourceGroupConfig(
                                    "adhoc", hard_concurrency_limit=2))),
        selectors=[Selector("etl_.*", "root.etl"),
                   Selector(".*", "root.adhoc")])
    assert rgm.select("etl_nightly").path == "root.etl"
    assert rgm.select("alice").path == "root.adhoc"
    # parent accounting: etl admission consumes root headroom too
    rgm.submit("etl_nightly", lambda: None)
    info = {g["group"]: g for g in rgm.info()}
    assert info["root.etl"]["running"] == 1
    assert info["root"]["running"] == 1


def test_coordinator_resource_group_endpoint():
    from trino_tpu.client.client import Client
    from trino_tpu.exec.session import Session
    from trino_tpu.server.coordinator import CoordinatorServer
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    try:
        client = Client(coord.uri, user="rg")
        client.execute("SELECT 1")
        import json
        from urllib.request import urlopen
        with urlopen(f"{coord.uri}/v1/resourceGroup", timeout=5) as r:
            info = json.loads(r.read())
        assert info[0]["group"] == "root"
        assert info[0]["totalAdmitted"] >= 1
    finally:
        coord.stop()
