"""Session surface tests: DDL/DML, SHOW/DESCRIBE, SET SESSION,
EXPLAIN (ANALYZE), information_schema, system.runtime.

Reference patterns: trino-memory connector tests, information_schema
connector, SystemSessionProperties, EXPLAIN ANALYZE output
(SURVEY.md §2.5, §2.11, §5.5, §5.6).
"""

import pytest

from trino_tpu.client.client import Client
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer


@pytest.fixture()
def session():
    return Session(default_cat="memory", default_schema="default")


@pytest.fixture(scope="module")
def tpch_session():
    return Session(default_schema="tiny")


def test_create_insert_select_drop(session):
    session.execute("CREATE TABLE default.t (a bigint, b varchar)")
    r = session.execute(
        "INSERT INTO t VALUES (1, 'x'), (2, 'y'), (3, NULL)")
    assert r.rows == [(3,)]
    got = session.execute("SELECT a, b FROM t ORDER BY a").rows
    assert got == [(1, "x"), (2, "y"), (3, None)]
    session.execute("INSERT INTO t VALUES (4, 'z')")
    got = session.execute(
        "SELECT count(*), count(b) FROM t").rows
    assert got == [(4, 3)]
    session.execute("DROP TABLE t")
    with pytest.raises(Exception):
        session.execute("SELECT * FROM t")


def test_ctas(session, tpch_session):
    tpch_session.execute("""
        CREATE TABLE memory.default.top_nations AS
        SELECT n_name, n_regionkey FROM tpch.tiny.nation
        WHERE n_regionkey = 1""")
    got = tpch_session.execute(
        "SELECT n_name FROM memory.default.top_nations "
        "ORDER BY n_name").rows
    assert len(got) == 5
    assert got[0][0] == "ARGENTINA"
    tpch_session.execute("DROP TABLE memory.default.top_nations")


def test_show_catalogs_schemas_tables(tpch_session):
    cats = [r[0] for r in tpch_session.execute("SHOW CATALOGS").rows]
    assert "tpch" in cats and "memory" in cats and "tpcds" in cats
    schemas = [r[0] for r in tpch_session.execute(
        "SHOW SCHEMAS FROM tpch").rows]
    assert "tiny" in schemas and "sf1" in schemas
    tables = [r[0] for r in tpch_session.execute("SHOW TABLES").rows]
    assert "lineitem" in tables


def test_describe(tpch_session):
    rows = tpch_session.execute("DESCRIBE nation").rows
    names = [r[0] for r in rows]
    assert names == ["n_nationkey", "n_name", "n_regionkey", "n_comment"]


def test_set_show_session(tpch_session):
    rows = dict((r[0], r[1]) for r in
                tpch_session.execute("SHOW SESSION").rows)
    assert rows["distributed"] == "False"
    tpch_session.execute("SET SESSION query_max_rows = 5000")
    rows = dict((r[0], r[1]) for r in
                tpch_session.execute("SHOW SESSION").rows)
    assert rows["query_max_rows"] == "5000"


def test_set_session_distributed_swaps_executor(tpch_session):
    from trino_tpu.parallel.dist_executor import MeshExecutor
    tpch_session.execute("SET SESSION distributed = true")
    assert isinstance(tpch_session.executor, MeshExecutor)
    r = tpch_session.execute("SELECT count(*) FROM lineitem")
    assert r.rows[0][0] > 0
    tpch_session.execute("SET SESSION distributed = false")
    assert not isinstance(tpch_session.executor, MeshExecutor)


def test_explain(tpch_session):
    text = "\n".join(r[0] for r in tpch_session.execute(
        "EXPLAIN SELECT count(*) FROM lineitem WHERE l_quantity > 10"
    ).rows)
    assert "TableScan" in text and "Aggregate" in text


def test_explain_analyze_has_stats(tpch_session):
    text = "\n".join(r[0] for r in tpch_session.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag").rows)
    assert "rows]" in text and "ms" in text


def test_information_schema(tpch_session):
    rows = tpch_session.execute("""
        SELECT table_name FROM tpch.information_schema.tables
        WHERE table_schema = 'tiny' ORDER BY table_name""").rows
    assert ("lineitem",) in rows
    cols = tpch_session.execute("""
        SELECT column_name, data_type
        FROM tpch.information_schema.columns
        WHERE table_name = 'nation' AND table_schema = 'tiny'
        ORDER BY ordinal_position""").rows
    assert cols[0][0] == "n_nationkey"


def test_system_runtime_queries():
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    try:
        client = Client(coord.uri, user="sys")
        client.execute("SELECT 1")
        rows = client.execute(
            "SELECT query_id, state, user FROM system.runtime.queries "
            "ORDER BY query_id").rows
        assert len(rows) >= 1
        assert any(r[2] == "sys" for r in rows)
        nodes = client.execute(
            "SELECT node_id, state FROM system.runtime.nodes").rows
        assert isinstance(nodes, list)
    finally:
        coord.stop()


def test_join_distribution_property_flips_plan():
    s = Session(default_schema="tiny")
    sql = ("SELECT c_name FROM customer c JOIN orders o"
           " ON c.c_custkey = o.o_custkey LIMIT 1")
    auto = s.execute("EXPLAIN " + sql).rows
    assert any("dist=broadcast" in r[0] for r in auto), auto
    s.execute("SET SESSION join_distribution_type = 'partitioned'")
    forced = s.execute("EXPLAIN " + sql).rows
    assert any("dist=partitioned" in r[0] for r in forced), forced
    # stats flip: a 0-byte threshold pushes every build to partitioned
    s.execute("SET SESSION join_distribution_type = 'auto'")
    s.execute("SET SESSION broadcast_join_threshold_mb = 0")
    tiny = s.execute("EXPLAIN " + sql).rows
    assert any("dist=partitioned" in r[0] for r in tiny), tiny


def test_query_deadline_enforced():
    import pytest as _pytest
    from trino_tpu.exec.executor import QueryDeadlineError
    s = Session(default_schema="tiny")
    s.execute("SET SESSION query_max_run_time_s = 0.000001")
    with _pytest.raises(QueryDeadlineError):
        s.execute("SELECT count(*) FROM lineitem, orders"
                  " WHERE l_orderkey = o_orderkey")
    s.execute("SET SESSION query_max_run_time_s = 0")
    r = s.execute("SELECT count(*) FROM nation")
    assert r.rows[0][0] == 25


def test_scan_cache_lru_eviction():
    s = Session(default_schema="tiny")
    s.execute("SET SESSION scan_cache_max_mb = 0")
    for t in ("nation", "region", "supplier", "customer", "orders"):
        s.execute(f"SELECT count(*) FROM {t}")
        # a zero budget keeps at most the current table resident
        assert len(s.executor._scan_cache) <= 1
    # results stay correct with continuous eviction
    assert s.execute("SELECT count(*) FROM nation").rows[0][0] == 25
    s.execute("SET SESSION scan_cache_max_mb = 1024")
    s.execute("SELECT count(*) FROM nation")
    s.execute("SELECT count(*) FROM region")
    assert len(s.executor._scan_cache) == 2


def test_dynamic_filtering_toggle():
    s = Session(default_schema="tiny")
    sql = ("SELECT count(*) FROM lineitem, orders"
           " WHERE l_orderkey = o_orderkey AND o_orderkey < 100")
    want = s.execute(sql).rows
    s.execute("SET SESSION dynamic_filtering = false")
    got = s.execute(sql).rows
    assert got == want
