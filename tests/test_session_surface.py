"""Session surface tests: DDL/DML, SHOW/DESCRIBE, SET SESSION,
EXPLAIN (ANALYZE), information_schema, system.runtime.

Reference patterns: trino-memory connector tests, information_schema
connector, SystemSessionProperties, EXPLAIN ANALYZE output
(SURVEY.md §2.5, §2.11, §5.5, §5.6).
"""

import pytest

from trino_tpu.client.client import Client
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer


@pytest.fixture()
def session():
    return Session(default_cat="memory", default_schema="default")


@pytest.fixture(scope="module")
def tpch_session():
    return Session(default_schema="tiny")


def test_create_insert_select_drop(session):
    session.execute("CREATE TABLE default.t (a bigint, b varchar)")
    r = session.execute(
        "INSERT INTO t VALUES (1, 'x'), (2, 'y'), (3, NULL)")
    assert r.rows == [(3,)]
    got = session.execute("SELECT a, b FROM t ORDER BY a").rows
    assert got == [(1, "x"), (2, "y"), (3, None)]
    session.execute("INSERT INTO t VALUES (4, 'z')")
    got = session.execute(
        "SELECT count(*), count(b) FROM t").rows
    assert got == [(4, 3)]
    session.execute("DROP TABLE t")
    with pytest.raises(Exception):
        session.execute("SELECT * FROM t")


def test_ctas(session, tpch_session):
    tpch_session.execute("""
        CREATE TABLE memory.default.top_nations AS
        SELECT n_name, n_regionkey FROM tpch.tiny.nation
        WHERE n_regionkey = 1""")
    got = tpch_session.execute(
        "SELECT n_name FROM memory.default.top_nations "
        "ORDER BY n_name").rows
    assert len(got) == 5
    assert got[0][0] == "ARGENTINA"
    tpch_session.execute("DROP TABLE memory.default.top_nations")


def test_show_catalogs_schemas_tables(tpch_session):
    cats = [r[0] for r in tpch_session.execute("SHOW CATALOGS").rows]
    assert "tpch" in cats and "memory" in cats and "tpcds" in cats
    schemas = [r[0] for r in tpch_session.execute(
        "SHOW SCHEMAS FROM tpch").rows]
    assert "tiny" in schemas and "sf1" in schemas
    tables = [r[0] for r in tpch_session.execute("SHOW TABLES").rows]
    assert "lineitem" in tables


def test_describe(tpch_session):
    rows = tpch_session.execute("DESCRIBE nation").rows
    names = [r[0] for r in rows]
    assert names == ["n_nationkey", "n_name", "n_regionkey", "n_comment"]


def test_set_show_session(tpch_session):
    rows = dict((r[0], r[1]) for r in
                tpch_session.execute("SHOW SESSION").rows)
    assert rows["distributed"] == "False"
    tpch_session.execute("SET SESSION query_max_rows = 5000")
    rows = dict((r[0], r[1]) for r in
                tpch_session.execute("SHOW SESSION").rows)
    assert rows["query_max_rows"] == "5000"


def test_set_session_distributed_swaps_executor(tpch_session):
    from trino_tpu.parallel.dist_executor import MeshExecutor
    tpch_session.execute("SET SESSION distributed = true")
    assert isinstance(tpch_session.executor, MeshExecutor)
    r = tpch_session.execute("SELECT count(*) FROM lineitem")
    assert r.rows[0][0] > 0
    tpch_session.execute("SET SESSION distributed = false")
    assert not isinstance(tpch_session.executor, MeshExecutor)


def test_explain(tpch_session):
    text = "\n".join(r[0] for r in tpch_session.execute(
        "EXPLAIN SELECT count(*) FROM lineitem WHERE l_quantity > 10"
    ).rows)
    assert "TableScan" in text and "Aggregate" in text


def test_explain_analyze_has_stats(tpch_session):
    text = "\n".join(r[0] for r in tpch_session.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag").rows)
    assert "rows]" in text and "ms" in text


def test_information_schema(tpch_session):
    rows = tpch_session.execute("""
        SELECT table_name FROM tpch.information_schema.tables
        WHERE table_schema = 'tiny' ORDER BY table_name""").rows
    assert ("lineitem",) in rows
    cols = tpch_session.execute("""
        SELECT column_name, data_type
        FROM tpch.information_schema.columns
        WHERE table_name = 'nation' AND table_schema = 'tiny'
        ORDER BY ordinal_position""").rows
    assert cols[0][0] == "n_nationkey"


def test_system_runtime_queries():
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    try:
        client = Client(coord.uri, user="sys")
        client.execute("SELECT 1")
        rows = client.execute(
            "SELECT query_id, state, user FROM system.runtime.queries "
            "ORDER BY query_id").rows
        assert len(rows) >= 1
        assert any(r[2] == "sys" for r in rows)
        nodes = client.execute(
            "SELECT node_id, state FROM system.runtime.nodes").rows
        assert isinstance(nodes, list)
    finally:
        coord.stop()
