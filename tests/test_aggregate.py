"""Aggregation kernel tests (reference: TestHashAggregationOperator and
aggregation function tests, SURVEY.md §4.1)."""

import numpy as np

from trino_tpu.batch import batch_from_numpy
from trino_tpu.ops.aggregate import (AggSpec, avg_decimal_finalize,
                                     direct_group_aggregate,
                                     global_aggregate, sort_group_aggregate)


def np_groupby_sum(keys, vals, mask):
    out = {}
    for k, v, m in zip(keys, vals, mask):
        if m:
            out.setdefault(k, 0)
            out[k] += v
    return out


def test_direct_group_sum_count():
    codes = np.array([0, 1, 0, 2, 1, 0], dtype=np.int32)
    vals = np.array([10, 20, 30, 40, 50, 60], dtype=np.int64)
    batch = batch_from_numpy([codes, vals], pad_multiple=8)
    out = direct_group_aggregate(
        batch, (0,), (3,),
        (AggSpec("sum", 1), AggSpec("count_star", None)))
    live = np.asarray(out.live)
    assert live[:3].all()
    np.testing.assert_array_equal(np.asarray(out.columns[0].data)[:3],
                                  [0, 1, 2])
    np.testing.assert_array_equal(np.asarray(out.columns[1].data)[:3],
                                  [100, 70, 40])
    np.testing.assert_array_equal(np.asarray(out.columns[2].data)[:3],
                                  [3, 2, 1])


def test_direct_two_keys_mixed_radix():
    k1 = np.array([0, 1, 1, 0], dtype=np.int32)
    k2 = np.array([1, 0, 1, 1], dtype=np.int32)
    v = np.array([1, 2, 3, 4], dtype=np.int64)
    batch = batch_from_numpy([k1, k2, v], pad_multiple=4)
    out = direct_group_aggregate(batch, (0, 1), (2, 2),
                                 (AggSpec("sum", 2),))
    # group ids: (0,0)=0 (dead), (0,1)=1 -> 5, (1,0)=2 -> 2, (1,1)=3 -> 3
    live = np.asarray(out.live)
    np.testing.assert_array_equal(live, [False, True, True, True])
    np.testing.assert_array_equal(np.asarray(out.columns[2].data)[1:],
                                  [5, 2, 3])


def test_sum_nulls_and_empty_group_null():
    codes = np.array([0, 0, 1], dtype=np.int32)
    vals = np.array([5, 7, 9], dtype=np.int64)
    valid = np.array([True, False, False])
    batch = batch_from_numpy([codes, vals], valids=[None, valid],
                             pad_multiple=4)
    out = direct_group_aggregate(
        batch, (0,), (2,), (AggSpec("sum", 1), AggSpec("count", 1)))
    sums = np.asarray(out.columns[1].data)
    sums_valid = np.asarray(out.columns[1].valid)
    counts = np.asarray(out.columns[2].data)
    assert sums[0] == 5 and sums_valid[0]
    assert not sums_valid[1]          # all-NULL group -> sum is NULL
    np.testing.assert_array_equal(counts[:2], [1, 0])


def test_sort_group_matches_numpy_random():
    rng = np.random.default_rng(7)
    n = 5000
    keys = rng.integers(0, 500, n).astype(np.int64)
    vals = rng.integers(-1000, 1000, n).astype(np.int64)
    batch = batch_from_numpy([keys, vals])
    out = sort_group_aggregate(
        batch, (0,),
        (AggSpec("sum", 1), AggSpec("min", 1), AggSpec("max", 1),
         AggSpec("count_star", None)),
        1024)
    live = np.asarray(out.live)
    got_keys = np.asarray(out.columns[0].data)[live]
    got_sums = np.asarray(out.columns[1].data)[live]
    got_mins = np.asarray(out.columns[2].data)[live]
    got_maxs = np.asarray(out.columns[3].data)[live]
    want = np_groupby_sum(keys, vals, np.ones(n, bool))
    assert len(got_keys) == len(want)
    order = np.argsort(got_keys)
    for i in order:
        k = got_keys[i]
        assert got_sums[i] == want[k]
        sel = vals[keys == k]
        assert got_mins[i] == sel.min() and got_maxs[i] == sel.max()


def test_sort_group_null_keys_group_together():
    keys = np.array([1, 1, 2], dtype=np.int64)
    kvalid = np.array([False, False, True])
    vals = np.array([10, 20, 30], dtype=np.int64)
    batch = batch_from_numpy([keys, vals], valids=[kvalid, None],
                             pad_multiple=4)
    out = sort_group_aggregate(batch, (0,), (AggSpec("sum", 1),), 4)
    live = np.asarray(out.live)
    assert live.sum() == 2            # NULL group + key=2 group
    kv = np.asarray(out.columns[0].valid)[live]
    sums = np.asarray(out.columns[1].data)[live]
    assert sorted(zip(kv.tolist(), sums.tolist())) == [(False, 30), (True, 30)]


def test_global_aggregate_empty_input():
    batch = batch_from_numpy([np.array([], dtype=np.int64)])
    out = global_aggregate(batch, (AggSpec("sum", 0),
                                   AggSpec("count", 0),
                                   AggSpec("count_star", None)))
    assert bool(out.live[0])
    assert not bool(out.columns[0].valid[0])   # sum over empty -> NULL
    assert int(out.columns[1].data[0]) == 0
    assert int(out.columns[2].data[0]) == 0


def test_avg_decimal_finalize_half_up():
    sums = np.array([10, 11, -11, 7], dtype=np.int64)
    counts = np.array([4, 2, 2, 2], dtype=np.int64)
    # 10/4=2.5 -> 3; 11/2=5.5 -> 6; -11/2=-5.5 -> -6; 7/2=3.5 -> 4
    np.testing.assert_array_equal(avg_decimal_finalize(sums, counts),
                                  [3, 6, -6, 4])


def test_dynamic_filter_compaction():
    """Build-side key range prunes + compacts the probe (DynamicFilterService
    role, executor edition)."""
    from trino_tpu.exec.session import Session
    s = Session(default_cat="memory", default_schema="default")
    s.execute("CREATE TABLE big AS SELECT o_orderkey k, o_totalprice v "
              "FROM tpch.tiny.orders")
    s.execute("CREATE TABLE dim (k bigint, name varchar)")
    s.execute("INSERT INTO dim VALUES (97, 'a'), (101, 'b'), (103, 'c')")
    r = s.execute("SELECT count(*) FROM big, dim WHERE big.k = dim.k")
    assert r.rows[0][0] == 3
    assert s.executor.stats.dynamic_filter_compactions >= 1
