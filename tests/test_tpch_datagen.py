"""TPC-H generator sanity + oracle harness smoke tests.

Reference: plugin/trino-tpch tests assert deterministic generation;
H2QueryRunner loads the same data into the oracle (SURVEY.md §4.4)."""

import numpy as np

from oracle import load_oracle, oracle_query, translate
from trino_tpu.connectors.tpch.connector import TpchConnector


def get_tiny():
    conn = TpchConnector()
    return {t: conn.get_table("tiny", t)
            for t in ["region", "nation", "customer", "orders", "lineitem"]}


def test_row_counts_and_determinism():
    c1, c2 = TpchConnector(), TpchConnector()
    t1 = c1.get_table("tiny", "lineitem")
    t2 = c2.get_table("tiny", "lineitem")
    assert t1.num_rows == t2.num_rows
    np.testing.assert_array_equal(t1.columns[0], t2.columns[0])
    orders = c1.get_table("tiny", "orders")
    assert orders.num_rows == 15_000
    assert c1.get_table("tiny", "customer").num_rows == 1_500
    # lineitem ~4x orders on average
    assert 3.5 * orders.num_rows < t1.num_rows < 4.5 * orders.num_rows


def test_referential_integrity():
    t = get_tiny()
    custkeys = set(t["customer"].columns[0].tolist())
    assert set(t["orders"].columns[1].tolist()) <= custkeys
    orderkeys = set(t["orders"].columns[0].tolist())
    assert set(t["lineitem"].columns[0].tolist()) <= orderkeys
    # dbgen invariant: no customer with custkey % 3 == 0 places orders
    assert all(k % 3 != 0 for k in set(t["orders"].columns[1].tolist()))


def test_dates_consistent():
    li = get_tiny()["lineitem"]
    s = li.schema
    ship = li.columns[s.index_of("l_shipdate")]
    receipt = li.columns[s.index_of("l_receiptdate")]
    assert (receipt > ship).all()


def test_translate_dialect():
    assert translate("DATE '1994-01-01'") == "'1994-01-01'"
    assert translate(
        "DATE '1995-01-01' + INTERVAL '3' MONTH") == "'1995-04-01'"
    assert translate(
        "DATE '1994-01-01' + INTERVAL '1' YEAR") == "'1995-01-01'"
    out = translate("EXTRACT(YEAR FROM o_orderdate)")
    assert "strftime" in out


def test_oracle_q6_runs():
    t = get_tiny()
    conn = load_oracle([t["lineitem"]])
    rows = oracle_query(conn, """
        SELECT sum(l_extendedprice * l_discount)
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
          AND l_discount BETWEEN 0.05 AND 0.07
          AND l_quantity < 24""")
    assert rows[0][0] is not None and rows[0][0] > 0
