"""TPC-DS query tests against the sqlite oracle.

Reference pattern: trino-tpcds conformance + benchmark query suites
(SURVEY.md §2.11, §6) — the engine and an independent SQL engine run the
same queries over identical generated data.
"""

import pytest

pytestmark = pytest.mark.slow

from oracle import assert_rows_match, load_oracle, oracle_query
from tpcds_queries import ORACLE, QUERIES, ULP_SENSITIVE
from trino_tpu.connectors.tpcds.connector import TABLE_NAMES
from trino_tpu.exec.session import Session


@pytest.fixture(scope="module")
def session():
    return Session(default_cat="tpcds", default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpcds")
    return load_oracle([conn.get_table("tiny", t) for t in TABLE_NAMES])


def test_datagen_shapes(session):
    conn = session.catalog.connector("tpcds")
    ss = conn.get_table("tiny", "store_sales")
    assert ss.num_rows >= 100000
    dd = conn.get_table("tiny", "date_dim")
    assert dd.num_rows == 1826


def test_fact_nulls_present(session):
    r = session.execute(
        "SELECT count(*) - count(ss_customer_sk) FROM store_sales")
    assert r.rows[0][0] > 0


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpcds_query(session, oracle, qid):
    sql = QUERIES[qid]
    got = session.execute(sql).rows
    want = oracle_query(oracle, ORACLE.get(qid, sql))
    if qid in ULP_SENSITIVE:
        # rank columns over floating-tie ratios swap between engines;
        # compare the identifying columns as a set
        got = sorted((r[0], r[1]) for r in got)
        want = sorted((r[0], r[1]) for r in want)
        assert len(got) == len(want)
        # allow tie-boundary membership wobble on at most 2 rows
        misses = len(set(got) - set(want))
        assert misses <= 2, (misses, got[:5], want[:5])
        return
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.02, ordered=True)
