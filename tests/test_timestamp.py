"""TIMESTAMP type tests (micros since epoch; Trino timestamp(6) layout)."""

import pytest

from trino_tpu.exec.session import Session


@pytest.fixture()
def session():
    return Session(default_cat="memory", default_schema="default")


def test_timestamp_ddl_literals_compare(session):
    session.execute("CREATE TABLE ev (name varchar, at timestamp)")
    session.execute("""
        INSERT INTO ev VALUES
          ('a', TIMESTAMP '2024-01-15 08:30:00'),
          ('b', TIMESTAMP '2024-01-15 19:45:30'),
          ('c', TIMESTAMP '2024-02-01 00:00:00'),
          ('d', NULL)""")
    rows = session.execute(
        "SELECT name, at FROM ev "
        "WHERE at >= TIMESTAMP '2024-01-15 12:00:00' ORDER BY at").rows
    assert [r[0] for r in rows] == ["b", "c"]
    assert rows[0][1] == "2024-01-15 19:45:30"


def test_timestamp_extract_and_functions(session):
    session.execute("CREATE TABLE t2 (at timestamp)")
    session.execute(
        "INSERT INTO t2 VALUES (TIMESTAMP '2023-07-04 13:05:59')")
    rows = session.execute("""
        SELECT EXTRACT(YEAR FROM at), EXTRACT(MONTH FROM at),
               EXTRACT(DAY FROM at), EXTRACT(HOUR FROM at),
               minute(at), second(at), CAST(at AS date)
        FROM t2""").rows
    assert rows == [(2023, 7, 4, 13, 5, 59, "2023-07-04")]


def test_date_to_timestamp_comparison(session):
    session.execute("CREATE TABLE t3 (d date, at timestamp)")
    session.execute("INSERT INTO t3 VALUES "
                    "(DATE '2024-03-01', TIMESTAMP '2024-03-01 10:00:00')")
    rows = session.execute(
        "SELECT count(*) FROM t3 WHERE at > d").rows
    assert rows == [(1,)]


def test_timestamp_aggregates_and_sort(session):
    session.execute("CREATE TABLE t4 (g bigint, at timestamp)")
    session.execute("""
        INSERT INTO t4 VALUES
          (1, TIMESTAMP '2024-01-01 01:00:00'),
          (1, TIMESTAMP '2024-01-02 02:00:00'),
          (2, TIMESTAMP '2024-01-03 03:00:00')""")
    rows = session.execute(
        "SELECT g, min(at), max(at) FROM t4 GROUP BY g ORDER BY g").rows
    assert rows[0] == (1, "2024-01-01 01:00:00", "2024-01-02 02:00:00")
    assert rows[1][0] == 2
