"""Cost-based join reordering plan tests.

Reference pattern: the plan-assertion tests around ReorderJoins
(core/trino-main/.../sql/planner/iterative/rule/ReorderJoins.java:97,
exercised by BasePlanTest subclasses): assert the optimizer picked a
different — and cheaper — join order than the FROM-clause order.
"""

import pytest

from trino_tpu.exec.session import Session
from trino_tpu.planner import logical as L

Q5 = """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey
  AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey
  AND n_regionkey = r_regionkey
  AND r_name = 'ASIA'
  AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1995-01-01'
GROUP BY n_name
ORDER BY revenue DESC
"""


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


def _joins(node):
    out = []

    def walk(n):
        if isinstance(n, L.JoinNode):
            out.append(n)
        for c in L.children(n):
            walk(c)
    walk(node)
    return out


def _scans(node):
    out = []

    def walk(n):
        if isinstance(n, L.ScanNode):
            out.append(n.table)
        for c in L.children(n):
            walk(c)
    walk(node)
    return out


def test_q5_joins_stay_single_key_dense(session):
    """The greedy left-deep order joined customer against the fact row on
    (o_custkey, s_nationkey) — a multi-column key with no dense domain,
    which forces the sorted-join kernels. The DP order + key
    minimization must keep EVERY inner join single-key (the nationkey
    equality becomes a post-join filter)."""
    from trino_tpu.sql.parser import parse
    rel = session.planner().plan_query(parse(Q5))
    joins = _joins(rel.node)
    assert len(joins) >= 4
    for j in joins:
        assert len(j.left_keys) == 1, \
            f"multi-key join survived reordering: {j.left_keys}"


def test_q5_bushy_build_side(session):
    """The winning q5 shape builds a dimension subtree (bushy tree):
    at least one join's BUILD side contains another join — the greedy
    left-deep order can never produce this."""
    from trino_tpu.sql.parser import parse
    rel = session.planner().plan_query(parse(Q5))
    joins = _joins(rel.node)
    assert any(_joins(j.right) for j in joins), \
        "no bushy build subtree in q5 plan"


def test_q5_fact_table_stays_probe_spine(session):
    """lineitem (the largest relation) must sit on the probe spine all
    the way up — the chunked driver can only stream the probe side."""
    from trino_tpu.sql.parser import parse
    rel = session.planner().plan_query(parse(Q5))
    joins = _joins(rel.node)
    for j in joins:
        assert "lineitem" not in _scans(j.right), \
            "fact table landed on a build side"


def test_reorder_result_matches_from_order(session):
    """Reordering must not change results: run q5 and a 3-table variant
    and compare against forcing the greedy order via a high DP cutoff."""
    from trino_tpu.planner.planner import Planner
    rows = session.execute(Q5).rows
    old = Planner.DP_REORDER_MAX
    try:
        Planner.DP_REORDER_MAX = 0       # greedy order
        rows_greedy = session.execute(Q5).rows
    finally:
        Planner.DP_REORDER_MAX = old
    assert [(r[0], round(float(r[1]), 2)) for r in rows] == \
           [(r[0], round(float(r[1]), 2)) for r in rows_greedy]
