"""Differential tests: packed (2-operand-sort) kernels vs the general
kernels. The packed paths activate in production only above
SORT_SMALL_ROWS (cheap-compile threshold), so no end-to-end test crosses
them on CPU — these call the kernels directly on small inputs and also
force the executor dispatch through them.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import trino_tpu.exec.executor as E
from trino_tpu.batch import batch_from_numpy, batch_to_numpy
from trino_tpu.ops.aggregate import (AggSpec, key_pack_plan,
                                     packed_sort_group_aggregate,
                                     sort_group_aggregate)
from trino_tpu.ops.sort import sort_batch, sort_batch_packed, sort_pack_plan


def rows_of(batch):
    arrays, valids = batch_to_numpy(batch)
    return [tuple(a[i].item() if v[i] else None
                  for a, v in zip(arrays, valids))
            for i in range(len(arrays[0]))]


def rand_batch(n=4000, seed=0, with_nulls=True):
    rng = np.random.default_rng(seed)
    k1 = rng.integers(-50, 50, n).astype(np.int64)
    k2 = rng.integers(0, 7, n).astype(np.int64)
    v = rng.integers(-1000, 1000, n).astype(np.int64)
    valids = None
    if with_nulls:
        valids = [rng.random(n) > 0.1, rng.random(n) > 0.2,
                  rng.random(n) > 0.15]
    return batch_from_numpy([k1, k2, v], valids=valids)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_packed_agg_matches_general(seed):
    b = rand_batch(seed=seed)
    aggs = (AggSpec("sum", 2), AggSpec("count", 2), AggSpec("min", 2),
            AggSpec("max", 2), AggSpec("count_star", None))
    plan = key_pack_plan(b, (0, 1))
    assert plan is not None
    kmins, bits = plan
    got = packed_sort_group_aggregate(b, jnp.asarray(kmins), (0, 1),
                                      bits, aggs, 1024)
    want = sort_group_aggregate(b, (0, 1), aggs, 1024)
    assert sorted(rows_of(got), key=repr) == \
        sorted(rows_of(want), key=repr)


def test_packed_agg_all_null_key():
    n = 512
    b = batch_from_numpy(
        [np.zeros(n, dtype=np.int64), np.arange(n, dtype=np.int64)],
        valids=[np.zeros(n, dtype=bool), None])
    aggs = (AggSpec("sum", 1),)
    plan = key_pack_plan(b, (0,))
    kmins, bits = plan
    got = packed_sort_group_aggregate(b, jnp.asarray(kmins), (0,), bits,
                                      aggs, 64)
    want = sort_group_aggregate(b, (0,), aggs, 64)
    assert sorted(rows_of(got), key=repr) == \
        sorted(rows_of(want), key=repr)


@pytest.mark.parametrize("asc,nf", [(True, False), (True, True),
                                    (False, False), (False, True)])
def test_packed_sort_matches_general(asc, nf):
    b = rand_batch(seed=3)
    keys = ((0, asc, nf), (1, not asc, not nf))
    plan = sort_pack_plan(b, keys)
    assert plan is not None
    kmins, bits = plan
    got = sort_batch_packed(b, jnp.asarray(kmins), keys, bits, 100)
    want = sort_batch(b, keys, 100)
    assert rows_of(got) == rows_of(want)


def test_pack_plan_refuses_wide_domains():
    n = 64
    b = batch_from_numpy(
        [np.array([0, 1 << 60] * (n // 2), dtype=np.int64),
         np.array([0, 1 << 60] * (n // 2), dtype=np.int64)])
    assert key_pack_plan(b, (0, 1)) is None


def test_executor_dispatch_through_packed(monkeypatch):
    """Force the production dispatch (threshold crossed) end-to-end."""
    monkeypatch.setattr(E, "SORT_SMALL_ROWS", 16)
    from trino_tpu.exec.session import Session
    s = Session(default_schema="tiny")
    got = s.execute(
        "SELECT l_returnflag, l_linestatus, sum(l_quantity) q, count(*)"
        " FROM lineitem GROUP BY l_returnflag, l_linestatus"
        " ORDER BY q DESC, l_returnflag, l_linestatus").rows
    monkeypatch.setattr(E, "SORT_SMALL_ROWS", 1 << 40)
    s2 = Session(default_schema="tiny")
    want = s2.execute(
        "SELECT l_returnflag, l_linestatus, sum(l_quantity) q, count(*)"
        " FROM lineitem GROUP BY l_returnflag, l_linestatus"
        " ORDER BY q DESC, l_returnflag, l_linestatus").rows
    assert got == want


def test_compact_gather_matches_sort():
    b = rand_batch(seed=5)
    import jax.numpy as jnp2
    live = np.asarray(b.live).copy()
    live[::3] = False
    b = b.with_live(jnp2.asarray(live))
    cap = 2048
    got = E._compact_gather(b, cap)
    want = E._compact_sort(b, cap)
    assert rows_of(got) == rows_of(want)


def test_two_phase_dense_join_matches(monkeypatch):
    """Selective big-probe inner joins compact before build gathers;
    results must equal the single-kernel dense join."""
    monkeypatch.setattr(E, "SORT_SMALL_ROWS", 16)
    from trino_tpu.exec.session import Session
    s = Session(default_schema="tiny")
    sql = ("SELECT o_orderkey, o_totalprice, c_name"
           " FROM orders o JOIN customer c ON o.o_custkey = c.c_custkey"
           " WHERE c.c_acctbal < -900"
           " ORDER BY o_orderkey LIMIT 50")
    got = s.execute(sql).rows
    assert s.executor.stats.dynamic_filter_compactions >= 1
    monkeypatch.setattr(E, "SORT_SMALL_ROWS", 1 << 40)
    want = Session(default_schema="tiny").execute(sql).rows
    assert got == want and len(got) > 0


def test_three_column_join_keys():
    """>2-column equi-joins overflowed the fixed 32-bit key packing and
    silently collided; range-compressed packing fixes them."""
    import sqlite3
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.exec.session import Session as S
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = S(catalog=cat, default_cat="m", default_schema="s")
    s.execute("CREATE TABLE m.s.l (a bigint, b bigint, c bigint,"
              " v bigint)")
    s.execute("CREATE TABLE m.s.r (a bigint, b bigint, c bigint,"
              " w bigint)")
    rows_l, rows_r = [], []
    import random
    rnd = random.Random(11)
    for i in range(300):
        rows_l.append((rnd.randrange(5), rnd.randrange(70000),
                       rnd.randrange(1 << 33), i))
    for i in range(120):
        rows_r.append((rnd.randrange(5), rnd.randrange(70000),
                       rnd.randrange(1 << 33), i))
    rows_r += rows_l[:40]                       # guarantee matches
    s.execute("INSERT INTO m.s.l VALUES " + ",".join(
        str(r) for r in rows_l))
    s.execute("INSERT INTO m.s.r VALUES " + ",".join(
        str(r) for r in rows_r))
    got = s.execute(
        "SELECT count(*), sum(v + w) FROM l, r"
        " WHERE l.a = r.a AND l.b = r.b AND l.c = r.c").rows
    o = sqlite3.connect(":memory:")
    o.execute("CREATE TABLE l (a,b,c,v)")
    o.execute("CREATE TABLE r (a,b,c,w)")
    o.executemany("INSERT INTO l VALUES (?,?,?,?)", rows_l)
    o.executemany("INSERT INTO r VALUES (?,?,?,?)", rows_r)
    want = o.execute(
        "SELECT count(*), sum(v + w) FROM l, r"
        " WHERE l.a = r.a AND l.b = r.b AND l.c = r.c").fetchall()
    assert [tuple(x) for x in got] == want
    assert got[0][0] >= 40


def test_multiword_packing_wide_group_by():
    """q10's shape: many group keys whose combined width exceeds one
    int64 pack into MULTIPLE words sorted LSD-radix style (stable
    2-operand sorts) — results identical to the general kernel."""
    import numpy as np

    from trino_tpu.batch import batch_from_numpy
    from trino_tpu.ops.aggregate import (key_pack_plan,
                                         key_pack_plan_words,
                                         sort_group_aggregate)
    rng = np.random.default_rng(11)
    n = 20_000
    cols = [rng.integers(0, 1 << 17, n),       # 7 wide keys > 62 bits
            rng.integers(0, 1 << 17, n),
            rng.integers(0, 1 << 21, n),
            rng.integers(0, 1 << 17, n),
            rng.integers(0, 25, n),
            rng.integers(0, 1 << 17, n),
            rng.integers(0, 1 << 17, n),
            rng.integers(0, 1000, n)]          # value
    b = batch_from_numpy(cols)
    keys = tuple(range(7))
    assert key_pack_plan(b, keys) is None       # single word: too wide
    plan = key_pack_plan_words(b, keys)
    assert plan is not None
    kmins, bits, splits = plan
    assert len(splits) >= 2
    aggs = (AggSpec("sum", 7), AggSpec("count_star", None))
    got = packed_sort_group_aggregate(b, jnp.asarray(kmins), keys, bits,
                                      aggs, 1 << 15, splits)
    want = sort_group_aggregate(b, keys, aggs, 1 << 15)

    def rows(batch):
        live = np.asarray(batch.live)
        out = []
        for i in np.nonzero(live)[0]:
            out.append(tuple(int(np.asarray(c.data)[i])
                             for c in batch.columns))
        return sorted(out)
    assert rows(got) == rows(want)


def test_multiword_packing_nulls_and_dead_rows():
    import numpy as np

    from trino_tpu.batch import batch_from_numpy
    from trino_tpu.ops.aggregate import (key_pack_plan_words,
                                         sort_group_aggregate)
    rng = np.random.default_rng(3)
    n = 5000
    k1 = rng.integers(0, 1 << 40, n)
    k2 = rng.integers(0, 1 << 40, n)
    v = rng.integers(0, 100, n)
    valid1 = rng.random(n) > 0.1
    b = batch_from_numpy([k1, k2, v], valids=[valid1, None, None])
    plan = key_pack_plan_words(b, (0, 1))
    kmins, bits, splits = plan
    assert len(splits) == 2                     # 42+42 bits -> 2 words
    aggs = (AggSpec("sum", 2), AggSpec("count", 2))
    got = packed_sort_group_aggregate(b, jnp.asarray(kmins), (0, 1),
                                      bits, aggs, 8192, splits)
    want = sort_group_aggregate(b, (0, 1), aggs, 8192)
    gl, wl = int(np.asarray(got.live).sum()), \
        int(np.asarray(want.live).sum())
    assert gl == wl
    def total(batch, j):
        live = np.asarray(batch.live)
        return int(np.asarray(batch.columns[j].data)[live].sum())
    assert total(got, 2) == total(want, 2)
    assert total(got, 3) == total(want, 3)


def test_key_span_measures_combined_packed_key():
    """Multi-key packed joins window by the COMBINED key (32 bits per
    trailing column); _key_span measuring keys[0] alone underestimated
    by ~2^32, so adapted windows always escaped (ADVICE round-5)."""
    import numpy as np

    from trino_tpu.exec.chunked import _key_span
    from trino_tpu.ops.join import _combined_key

    b = batch_from_numpy([np.array([5, 5, 5, 5], dtype=np.int64),
                          np.array([1, 9, 2, 7], dtype=np.int64)])
    key, _ = _combined_key(b, (0, 1))
    k = np.asarray(key)[np.asarray(b.live)]
    assert int(_key_span(b, (0, 1))) == int(k.max() - k.min() + 1)
    # the old keys[0]-only measurement would collapse distinct combined
    # keys: a second leading-key value must widen the span past 2^32
    b3 = batch_from_numpy([np.array([5, 6], dtype=np.int64),
                           np.array([1, 1], dtype=np.int64)])
    assert int(_key_span(b3, (0, 1))) == (1 << 32) + 1
    # single-key measurement is unchanged
    assert int(_key_span(b, (1,))) == 9
    # and a NULL-masked row is excluded from the extent
    b2 = batch_from_numpy([np.array([5, 5, 5], dtype=np.int64),
                           np.array([1, 2, 1000], dtype=np.int64)],
                          valids=[None, np.array([True, True, False])])
    assert int(_key_span(b2, (0, 1))) == 2
