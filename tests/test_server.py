"""Coordinator/worker protocol tests.

Reference pattern: DistributedQueryRunner boots a coordinator + workers in
one JVM with real HTTP between them (DistributedQueryRunner.java:107,
TestingTrinoServer.java:155). Here: CoordinatorServer + WorkerServers in
one process over real sockets; queries flow through the full statement
protocol (POST /v1/statement -> nextUri paging) via the Python client.
"""

import time

import pytest

from trino_tpu.client.cli import LocalBackend, render_table
from trino_tpu.client.client import Client, QueryError
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.failuredetector import HeartbeatFailureDetector
from trino_tpu.server.worker import WorkerServer


@pytest.fixture(scope="module")
def cluster():
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    workers = [WorkerServer(f"worker-{i}", coord.uri,
                            announce_interval_s=0.2).start()
               for i in range(2)]
    detector = HeartbeatFailureDetector(coord.state,
                                        interval_s=0.2).start()
    yield coord, workers, detector
    detector.stop()
    for w in workers:
        w.stop()
    coord.stop()


@pytest.fixture(scope="module")
def client(cluster):
    coord, _, _ = cluster
    return Client(coord.uri, user="test")


def test_statement_protocol_roundtrip(client):
    r = client.execute("SELECT n_name, n_regionkey FROM nation "
                       "ORDER BY n_nationkey LIMIT 5")
    assert r.state == "FINISHED"
    assert r.columns == ["n_name", "n_regionkey"]
    assert len(r.rows) == 5
    assert r.rows[0][0] == "ALGERIA"


def test_query_with_aggregation(client):
    r = client.execute(
        "SELECT count(*), sum(o_totalprice) FROM orders")
    assert len(r.rows) == 1
    assert r.rows[0][0] == 15000


def test_paging_over_page_size(client):
    # 15000 orders rows > PAGE_ROWS=1000 -> multiple nextUri pages
    r = client.execute("SELECT o_orderkey FROM orders")
    assert len(r.rows) == 15000


def test_query_failure_propagates(client):
    with pytest.raises(QueryError) as ei:
        client.execute("SELECT no_such_column FROM nation")
    assert "no_such_column" in str(ei.value) or "no column" in str(ei.value)


def test_syntax_error_propagates(client):
    with pytest.raises(QueryError):
        client.execute("SELEC broken")


def test_query_info_and_listing(client):
    r = client.execute("SELECT 1")
    info = client.query_info(r.query_id)
    assert info["state"] == "FINISHED"
    listed = client.list_queries()
    assert any(q["queryId"] == r.query_id for q in listed)


def test_worker_announcement(cluster, client):
    deadline = time.time() + 5
    while time.time() < deadline:
        nodes = client.nodes()
        if len(nodes) == 2 and all(n["state"] == "ACTIVE" for n in nodes):
            return
        time.sleep(0.1)
    raise AssertionError(f"workers not announced: {client.nodes()}")


def test_failure_detector_marks_and_recovers(cluster, client):
    coord, workers, detector = cluster
    w = workers[0]
    # make sure the worker is registered and healthy first
    test_worker_announcement(cluster, client)
    w.fail_status = True
    deadline = time.time() + 10
    while time.time() < deadline:
        nodes = {n["nodeId"]: n["state"] for n in client.nodes()}
        if nodes.get(w.node_id) == "FAILED":
            break
        time.sleep(0.1)
    else:
        raise AssertionError("failure not detected")
    w.fail_status = False
    deadline = time.time() + 10
    while time.time() < deadline:
        nodes = {n["nodeId"]: n["state"] for n in client.nodes()}
        if nodes.get(w.node_id) == "ACTIVE":
            return
        time.sleep(0.1)
    raise AssertionError("worker did not recover")


def test_server_info(client):
    info = client.server_info()
    assert info["coordinator"] is True


def test_cli_render_and_local_backend(capsys):
    backend = LocalBackend()
    columns, rows = backend.execute(
        "SELECT n_name FROM nation ORDER BY n_nationkey LIMIT 2")
    render_table(columns, rows)
    out = capsys.readouterr().out
    assert "ALGERIA" in out and "(2 rows)" in out


def test_spooled_result_protocol(cluster):
    """Spooled protocol: big results arrive as fetch/ack segments
    (spi/spool + spooling-filesystem role)."""
    coord, _, _ = cluster
    spooled = Client(coord.uri, user="spool", spooled=True)
    r = spooled.execute("SELECT o_orderkey FROM orders")
    assert len(r.rows) == 15000
    assert coord.state.spooling.segments_written >= 3
    # acked segments are deleted from the spool directory
    import os
    assert os.listdir(coord.state.spooling.directory) == []
    # small results stay inline even for spooled clients
    r2 = spooled.execute("SELECT 1")
    assert r2.rows == [[1]] or r2.rows == [(1,)]
