"""Exactly-once distributed writes (server/writeprotocol.py + the
scheduler's write path).

Round-18 acceptance surface: staged task outputs are invisible until the
coordinator's commit; the CRC-framed fsync'd journal replays idempotently
from every byte prefix (torn tail included); duplicate attempts from
forced hedging dedup first-success-wins; a crash injected at each write
chaos point (WRITE_STAGE / WRITE_COMMIT / WRITE_PUBLISH) recovers to the
sqlite-oracle row set with zero lost rows, zero duplicates, zero orphans;
CTAS -> query round-trips bit-exact with zone-map pruning live and the
result cache invalidated by the commit's catalog-version bump.
"""

import os
import shutil
import time

import numpy as np
import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.batch import Field, Schema
from trino_tpu.client.client import Client
from trino_tpu.connectors.orcdir import OrcConnector, export_table, load_orc
from trino_tpu.connectors.tpch.datagen import TableData
from trino_tpu.exec.session import Session
from trino_tpu.metrics import RESULT_CACHE_INVALIDATIONS
from trino_tpu.server import writeprotocol as wp
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.failureinjector import (CORRUPT, CRASH, RAISE,
                                              WRITE_COMMIT, WRITE_POINTS,
                                              FailureInjector)
from trino_tpu.server.worker import WorkerServer
from trino_tpu.types import BIGINT
from trino_tpu.utils.atomicio import atomic_write_bytes


def _ints(name, n, seed=0):
    rng = np.random.default_rng(seed)
    return TableData(name, Schema((Field("a", BIGINT), Field("b", BIGINT))),
                     [np.arange(n, dtype=np.int64),
                      rng.integers(0, 100, n).astype(np.int64)])


# ---------------------------------------------------------------------------
# satellite: torn-file exposure in the file writers
# ---------------------------------------------------------------------------

def test_atomic_write_crash_leaves_no_partial(tmp_path, monkeypatch):
    target = str(tmp_path / "t.orc")
    atomic_write_bytes(target, b"v1")
    import trino_tpu.utils.atomicio as aio

    def boom(src, dst):
        raise OSError("injected crash before rename")
    monkeypatch.setattr(aio.os, "replace", boom)
    with pytest.raises(OSError):
        atomic_write_bytes(target, b"v2-partial")
    monkeypatch.undo()
    # old content intact, no temp stray a directory scan could surface
    with open(target, "rb") as f:
        assert f.read() == b"v1"
    assert os.listdir(tmp_path) == ["t.orc"]


def test_write_orc_leaves_no_temp_files(tmp_path):
    path = str(tmp_path / "x.orc")
    export_table(_ints("x", 100), path)
    assert sorted(os.listdir(tmp_path)) == ["x.orc"]
    assert load_orc(path, "x").num_rows == 100


# ---------------------------------------------------------------------------
# satellite: directory scans skip write-protocol artifacts
# ---------------------------------------------------------------------------

def test_scan_skips_staging_and_journal_artifacts(tmp_path):
    conn = OrcConnector(str(tmp_path))
    conn.create_table("s1", "t", _ints("t", 50))
    # plant every artifact class a crashed write could leave behind
    td = tmp_path / "s1" / "t"
    os.makedirs(td / ".staging", exist_ok=True)
    (td / ".staging" / "deadbeef_1_0_t9.orc").write_bytes(b"orphan")
    (td / ".commit_deadbeef.journal").write_bytes(b"torn")
    (td / ".tmp.123.part").write_bytes(b"half")
    (tmp_path / "s1" / ".hidden").mkdir()
    (tmp_path / "s1" / "x.journal").write_bytes(b"junk")
    assert conn.table_names("s1") == ["t"]
    assert conn._load_table("s1", "t").num_rows == 50
    # startup sweep removes the orphans without touching the table
    conn2 = OrcConnector(str(tmp_path))
    assert not (td / ".staging").exists()
    assert not (td / ".commit_deadbeef.journal").exists()
    assert not (td / ".tmp.123.part").exists()
    assert conn2._load_table("s1", "t").num_rows == 50


# ---------------------------------------------------------------------------
# journal replay: every byte prefix is idempotent
# ---------------------------------------------------------------------------

def _journal_bytes(table_dir, manifests):
    """The exact intent+commit frames wp.commit would journal, with the
    file paths rebased into `table_dir`."""
    tok = wp.qtoken("q1")
    files = [{"src": os.path.join(table_dir, wp.STAGING_DIR,
                                  os.path.basename(m["path"])),
              "dst": os.path.join(table_dir, wp.part_filename(
                  i, tok, m["rows"], "orc")),
              "rows": m["rows"], "crc": m["crc"]}
             for i, m in enumerate(manifests)]
    return (wp._frame({"rec": "intent", "query": "q1", "files": files})
            + wp._frame({"rec": "commit", "query": "q1"}))


def test_journal_prefix_replay_idempotent(tmp_path):
    import struct
    tmpl = str(tmp_path / "tmpl")
    manifests = (wp.stage_table_data(tmpl, _ints("t", 10, seed=1),
                                     "q1", 1, 0, "t1", "orc"),
                 wp.stage_table_data(tmpl, _ints("t", 20, seed=2),
                                     "q1", 1, 1, "t2", "orc"))
    # fixed-width work-dir names => identical journal length for every
    # cut, so one intent_end offset applies to all of them
    probe = _journal_bytes(str(tmp_path / "w0000"), manifests)
    intent_end = 12 + struct.unpack_from("<I", probe, 8)[0]
    jname = ".commit_%s.journal" % wp.qtoken("q1")
    for cut in range(len(probe) + 1):
        work = str(tmp_path / f"w{cut:04d}")
        shutil.copytree(tmpl, work)
        journal = _journal_bytes(work, manifests)
        assert len(journal) == len(probe)
        with open(os.path.join(work, jname), "wb") as f:
            f.write(journal[:cut])
        wp.recover_table_dir(work)
        parts = wp.list_parts(work)
        if cut >= intent_end:
            # durable intent: rolled forward, both parts, exact rows
            assert wp.published_rows_for(work, "q1") == 30, (cut, parts)
            assert len(parts) == 2
        else:
            # torn/absent intent: rolled back, nothing published
            assert parts == [], (cut, parts)
        # no staging, no journal, no temp strays — ever
        assert not os.path.isdir(wp.staging_dir(work))
        assert [f for f in os.listdir(work)
                if f.endswith(".journal") or f.startswith(".tmp.")] == []
        before = sorted(os.listdir(work))
        wp.recover_table_dir(work)           # replay is idempotent
        assert sorted(os.listdir(work)) == before


def test_commit_is_idempotent_per_query(tmp_path):
    td = str(tmp_path / "t")
    m = wp.stage_table_data(td, _ints("t", 25), "q7", 1, 0, "t1", "orc")
    s1 = wp.commit(td, "q7", [m])
    assert s1["rows"] == 25 and s1["published"] == 1
    # whole-query retry: the same query id commits again -> recognized
    # by the part-name token, not re-published
    s2 = wp.commit(td, "q7", [])
    assert s2["rows"] == 25 and s2["published"] == 0
    assert len(wp.list_parts(td)) == 1


def test_duplicate_attempt_dedup_first_success_wins(tmp_path):
    td = str(tmp_path / "t")
    m_win = wp.stage_table_data(td, _ints("t", 40, seed=3), "q9", 1, 0,
                                "t1", "orc")
    m_dup = wp.stage_table_data(td, _ints("t", 40, seed=3), "q9", 1, 0,
                                "t2", "orc")
    stats = wp.commit(td, "q9", [m_win, m_dup])
    assert stats["deduped"] == 1 and stats["published"] == 1
    assert stats["rows"] == 40
    assert len(wp.list_parts(td)) == 1
    assert not os.path.isdir(wp.staging_dir(td))   # loser swept too


def test_abort_sweeps_staging_clean(tmp_path):
    td = str(tmp_path / "t")
    wp.stage_table_data(td, _ints("t", 15), "q5", 1, 0, "t1", "orc")
    wp.stage_table_data(td, _ints("t", 15), "q5", 1, 1, "t2", "orc")
    wp.abort(td, "q5")
    assert wp.list_parts(td) == []
    assert not os.path.isdir(wp.staging_dir(td))
    assert wp.published_rows_for(td, "q5") is None


# ---------------------------------------------------------------------------
# cluster: distributed writes under chaos, vs the sqlite oracle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def wcluster(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("orcw"))
    os.makedirs(os.path.join(root, "out"))
    session = Session(default_schema="tiny")
    conn = OrcConnector(root)
    session.catalog.register("orc", conn)
    coord = CoordinatorServer(session, retry_policy="QUERY").start()
    sched = coord.state.scheduler
    sched.split_rows = 4096
    workers = [WorkerServer(f"w-{i}", coord.uri, announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)
    orders = session.catalog.connector("tpch").get_table("tiny", "orders")
    oracle = load_oracle([orders])
    yield coord, workers, session, conn, sched, oracle
    for w in workers:
        w.stop()
    coord.stop()


@pytest.fixture(autouse=True)
def _wclean(request):
    if "wcluster" not in request.fixturenames:
        yield
        return
    coord, workers, _, _, sched, _ = request.getfixturevalue("wcluster")
    sched.spool.clear()
    yield
    sched.failure_injector = None
    sched.force_write_hedge = False
    for w in workers:
        w.task_manager.injector = None
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)


_SRC = ("SELECT o_orderkey, o_custkey, o_orderstatus, o_totalprice "
        "FROM tpch.tiny.orders")


def _assert_table_matches_oracle(session, oracle, table, times=1):
    got = session.execute(
        f"SELECT o_orderkey, o_custkey, o_orderstatus, o_totalprice "
        f"FROM {table} ORDER BY o_orderkey").rows
    want = oracle_query(
        oracle, "SELECT o_orderkey, o_custkey, o_orderstatus, "
                "o_totalprice FROM orders ORDER BY o_orderkey") * times
    want.sort(key=lambda r: r[0])
    assert_rows_match(got, want)


def test_distributed_ctas_roundtrip_bit_exact(wcluster):
    _, _, session, conn, sched, oracle = wcluster
    res = sched.execute(f"CREATE TABLE orc.out.rt AS {_SRC}",
                        query_id="q_rt")
    assert res is not None, sched.fallback_reason
    assert res.rows == [(15000,)]
    wr = sched.last_query["write"]
    assert wr["phase"] == "committed" and wr["rows"] == 15000
    assert wr["partitions"] == 3 and wr["deduped"] == 0
    _assert_table_matches_oracle(session, oracle, "orc.out.rt")
    # zone-map pruning is live on the published parts: an impossible
    # range prunes every stripe, a real predicate stays oracle-exact
    pruned = conn.get_table_pruned("out", "rt", {"o_orderkey": (-10, -1)})
    assert pruned.total_stripes > 0
    assert pruned.skipped_stripes == pruned.total_stripes
    got = session.execute(
        "SELECT COUNT(*), SUM(o_totalprice) FROM orc.out.rt "
        "WHERE o_orderkey <= 1000").rows
    want = oracle_query(oracle, "SELECT COUNT(*), SUM(o_totalprice) "
                                "FROM orders WHERE o_orderkey <= 1000")
    assert_rows_match(got, want)


def test_write_retry_same_query_id_is_exactly_once(wcluster):
    _, _, session, _, sched, _ = wcluster
    r1 = sched.execute(f"CREATE TABLE orc.out.once AS {_SRC}",
                       query_id="q_once")
    assert r1 is not None, sched.fallback_reason
    r2 = sched.execute(f"CREATE TABLE orc.out.once AS {_SRC}",
                       query_id="q_once")    # whole-query retry
    assert r1.rows == r2.rows == [(15000,)]
    assert session.execute(
        "SELECT COUNT(*) FROM orc.out.once").rows == [(15000,)]


def test_forced_hedge_duplicates_dedup(wcluster):
    _, _, session, conn, sched, oracle = wcluster
    sched.force_write_hedge = True
    res = sched.execute(f"CREATE TABLE orc.out.hedge AS {_SRC}",
                        query_id="q_hedge")
    assert res is not None, sched.fallback_reason
    assert res.rows == [(15000,)]
    wr = sched.last_query["write"]
    assert wr["deduped"] >= 1, wr       # both attempts staged, one wins
    _assert_table_matches_oracle(session, oracle, "orc.out.hedge")
    td = conn._table_dir("out", "hedge")
    assert not os.path.isdir(wp.staging_dir(td))


@pytest.mark.parametrize("fault", [RAISE, CRASH])
@pytest.mark.parametrize("point", WRITE_POINTS)
def test_crash_at_each_write_point_recovers_exactly_once(
        wcluster, point, fault):
    _, workers, session, conn, sched, oracle = wcluster
    tbl = f"c_{point.lower()}_{fault.lower()}"
    qid = f"q_{tbl}"
    inj = FailureInjector()
    inj.inject(point, times=1, fault=fault)
    sched.failure_injector = inj
    for w in workers:
        w.task_manager.injector = inj
    sql = f"CREATE TABLE orc.out.{tbl} AS {_SRC}"
    try:
        res = sched.execute(sql, query_id=qid)
    except Exception:
        # pre-intent failure aborted the query: the QUERY retry policy
        # reruns it under the same id — the rerun must be exactly-once
        res = sched.execute(sql, query_id=qid)
    assert res is not None, sched.fallback_reason
    assert res.rows == [(15000,)]
    assert inj.injected_count == 1, (point, fault)
    # oracle row-set equality: zero lost, zero duplicate rows
    _assert_table_matches_oracle(session, oracle, f"orc.out.{tbl}")
    td = conn._table_dir("out", tbl)
    assert not os.path.isdir(wp.staging_dir(td))       # zero orphans
    assert [f for f in os.listdir(td) if f.endswith(".journal")] == []


def test_torn_intent_journal_rolls_back_then_recovers(wcluster):
    """CORRUPT at WRITE_COMMIT models a torn intent append: half the
    frame hits disk, then the coordinator dies. Replay must treat the
    torn record as absent (roll back), and the rerun commits cleanly."""
    _, _, session, conn, sched, oracle = wcluster
    inj = FailureInjector()
    inj.inject(WRITE_COMMIT, times=1, fault=CORRUPT)
    sched.failure_injector = inj
    sql = f"CREATE TABLE orc.out.torn AS {_SRC}"
    with pytest.raises(Exception):
        sched.execute(sql, query_id="q_torn")
    assert inj.injected_count == 1
    sched.failure_injector = None
    res = sched.execute(sql, query_id="q_torn")
    assert res is not None, sched.fallback_reason
    assert res.rows == [(15000,)]
    _assert_table_matches_oracle(session, oracle, "orc.out.torn")
    td = conn._table_dir("out", "torn")
    assert [f for f in os.listdir(td) if f.endswith(".journal")] == []


def test_distributed_insert_appends_exactly_once(wcluster):
    _, _, session, _, sched, oracle = wcluster
    r0 = sched.execute(f"CREATE TABLE orc.out.app AS {_SRC}",
                       query_id="q_a1")
    assert r0 is not None, sched.fallback_reason
    res = sched.execute(f"INSERT INTO orc.out.app {_SRC}",
                        query_id="q_a2")
    assert res is not None, sched.fallback_reason
    assert res.rows == [(15000,)]
    # the same INSERT retried under its query id must not double-append
    res2 = sched.execute(f"INSERT INTO orc.out.app {_SRC}",
                         query_id="q_a2")
    assert res2.rows == [(15000,)]
    _assert_table_matches_oracle(session, oracle, "orc.out.app", times=2)


def test_commit_invalidates_result_cache(wcluster):
    coord, _, session, _, sched, _ = wcluster
    client = Client(coord.uri, user="fte", poll_interval_s=0.005)
    client.execute("CREATE TABLE memory.s.wrc (k bigint)")
    client.execute("INSERT INTO memory.s.wrc VALUES (1), (2)")
    client.execute("SET SESSION enable_result_cache = true")
    sql = "SELECT count(*) FROM memory.s.wrc"
    assert client.execute(sql).rows == [[2]]
    assert client.execute(sql).rows == [[2]]       # cached page
    v0 = session.catalog.version
    i0 = RESULT_CACHE_INVALIDATIONS.value()
    res = sched.execute(
        "CREATE TABLE orc.out.vbump AS SELECT o_orderkey "
        "FROM tpch.tiny.orders", query_id="q_vb")
    assert res is not None, sched.fallback_reason
    assert session.catalog.version > v0
    # the stale page is version-mismatched now: dropped and re-executed
    assert client.execute(sql).rows == [[2]]
    assert RESULT_CACHE_INVALIDATIONS.value() > i0


def test_query_info_reports_write_stats(wcluster):
    coord, _, _, _, _, _ = wcluster
    client = Client(coord.uri, user="fte", poll_interval_s=0.005)
    r = client.execute("CREATE TABLE orc.out.qinfo AS SELECT o_orderkey, "
                       "o_custkey FROM tpch.tiny.orders")
    info = client.query_info(r.query_id)
    assert info["writtenRows"] == 15000
    assert info["writtenBytes"] > 0
    assert info["commitPhase"] == "committed"


def test_explain_analyze_write_renders_commit_plan(wcluster):
    _, _, _, _, sched, _ = wcluster
    res = sched.execute(
        "EXPLAIN ANALYZE CREATE TABLE orc.out.exp AS SELECT o_orderkey, "
        "o_custkey FROM tpch.tiny.orders", query_id="q_exp")
    assert res is not None, sched.fallback_reason
    text = "\n".join(r[0] for r in res.rows)
    assert "TableCommit[orc.out.exp]" in text
    assert "TableWriter[orc.out.exp]" in text
    assert "write: " in text and "staged" in text and "deduped" in text
