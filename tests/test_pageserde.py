"""Binary page-frame serde tests (the PagesSerde analog —
execution/buffer/CompressingEncryptingPageSerializer.java:60)."""

import numpy as np
import pytest

from trino_tpu.server.pageserde import MAGIC, decode_page, encode_page


def roundtrip(arrays, valids):
    frame = encode_page(arrays, valids)
    assert frame[:4] == MAGIC
    out_a, out_v = decode_page(frame)
    assert len(out_a) == len(arrays)
    for a, b in zip(arrays, out_a):
        np.testing.assert_array_equal(np.asarray(a), b)
        assert np.asarray(a).dtype == b.dtype
    for v, w in zip(valids, out_v):
        np.testing.assert_array_equal(
            np.asarray(v, dtype=np.bool_), w)
    return frame


def test_roundtrip_mixed_dtypes():
    rng = np.random.default_rng(0)
    n = 10_000
    arrays = [rng.integers(-(1 << 40), 1 << 40, n),
              rng.integers(0, 100, n).astype(np.int32),
              rng.random(n),
              rng.integers(0, 2, n).astype(np.bool_)]
    valids = [np.ones(n, np.bool_), rng.random(n) < 0.9,
              np.zeros(n, np.bool_), np.ones(n, np.bool_)]
    roundtrip(arrays, valids)


def test_compression_engages_on_compressible_data():
    n = 200_000
    arrays = [np.zeros(n, np.int64), np.arange(n, dtype=np.int64)]
    valids = [np.ones(n, np.bool_)] * 2
    frame = roundtrip(arrays, valids)
    # 3.2 MB raw; sorted/constant data must compress well below half
    assert len(frame) < n * 16 // 2, len(frame)


def test_empty_page():
    roundtrip([np.empty(0, np.int64)], [np.empty(0, np.bool_)])


def test_bad_magic_rejected():
    with pytest.raises(ValueError):
        decode_page(b"XXXX" + b"\x00" * 16)


def test_legacy_json_page_still_decodes():
    """Rolling upgrade: decode_columns accepts the round-3 base64 dict."""
    import base64

    from trino_tpu.server.tasks import decode_columns
    a = np.arange(5, dtype=np.int64)
    v = np.ones(5, np.bool_)
    legacy = {"rows": 5, "columns": [{
        "dtype": "int64",
        "data": base64.b64encode(a.tobytes()).decode(),
        "valid": base64.b64encode(v.tobytes()).decode()}]}
    arrs, vals = decode_columns(legacy)
    np.testing.assert_array_equal(arrs[0], a)
    np.testing.assert_array_equal(vals[0], v)


def test_concurrent_encode_decode_threads():
    """zstd contexts are per-thread (sharing one corrupts frames under
    the partitioned exchange's concurrent pulls — observed live)."""
    import threading

    import numpy as np

    from trino_tpu.server.pageserde import decode_page, encode_page
    rng = np.random.default_rng(7)
    cols = [rng.integers(0, 1 << 40, 50_000) for _ in range(4)]
    vals = [np.ones(50_000, dtype=bool) for _ in range(4)]
    errors = []

    def worker():
        try:
            for _ in range(30):
                frame = encode_page(cols, vals)
                arrs, _ = decode_page(frame)
                assert np.array_equal(arrs[0], cols[0])
        except Exception as e:            # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
