"""RIGHT / FULL OUTER JOIN tests against the sqlite oracle (3.39+
implements both natively)."""

import pytest

from oracle import assert_rows_match, load_oracle, oracle_query
from trino_tpu.exec.session import Session

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, sql, abs_tol=0.01):
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol)


def test_left_join_unmatched_nulls(session, oracle):
    # customers without orders appear with NULLs (1/3 of customers)
    check(session, oracle, """
        SELECT c_custkey, o_orderkey
        FROM customer LEFT JOIN orders ON c_custkey = o_custkey
        WHERE c_custkey <= 30
        ORDER BY c_custkey, o_orderkey NULLS FIRST""")


def test_right_join(session, oracle):
    check(session, oracle, """
        SELECT o_orderkey, c_custkey, c_name
        FROM orders RIGHT JOIN customer ON o_custkey = c_custkey
        WHERE c_custkey <= 30
        ORDER BY c_custkey, o_orderkey NULLS FIRST""")


def test_full_join(session, oracle):
    # orders per region-5 customer vs all: FULL keeps both unmatched sides
    check(session, oracle, """
        SELECT a.k, b.k FROM
          (SELECT n_nationkey k FROM nation WHERE n_regionkey <> 0) a
          FULL JOIN
          (SELECT n_nationkey + 3 k FROM nation WHERE n_regionkey <> 1) b
          ON a.k = b.k
        ORDER BY a.k NULLS FIRST, b.k NULLS FIRST""")


def test_full_join_aggregate(session, oracle):
    check(session, oracle, """
        SELECT count(*), count(c_custkey), count(o_orderkey)
        FROM customer FULL JOIN orders ON c_custkey = o_custkey""")
