"""Wide decimal sums (two-limb int64 accumulation).

Reference: sum(decimal(p,s)) -> decimal(38,s) with Int128 state
(core/trino-spi/.../type/Int128.java, DecimalAggregation). Here the
planner splits unscaled values into (hi = x >> 32, lo = x & 0xffffffff)
limbs summed as plain int64 states and recombined post-aggregation —
exact while |total| < 2^63, mergeable in chunked/distributed execution
because the states are ordinary sums.
"""

from decimal import Decimal

import numpy as np

from trino_tpu.catalog import Catalog
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.exec.session import Session


def _mem_session():
    from trino_tpu.connectors.tpch.connector import TpchConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    cat.register("tpch", TpchConnector())
    return Session(catalog=cat, default_cat="m", default_schema="s")


def test_sum_result_type_is_decimal38():
    s = Session(default_schema="tiny")
    rel = s.planner().plan_query(
        __import__("trino_tpu.sql.parser", fromlist=["parse"]).parse(
            "SELECT sum(l_extendedprice) FROM lineitem"))
    t = rel.scope.columns[0].dtype
    assert t.precision == 38 and t.scale == 2


def test_sum_beyond_double_mantissa_is_exact():
    """Totals past 2^53 lose cents in a float64 accumulator; the limb
    path must keep them exact."""
    s = _mem_session()
    s.execute("CREATE TABLE m.s.t (v decimal(18,2))")
    # 1M rows of 9_000_000_000.01 -> unscaled total 9.0e17: past the
    # float64 mantissa (2^53 ~ 9.0e15) yet inside the two-limb
    # exactness ceiling (2^63 ~ 9.2e18)
    big = Decimal("9000000000.01")
    n = 1_000_000
    s.execute(f"INSERT INTO m.s.t SELECT CAST({big} AS "
              f"decimal(18,2)) FROM tpch.sf1.orders LIMIT {n}")
    got = s.execute("SELECT sum(v), count(*) FROM m.s.t").rows[0]
    assert got[1] == n
    assert got[0] == big * n              # exact to the cent
    # a float64 accumulator over the unscaled cents could not hold this
    unscaled_total = int(big.scaleb(2)) * n
    assert int(float(unscaled_total)) != unscaled_total


def test_grouped_and_chunked_sums_match():
    s = Session(default_schema="tiny")
    q = ("SELECT l_returnflag, sum(l_extendedprice), "
         "sum(l_extendedprice * (1 - l_discount)) "
         "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag")
    want = s.execute(q).rows
    s2 = Session(default_schema="tiny")
    s2.properties["spill_chunk_rows"] = 8192
    s2.executor.spill_chunk_rows = 8192
    got = s2.execute(q).rows
    assert s2.executor.stats.agg_spill_chunks > 1
    assert got == want


def test_all_null_and_empty_groups():
    s = _mem_session()
    s.execute("CREATE TABLE m.s.e (g bigint, v decimal(10,2))")
    s.execute("INSERT INTO m.s.e VALUES (1, NULL), (1, NULL), "
              "(2, 5.25)")
    rows = s.execute("SELECT g, sum(v) FROM m.s.e GROUP BY g "
                     "ORDER BY g").rows
    assert rows == [(1, None), (2, Decimal("5.25"))]
    rows = s.execute(
        "SELECT sum(v) FROM m.s.e WHERE g = 99").rows
    assert rows == [(None,)]


def test_having_and_order_by_on_wide_sum():
    s = Session(default_schema="tiny")
    rows = s.execute("""
        SELECT l_returnflag, sum(l_extendedprice) AS t FROM lineitem
        GROUP BY l_returnflag HAVING sum(l_extendedprice) > 0
        ORDER BY t DESC""").rows
    assert len(rows) == 3
    assert rows[0][1] >= rows[1][1] >= rows[2][1]
