"""Observability-surface lints (tier-1 CI guards).

Two invariants the metrics/tracing layer depends on, enforced as tests so
they hold as the server grows:

1. Every `/v1/...` HTTP route must flow through the declarative ROUTES
   table (server/routes.py) — that is what guarantees each route has a
   pre-initialized `trino_tpu_http_requests_total{server,route}` counter.
   A handler with inline path literals would dodge the metrics surface,
   so the do_* dispatch methods are checked to be table-driven only.

2. Every pytest marker used under tests/ must be declared in pytest.ini
   (an undeclared marker silently deselects nothing and rots).
"""

import configparser
import inspect
import os
import re

from trino_tpu.metrics import HTTP_REQUESTS
from trino_tpu.server import coordinator, worker
from trino_tpu.server.routes import route_label

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

SERVERS = (
    (coordinator, coordinator._Handler),
    (worker, worker._WorkerHandler),
)


def test_every_route_has_a_preinitialized_counter():
    """A cold server's /v1/metrics must already list every route at 0 —
    new routes added to ROUTES get this for free via register_routes."""
    for module, _handler in SERVERS:
        for method, pattern, *_ in module.ROUTES:
            label = route_label(method, pattern)
            assert HTTP_REQUESTS.has_sample(
                server=module.SERVER_NAME, route=label), \
                f"{module.__name__}: route {label} has no counter sample"


def test_route_handlers_exist_and_are_complete():
    for module, handler in SERVERS:
        for method, pattern, fn_name, _auth in module.ROUTES:
            assert callable(getattr(handler, fn_name, None)), \
                f"{module.__name__}: ROUTES references missing " \
                f"{fn_name}"
            assert method in ("GET", "POST", "DELETE", "PUT")


def test_no_inline_route_dispatch_outside_the_table():
    """do_GET/do_POST/... must stay pure table dispatchers: any inline
    '/v1' literal or parts[...] comparison in them means a route was
    added OUTSIDE the ROUTES table — invisible to the request counters.
    That is exactly the regression this lint exists to catch."""
    for module, handler in SERVERS:
        for do in ("do_GET", "do_POST", "do_DELETE", "do_PUT"):
            fn = getattr(handler, do, None)
            if fn is None:
                continue
            src = inspect.getsource(fn)
            assert "/v1" not in src, \
                f"{module.__name__}.{do} hardcodes a /v1 path — " \
                f"add the route to ROUTES instead"
            assert "parts[" not in src, \
                f"{module.__name__}.{do} matches path segments " \
                f"inline — add the route to ROUTES instead"


# acceptance-scraped metric families that MUST render on a cold server
# (pre-initialized at import — a missing sample reads as "metric never
# existed" to a scraper, round-9 memory surface included)
REQUIRED_FAMILIES = (
    "trino_tpu_memory_reserved_bytes",
    "trino_tpu_memory_revocable_bytes",
    "trino_tpu_memory_revocations_total",
    "trino_tpu_memory_accounting_errors_total",
    "trino_tpu_spill_bytes_total",
    "trino_tpu_spill_partitions_total",
    "trino_tpu_spill_retries_total",
    "trino_tpu_queries_killed_oom_total",
    "trino_tpu_exchange_backpressure_waits_total",
    "trino_tpu_pageserde_crc_failures_total",
    "trino_tpu_sched_task_retries_total",
    # round-10 performance-introspection surface: JIT-compile
    # observability, fenced device-time attribution, query history +
    # latency-regression detection
    "trino_tpu_jit_compiles_total",
    "trino_tpu_jit_cache_hits_total",
    "trino_tpu_jit_compile_seconds",
    "trino_tpu_operator_device_ms_total",
    "trino_tpu_operator_compile_ms_total",
    "trino_tpu_query_latency_regressions_total",
    "trino_tpu_query_history_records_total",
    # round-11 high-concurrency serving surface: plan/result caches,
    # cost-based CPU/TPU co-routing, micro-batched point dispatch
    "trino_tpu_plan_cache_hits_total",
    "trino_tpu_plan_cache_misses_total",
    "trino_tpu_plan_cache_evictions_total",
    "trino_tpu_result_cache_hits_total",
    "trino_tpu_result_cache_misses_total",
    "trino_tpu_result_cache_invalidations_total",
    "trino_tpu_router_decisions_total",
    "trino_tpu_microbatch_queries_total",
    "trino_tpu_microbatch_batches_total",
    # round-12 TPU-native hash aggregation / hybrid hash join surface:
    # the per-operator strategy gate's decision counters
    "trino_tpu_agg_strategy_decisions_total",
    "trino_tpu_join_strategy_decisions_total",
    # round-13 mesh-partitioned join surface: distribution decisions,
    # batched dynamic-filter pruning, all_to_all exchange accounting
    "trino_tpu_join_distribution_decisions_total",
    "trino_tpu_dynamic_filter_rows_pruned_total",
    "trino_tpu_mesh_repartition_bytes_total",
    # round-14 scan-path surface: zone-map pruning + the chunked-driver
    # prefetch pipeline
    "trino_tpu_scan_splits_pruned_total",
    "trino_tpu_scan_zones_pruned_total",
    "trino_tpu_scan_prefetch_buffers_in_use",
    "trino_tpu_scan_prefetch_stall_seconds",
    # round-15 elastic-membership / tenancy surface: lifecycle
    # transitions, drain handoffs, per-tenant accounting, soak SLOs
    "trino_tpu_node_lifecycle_transitions_total",
    "trino_tpu_splits_migrated_total",
    "trino_tpu_tenant_queries_total",
    "trino_tpu_soak_slo_violations_total",
    # round-16 cold-start surface: AOT prewarm accounting + the
    # shape-canonicalization distinct-shape gauge
    "trino_tpu_prewarm_compiles_total",
    "trino_tpu_prewarm_hits_total",
    "trino_tpu_compile_seconds_saved_total",
    "trino_tpu_jit_distinct_shapes",
    # round-17 fused multiway star join: kernel launches + per-reason
    # dim degrades back to the pairwise ladder
    "trino_tpu_multijoin_fused_probes_total",
    "trino_tpu_multijoin_degrades_total",
    # round-18 exactly-once distributed writes: staged attempts, commit
    # outcomes, first-success-wins dedup, orphan sweeps
    "trino_tpu_write_tasks_total",
    "trino_tpu_write_attempts_deduped_total",
    "trino_tpu_write_commits_total",
    "trino_tpu_write_orphans_swept_total",
    # round-19 timeline + flight recorder: critical-path attribution and
    # the bounded telemetry ring's sample/eviction accounting
    "trino_tpu_timeline_queries_total",
    "trino_tpu_critical_path_seconds",
    "trino_tpu_telemetry_samples_total",
    "trino_tpu_telemetry_ring_evictions_total",
    # round-20 coordinator crash recovery: durable query ledger,
    # warm-standby promotion, resumption accounting
    "trino_tpu_coordinator_failovers_total",
    "trino_tpu_ledger_records_total",
    "trino_tpu_ledger_bytes",
    "trino_tpu_queries_resumed_total",
    # round-21 live query observability: heartbeat-streamed task stats,
    # stuck-query diagnosis, per-node host/device utilization
    "trino_tpu_task_heartbeats_total",
    "trino_tpu_live_stats_bytes_total",
    "trino_tpu_stuck_queries_diagnosed_total",
    "trino_tpu_node_busy_fraction",
    "trino_tpu_node_busy_ms_total",
    # round-22 query-lifetime enforcement: deadlines, cancellation
    # fan-out, orphan reaping, overload admission control
    "trino_tpu_queries_deadline_exceeded_total",
    "trino_tpu_queries_rejected_total",
    "trino_tpu_tasks_abandoned_total",
    "trino_tpu_cancel_propagations_total",
    "trino_tpu_retry_budget_exhausted_total",
    "trino_tpu_microbatch_follower_timeouts_total",
    "trino_tpu_backpressure_deadline_degrades_total",
)


def test_required_families_render_preinitialized():
    from trino_tpu.metrics import REGISTRY
    text = REGISTRY.render()
    for family in REQUIRED_FAMILIES:
        assert f"# TYPE {family} " in text, \
            f"{family} missing from a cold registry render"
        # at least one sample line (pre-initialized, not just declared)
        assert any(line.startswith(family) and " " in line
                   for line in text.splitlines()
                   if not line.startswith("#")), \
            f"{family} declared but renders no sample"


def test_markers_used_are_declared_in_pytest_ini():
    ini = configparser.ConfigParser()
    ini.read(os.path.join(REPO_ROOT, "pytest.ini"))
    declared = {line.strip().split(":")[0]
                for line in ini["pytest"]["markers"].splitlines()
                if line.strip()}
    builtin = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
               "filterwarnings"}
    used = set()
    pat = re.compile(r"pytest\.mark\.([a-zA-Z_][a-zA-Z0-9_]*)")
    for fname in os.listdir(TESTS_DIR):
        if not fname.endswith(".py"):
            continue
        with open(os.path.join(TESTS_DIR, fname)) as f:
            used.update(pat.findall(f.read()))
    undeclared = used - declared - builtin
    assert not undeclared, \
        f"markers used but not declared in pytest.ini: {undeclared}"
