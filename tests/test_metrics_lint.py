"""Observability-surface lints (tier-1 CI guards).

Two invariants the metrics/tracing layer depends on, enforced as tests so
they hold as the server grows:

1. Every `/v1/...` HTTP route must flow through the declarative ROUTES
   table (server/routes.py) — that is what guarantees each route has a
   pre-initialized `trino_tpu_http_requests_total{server,route}` counter.
   A handler with inline path literals would dodge the metrics surface,
   so the do_* dispatch methods are checked to be table-driven only.

2. Every pytest marker used under tests/ must be declared in pytest.ini
   (an undeclared marker silently deselects nothing and rots).
"""

import configparser
import inspect
import os
import re

from trino_tpu.metrics import HTTP_REQUESTS
from trino_tpu.server import coordinator, worker
from trino_tpu.server.routes import route_label

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

SERVERS = (
    (coordinator, coordinator._Handler),
    (worker, worker._WorkerHandler),
)


def test_every_route_has_a_preinitialized_counter():
    """A cold server's /v1/metrics must already list every route at 0 —
    new routes added to ROUTES get this for free via register_routes."""
    for module, _handler in SERVERS:
        for method, pattern, *_ in module.ROUTES:
            label = route_label(method, pattern)
            assert HTTP_REQUESTS.has_sample(
                server=module.SERVER_NAME, route=label), \
                f"{module.__name__}: route {label} has no counter sample"


def test_route_handlers_exist_and_are_complete():
    for module, handler in SERVERS:
        for method, pattern, fn_name, _auth in module.ROUTES:
            assert callable(getattr(handler, fn_name, None)), \
                f"{module.__name__}: ROUTES references missing " \
                f"{fn_name}"
            assert method in ("GET", "POST", "DELETE", "PUT")


def test_no_inline_route_dispatch_outside_the_table():
    """do_GET/do_POST/... must stay pure table dispatchers: any inline
    '/v1' literal or parts[...] comparison in them means a route was
    added OUTSIDE the ROUTES table — invisible to the request counters.
    That is exactly the regression this lint exists to catch."""
    for module, handler in SERVERS:
        for do in ("do_GET", "do_POST", "do_DELETE", "do_PUT"):
            fn = getattr(handler, do, None)
            if fn is None:
                continue
            src = inspect.getsource(fn)
            assert "/v1" not in src, \
                f"{module.__name__}.{do} hardcodes a /v1 path — " \
                f"add the route to ROUTES instead"
            assert "parts[" not in src, \
                f"{module.__name__}.{do} matches path segments " \
                f"inline — add the route to ROUTES instead"


def test_markers_used_are_declared_in_pytest_ini():
    ini = configparser.ConfigParser()
    ini.read(os.path.join(REPO_ROOT, "pytest.ini"))
    declared = {line.strip().split(":")[0]
                for line in ini["pytest"]["markers"].splitlines()
                if line.strip()}
    builtin = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
               "filterwarnings"}
    used = set()
    pat = re.compile(r"pytest\.mark\.([a-zA-Z_][a-zA-Z0-9_]*)")
    for fname in os.listdir(TESTS_DIR):
        if not fname.endswith(".py"):
            continue
        with open(os.path.join(TESTS_DIR, fname)) as f:
            used.update(pat.findall(f.read()))
    undeclared = used - declared - builtin
    assert not undeclared, \
        f"markers used but not declared in pytest.ini: {undeclared}"
