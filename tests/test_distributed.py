"""Distributed execution tests: full TPC-H queries on the 8-device mesh.

Reference pattern: AbstractTestDistributedQueries — the same query suite
must produce identical results on a multi-node cluster as on one node.
Here: MeshExecutor (row-sharded scans + GSPMD collectives) vs the sqlite
oracle on the virtual 8-device CPU mesh from conftest.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from oracle import assert_rows_match, load_oracle, oracle_query
from tpch_full import QUERIES
from trino_tpu.exec.session import Session
from trino_tpu.parallel.dist_executor import MeshExecutor
from trino_tpu.parallel.mesh import make_mesh

TPCH_TABLES = ["region", "nation", "supplier", "customer", "part",
               "partsupp", "orders", "lineitem"]


@pytest.fixture(scope="module")
def session():
    s = Session(default_schema="tiny")
    s.executor = MeshExecutor(s.catalog, make_mesh(8))
    return s


@pytest.fixture(scope="module")
def oracle(session):
    conn = session.catalog.connector("tpch")
    return load_oracle([conn.get_table("tiny", t) for t in TPCH_TABLES])


def check(session, oracle, sql, ordered=True, abs_tol=0.01):
    got = session.execute(sql).rows
    want = oracle_query(oracle, sql)
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=abs_tol,
                      ordered=ordered)


def test_sharded_scan_placement(session, oracle):
    check(session, oracle, "SELECT count(*) FROM lineitem")


# the distributed executor must pass the same oracle suite as the local
# one — the FULL list (VERDICT round-1 item 7)
@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_distributed(session, oracle, qid):
    check(session, oracle, QUERIES[qid], abs_tol=0.02)


def test_distributed_window(session, oracle):
    check(session, oracle, """
        SELECT o_custkey, o_orderkey,
               sum(o_totalprice) OVER (PARTITION BY o_custkey
                                       ORDER BY o_orderkey) AS rt
        FROM orders ORDER BY o_custkey, o_orderkey""")


def test_distributed_join_agg(session, oracle):
    check(session, oracle, """
        SELECT n_name, count(*) AS c
        FROM customer, nation
        WHERE c_nationkey = n_nationkey
        GROUP BY n_name ORDER BY c DESC, n_name""")


# ---- full TPC-DS suite through the mesh executor ----

from tpcds_queries import ORACLE as DS_ORACLE, ULP_SENSITIVE
from tpcds_queries import QUERIES as DS_QUERIES
from trino_tpu.connectors.tpcds.connector import TABLE_NAMES as DS_TABLES


@pytest.fixture(scope="module")
def ds_session():
    s = Session(default_cat="tpcds", default_schema="tiny")
    s.executor = MeshExecutor(s.catalog, make_mesh(8))
    return s


@pytest.fixture(scope="module")
def ds_oracle(ds_session):
    conn = ds_session.catalog.connector("tpcds")
    return load_oracle([conn.get_table("tiny", t) for t in DS_TABLES])


# full 61-query distributed sweep ~8 min on the virtual mesh: CI runs a
# cross-section; TRINO_TPU_FULL_DIST=1 runs everything (the full-run
# record lives in docs/verification.md)
import os
_DS_DIST = sorted(DS_QUERIES) if os.environ.get("TRINO_TPU_FULL_DIST") \
    else sorted(DS_QUERIES)[::4]


@pytest.mark.parametrize("qid", _DS_DIST)
def test_tpcds_distributed(ds_session, ds_oracle, qid):
    got = ds_session.execute(DS_QUERIES[qid]).rows
    want = oracle_query(ds_oracle,
                        DS_ORACLE.get(qid, DS_QUERIES[qid]))
    if qid in ULP_SENSITIVE:
        assert sorted((r[0], r[1]) for r in got) == \
            sorted((r[0], r[1]) for r in want)
        return
    assert_rows_match(got, want, rel_tol=1e-6, abs_tol=0.02)
