"""CSV connector tests: file layout, inference, NULLs, SQL over files."""

import pytest

from trino_tpu.connectors.csvfile import CsvConnector
from trino_tpu.exec.session import Session


@pytest.fixture()
def csv_session(tmp_path):
    d = tmp_path / "default"
    d.mkdir()
    (d / "people.csv").write_text(
        "name,age,height,joined\n"
        "alice,34,1.7,2020-01-15\n"
        "bob,28,1.82,2021-06-01\n"
        "carol,,1.65,2019-11-30\n"
        "dave,41,,2022-03-10\n")
    (d / "cities.csv").write_text(
        "name,city\nalice,berlin\nbob,paris\ncarol,berlin\n")
    s = Session(default_cat="csv", default_schema="default")
    s.catalog.register("csv", CsvConnector(str(tmp_path)))
    return s


def test_inference_and_metadata(csv_session):
    rows = csv_session.execute("DESCRIBE people").rows
    assert rows == [("name", "varchar"), ("age", "bigint"),
                    ("height", "double"), ("joined", "date")]
    tables = [r[0] for r in csv_session.execute("SHOW TABLES").rows]
    assert tables == ["cities", "people"]


def test_select_with_nulls(csv_session):
    rows = csv_session.execute(
        "SELECT name, age FROM people ORDER BY name").rows
    assert rows == [("alice", 34), ("bob", 28), ("carol", None),
                    ("dave", 41)]


def test_aggregate_and_join_over_files(csv_session):
    rows = csv_session.execute("""
        SELECT c.city, count(*) AS n, avg(p.age) AS avg_age
        FROM people p, cities c
        WHERE p.name = c.name
        GROUP BY c.city ORDER BY c.city""").rows
    assert rows[0][0] == "berlin" and rows[0][1] == 2
    assert rows[1] == ("paris", 1, 28.0)


def test_date_filtering(csv_session):
    rows = csv_session.execute(
        "SELECT name FROM people WHERE joined >= DATE '2021-01-01' "
        "ORDER BY name").rows
    assert rows == [("bob",), ("dave",)]


def test_varchar_join_across_different_pools(csv_session, tmp_path):
    # extras.csv's name pool differs from cities.csv's (zed sorts last,
    # shifting codes) — the join must align dictionaries, not codes
    (tmp_path / "default" / "extras.csv").write_text(
        "name,score\nzed,1\ncarol,2\nalice,3\n")
    rows = csv_session.execute("""
        SELECT e.name, c.city, e.score
        FROM extras e JOIN cities c ON e.name = c.name
        ORDER BY e.name""").rows
    assert rows == [("alice", "berlin", 3), ("carol", "berlin", 2)]


def test_varchar_equality_across_pools(csv_session, tmp_path):
    (tmp_path / "default" / "alt.csv").write_text(
        "name2\nbob\nzed\n")
    rows = csv_session.execute("""
        SELECT p.name FROM people p, alt a
        WHERE p.name = a.name2 ORDER BY p.name""").rows
    assert rows == [("bob",)]


def test_varchar_in_subquery_across_pools(csv_session, tmp_path):
    (tmp_path / "default" / "vip.csv").write_text("vip\nzed\ndave\n")
    rows = csv_session.execute("""
        SELECT name FROM people
        WHERE name IN (SELECT vip FROM vip) ORDER BY name""").rows
    assert rows == [("dave",)]
    rows = csv_session.execute("""
        SELECT name FROM people
        WHERE name NOT IN (SELECT vip FROM vip) ORDER BY name""").rows
    assert rows == [("alice",), ("bob",), ("carol",)]


def test_exists_across_pools(csv_session, tmp_path):
    (tmp_path / "default" / "ex.csv").write_text(
        "name,score\nzed,1\ncarol,2\nalice,3\n")
    rows = csv_session.execute("""
        SELECT name FROM people p
        WHERE EXISTS (SELECT 1 FROM ex e WHERE e.name = p.name)
        ORDER BY name""").rows
    assert rows == [("alice",), ("carol",)]
    rows = csv_session.execute("""
        SELECT name FROM people p
        WHERE NOT EXISTS (SELECT 1 FROM ex e WHERE e.name = p.name)
        ORDER BY name""").rows
    assert rows == [("bob",), ("dave",)]


def test_correlated_scalar_across_pools(csv_session, tmp_path):
    (tmp_path / "default" / "sc.csv").write_text(
        "name,score\nzed,100\ncarol,1\nalice,1\n")
    rows = csv_session.execute("""
        SELECT name FROM people p
        WHERE age > (SELECT sum(score) FROM sc e WHERE e.name = p.name)
        ORDER BY name""").rows
    # carol's age is NULL (NULL > 1 excludes); a raw-code bug would
    # wrongly admit bob (his code collides with carol's in sc's pool)
    assert rows == [("alice",)]


def test_computed_varchar_in_key_across_pools(csv_session, tmp_path):
    (tmp_path / "default" / "vip2.csv").write_text("vip\ncarol\nzed\n")
    rows = csv_session.execute("""
        SELECT name FROM people
        WHERE (CASE WHEN age > 0 THEN name ELSE name END)
              IN (SELECT vip FROM vip2)
        ORDER BY name""").rows
    assert rows == [("carol",)]


def test_cross_pool_where_equality(csv_session, tmp_path):
    (tmp_path / "default" / "pairs.csv").write_text(
        "a,b\nalice,alice\nbob,zed\ncarol,carol\n")
    rows = csv_session.execute(
        "SELECT a FROM pairs WHERE a = b ORDER BY a").rows
    assert rows == [("alice",), ("carol",)]
    rows = csv_session.execute(
        "SELECT a FROM pairs WHERE a <> b ORDER BY a").rows
    assert rows == [("bob",)]


def test_full_join_across_pools(csv_session, tmp_path):
    (tmp_path / "default" / "fx.csv").write_text(
        "name,score\nzed,1\ncarol,2\n")
    rows = csv_session.execute("""
        SELECT p.name, f.name, f.score
        FROM people p FULL JOIN fx f ON p.name = f.name
        ORDER BY p.name NULLS FIRST, f.name NULLS FIRST""").rows
    assert rows[0] == (None, "zed", 1)
    assert ("carol", "carol", 2) in rows
    assert ("bob", None, None) in rows
    assert len(rows) == 5
