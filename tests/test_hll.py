"""HyperLogLog approx_distinct (relational rewrite).

Reference behavior: ApproximateCountDistinctAggregation.java — default
max standard error 2.3%, NULLs ignored, mergeable partial state. Here
the sketch is a relational rewrite (planner.plan_hll_aggregation): an
inner max-aggregate over (keys, bucket) rows whose partials merge with
the ordinary machinery, so the same bound must hold in single-shot,
chunked, and distributed execution.
"""

import pytest

from trino_tpu.exec.session import Session

TOL = 0.023


def _close(got, want):
    # 2.3% is the sketch's ASYMPTOTIC standard error; for small true
    # counts the absolute error floor of a few registers dominates
    return abs(got - want) <= max(TOL * want, 5)


@pytest.fixture(scope="module")
def session():
    return Session(default_schema="tiny")


def _exact(session, sql):
    return session.execute(sql).rows


def test_hll_global_accuracy(session):
    got = session.execute(
        "SELECT approx_distinct(o_custkey) FROM orders").rows[0][0]
    want = session.execute(
        "SELECT count(DISTINCT o_custkey) FROM orders").rows[0][0]
    assert _close(got, want)


def test_hll_grouped_accuracy(session):
    got = session.execute("""
        SELECT l_returnflag, approx_distinct(l_orderkey)
        FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag""").rows
    want = session.execute("""
        SELECT l_returnflag, count(DISTINCT l_orderkey)
        FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag""").rows
    for (f1, a), (f2, e) in zip(got, want):
        assert f1 == f2
        assert _close(a, e)


def test_hll_mixed_with_plain_aggs(session):
    rows = session.execute("""
        SELECT l_returnflag, approx_distinct(l_suppkey), count(*),
               sum(l_quantity), min(l_orderkey), max(l_orderkey)
        FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag""").rows
    want = session.execute("""
        SELECT l_returnflag, count(DISTINCT l_suppkey), count(*),
               sum(l_quantity), min(l_orderkey), max(l_orderkey)
        FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag""").rows
    for g, w in zip(rows, want):
        assert g[0] == w[0]
        assert _close(g[1], w[1])
        assert tuple(g[2:]) == tuple(w[2:])       # plain aggs stay exact


def test_hll_nulls_and_empty(session):
    # all rows filtered out: approx_distinct over empty input is 0
    got = session.execute(
        "SELECT approx_distinct(o_custkey) FROM orders "
        "WHERE o_custkey < 0").rows[0][0]
    assert got == 0
    # NULLs are ignored (nation has no nulls; synthesize via nullif)
    got = session.execute(
        "SELECT approx_distinct(nullif(n_nationkey, n_nationkey)) "
        "FROM nation").rows[0][0]
    assert got == 0
    got = session.execute(
        "SELECT approx_distinct(nullif(n_nationkey, 3)) "
        "FROM nation").rows[0][0]
    assert got == 24


def test_hll_chunked_bounded_state(session):
    """The chunked driver merges the inner aggregate's partial rows —
    bounded 2^p rows per group — instead of refusing distinct the way
    the exact path must."""
    s = Session(default_schema="tiny")
    want = s.execute(
        "SELECT count(DISTINCT l_orderkey) FROM lineitem").rows[0][0]
    s.properties["spill_chunk_rows"] = 8192
    s.executor.spill_chunk_rows = 8192
    got = s.execute(
        "SELECT approx_distinct(l_orderkey) FROM lineitem").rows[0][0]
    assert s.executor.stats.agg_spill_chunks > 1, "did not chunk"
    assert _close(got, want)


def test_hll_matches_exact_fallbacks(session):
    """Mixed with an exact DISTINCT aggregate the rewrite steps aside
    (shared sort-dedup column), so approx == exact there."""
    rows = session.execute("""
        SELECT approx_distinct(o_custkey), count(DISTINCT o_custkey)
        FROM orders""").rows[0]
    assert rows[0] == rows[1]
