"""Tracing spans, event listeners, and verifier tests."""

import pytest

from trino_tpu.client.client import Client
from trino_tpu.events import EventListener
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.utils.tracing import Tracer
from trino_tpu.verifier import Verifier


def test_tracer_spans_nest_and_time():
    s = Session(default_schema="tiny")
    s.tracer = Tracer()
    s.execute("SELECT count(*) FROM nation")
    names = [sp["name"] for sp in s.tracer.export()]
    assert {"plan", "optimize", "execute", "decode"} <= set(names)
    ex = next(sp for sp in s.tracer.export() if sp["name"] == "execute")
    assert ex["durationMs"] >= 0


def test_noop_tracer_collects_nothing():
    s = Session(default_schema="tiny")
    s.execute("SELECT 1")
    assert s.tracer.export() == []


class Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, ev):
        self.created.append(ev)

    def query_completed(self, ev):
        self.completed.append(ev)


def test_event_listener_dispatch():
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    try:
        rec = Recorder()
        coord.state.dispatcher.event_listeners.register(rec)
        client = Client(coord.uri, user="ev")
        r = client.execute("SELECT count(*) FROM nation")
        assert any(e.query_id == r.query_id for e in rec.created)
        done = [e for e in rec.completed if e.query_id == r.query_id]
        assert done and done[0].state == "FINISHED"
        with pytest.raises(Exception):
            client.execute("SELECT broken_col FROM nation")
        assert any(e.state == "FAILED" for e in rec.completed)
    finally:
        coord.stop()


def test_verifier_detects_match_and_mismatch():
    session = Session(default_schema="tiny")
    v = Verifier(session, ["region", "nation"])
    r = v.verify("q", "SELECT n_regionkey, count(*) FROM nation "
                      "GROUP BY n_regionkey ORDER BY n_regionkey")
    assert r.status == "MATCH"
    # control differs: compare against a deliberately different query
    r2 = v.verify("bad", "SELECT count(*) FROM nation",
                  control_sql="SELECT count(*) FROM region")
    assert r2.status == "MISMATCH"
