"""Tracing spans, metrics, event listeners, and verifier tests."""

import logging
import re
import time

import pytest

from trino_tpu.client.client import Client
from trino_tpu.events import EventListener, EventListenerManager
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.worker import WorkerServer
from trino_tpu.utils.tracing import (NOOP, Tracer, format_traceparent,
                                     parse_traceparent)
from trino_tpu.verifier import Verifier


def test_tracer_spans_nest_and_time():
    s = Session(default_schema="tiny")
    s.tracer = Tracer()
    s.execute("SELECT count(*) FROM nation")
    names = [sp["name"] for sp in s.tracer.export()]
    assert {"plan", "optimize", "execute", "decode"} <= set(names)
    ex = next(sp for sp in s.tracer.export() if sp["name"] == "execute")
    assert ex["durationMs"] >= 0


def test_noop_tracer_collects_nothing():
    s = Session(default_schema="tiny")
    s.execute("SELECT 1")
    assert s.tracer.export() == []


class Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, ev):
        self.created.append(ev)

    def query_completed(self, ev):
        self.completed.append(ev)


def test_event_listener_dispatch():
    coord = CoordinatorServer(Session(default_schema="tiny")).start()
    try:
        rec = Recorder()
        coord.state.dispatcher.event_listeners.register(rec)
        client = Client(coord.uri, user="ev")
        r = client.execute("SELECT count(*) FROM nation")
        assert any(e.query_id == r.query_id for e in rec.created)
        done = [e for e in rec.completed if e.query_id == r.query_id]
        assert done and done[0].state == "FINISHED"
        with pytest.raises(Exception):
            client.execute("SELECT broken_col FROM nation")
        assert any(e.state == "FAILED" for e in rec.completed)
    finally:
        coord.stop()


def test_event_listener_failures_logged_once(caplog):
    class Bad(EventListener):
        def query_created(self, ev):
            raise RuntimeError("boom")

    class FakeTQ:
        query_id, session_user, sql = "q1", "u", "SELECT 1"

    mgr = EventListenerManager()
    mgr.register(Bad())
    with caplog.at_level(logging.ERROR, logger="trino_tpu.events"):
        mgr.query_created(FakeTQ())
        mgr.query_created(FakeTQ())       # second failure is suppressed
    recs = [r for r in caplog.records if "event listener" in r.message]
    assert len(recs) == 1
    assert "Bad" in recs[0].getMessage()


# ---------------------------------------------------------------------------
# tracer: span ids, parent links, W3C propagation
# ---------------------------------------------------------------------------

def test_span_parentage_links_by_id_not_name():
    t = Tracer()
    with t.span("query") as root:
        with t.span("task"):
            pass
        with t.span("task"):              # same NAME, different span
            pass
    spans = t.export()
    tasks = [s for s in spans if s["name"] == "task"]
    assert len(tasks) == 2
    assert tasks[0]["spanId"] != tasks[1]["spanId"]
    # both link to the root by SPAN ID (a name link would be ambiguous)
    assert all(s["parentSpanId"] == root.span_id for s in tasks)
    q = next(s for s in spans if s["name"] == "query")
    assert q["parentSpanId"] is None
    assert all(s["traceId"] == t.trace_id for s in spans)


def test_traceparent_roundtrip_and_remote_parentage():
    t = Tracer()
    with t.span("dispatch") as d:
        tp = t.traceparent()
    assert tp == format_traceparent(t.trace_id, d.span_id)
    assert parse_traceparent(tp) == (t.trace_id, d.span_id)
    # a remote tracer adopting the header roots its spans under the
    # dispatching span and keeps the trace id
    remote = Tracer.from_traceparent(tp, service="worker:w0")
    assert remote.trace_id == t.trace_id
    with remote.span("worker-task"):
        pass
    (w,) = remote.export()
    assert w["parentSpanId"] == d.span_id
    assert w["service"] == "worker:w0"
    # malformed headers degrade to a fresh trace, never an error
    assert parse_traceparent("garbage") is None
    assert parse_traceparent(None) is None
    assert Tracer.from_traceparent("garbage").remote_parent is None


def test_noop_tracer_emits_no_traceparent():
    assert NOOP.traceparent() is None
    with NOOP.span("x") as s:
        assert s is None
    assert NOOP.export() == []


def test_adopted_remote_spans_merge_into_export():
    t = Tracer()
    t.adopt([{"name": "remote", "spanId": "aa", "traceId": t.trace_id}])
    assert any(s["name"] == "remote" for s in t.export())
    t.clear()
    assert t.export() == []


# ---------------------------------------------------------------------------
# metrics registry: Prometheus text exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9.eE+-]+(Inf)?$")


def _assert_prometheus_text(text):
    """Every non-comment line must be a well-formed sample."""
    names = set()
    for line in text.strip().split("\n"):
        if line.startswith("# HELP") or line.startswith("# TYPE"):
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        names.add(line.split("{")[0].split(" ")[0])
    return names


def test_metrics_registry_renders_prometheus_text():
    from trino_tpu.metrics import MetricsRegistry
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help text")
    g = reg.gauge("t_gauge", "g", labelnames=("node",))
    h = reg.histogram("t_seconds", "h")
    c.inc()
    c.inc(2)
    g.set(7, node="w0")
    h.observe(0.3)
    text = reg.render()
    assert "# TYPE t_total counter" in text
    assert "t_total 3" in text
    assert 't_gauge{node="w0"} 7' in text
    assert 't_seconds_bucket{le="+Inf"} 1' in text
    assert "t_seconds_count 1" in text
    _assert_prometheus_text(text)
    # idempotent re-registration returns the same metric
    assert reg.counter("t_total") is c
    with pytest.raises(ValueError):
        reg.gauge("t_total")
    # unobserved unlabeled counters still render at 0
    reg.counter("t_cold_total", "never incremented")
    assert "t_cold_total 0" in reg.render()


# ---------------------------------------------------------------------------
# cluster: trace propagation + /v1/metrics + distributed EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session).start()
    coord.state.scheduler.split_rows = 8192
    workers = [WorkerServer(f"obs-w{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(2)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 2 and time.time() < deadline:
        time.sleep(0.05)
    yield coord, workers, session
    coord.stop()
    for w in workers:
        w.stop()


DIST_SQL = ("SELECT l_returnflag, count(*) AS c FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag")


def test_cluster_trace_stitched_across_workers(cluster):
    coord, workers, session = cluster
    # cold spool: a durable-exchange hit would satisfy the query
    # without dispatching tasks (no TaskStats to roll up)
    coord.state.scheduler.spool.clear()
    client = Client(coord.uri, user="obs")
    client.execute("SET SESSION enable_tracing = true")
    try:
        r = client.execute(DIST_SQL)
        info = client.query_info(r.query_id)
        assert info["distributed"], info["fallbackReason"]
        trace = client._request(
            "GET", f"{coord.uri}/v1/query/{r.query_id}/trace")
        spans = trace["spans"]
        names = {s["name"] for s in spans}
        # coordinator-side spans AND worker-side spans in ONE trace
        assert {"query", "source-stage", "worker-task"} <= names
        assert len({s["traceId"] for s in spans}) == 1
        assert trace["traceId"] == spans[0]["traceId"]
        # every non-root span's parent is a span in the same trace
        ids = {s["spanId"] for s in spans}
        for s in spans:
            if s["parentSpanId"] is not None:
                assert s["parentSpanId"] in ids, s
        # worker spans attribute their service
        services = {s.get("service") for s in spans}
        assert any(sv and sv.startswith("worker:") for sv in services)
        # task rollup reached the completion surface
        st = info["stageStats"]
        assert st["tasks"] >= 2 and st["bytesShuffled"] > 0
    finally:
        client.execute("SET SESSION enable_tracing = false")


def test_client_traceparent_continues_callers_trace(cluster):
    """A client that sends its own W3C context gets the query trace
    rooted under ITS span (same trace id, coordinator query span
    parented on the caller's span id)."""
    coord, workers, session = cluster
    caller = Tracer(service="caller")
    with caller.span("app-request") as app:
        client = Client(coord.uri, user="obs",
                        traceparent=caller.traceparent())
        client.execute("SET SESSION enable_tracing = true")
        try:
            r = client.execute(DIST_SQL)
        finally:
            client.execute("SET SESSION enable_tracing = false")
    trace = client._request(
        "GET", f"{coord.uri}/v1/query/{r.query_id}/trace")
    assert trace["traceId"] == caller.trace_id
    q = next(s for s in trace["spans"] if s["name"] == "query")
    assert q["parentSpanId"] == app.span_id


def test_cluster_trace_empty_when_tracing_disabled(cluster):
    coord, workers, session = cluster
    client = Client(coord.uri, user="obs")
    r = client.execute(DIST_SQL)
    trace = client._request(
        "GET", f"{coord.uri}/v1/query/{r.query_id}/trace")
    assert trace["spans"] == []
    # and the session-level tracer collected nothing either
    assert session.tracer.export() == []


def test_metrics_endpoints_serve_prometheus(cluster):
    coord, workers, session = cluster
    from urllib.request import urlopen
    from trino_tpu.metrics import QUERIES
    finished0 = QUERIES.value(state="FINISHED")
    client = Client(coord.uri, user="obs")
    client.execute(DIST_SQL)
    for uri in (coord.uri, workers[0].uri):
        with urlopen(f"{uri}/v1/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        names = _assert_prometheus_text(text)
        # the acceptance surface: operator rows/bytes, scheduler
        # hedge/retry counters, CRC failures — present even at 0
        assert "trino_tpu_operator_rows_total" in names
        assert "trino_tpu_task_output_bytes_total" in names
        assert "trino_tpu_sched_task_retries_total" in names
        assert "trino_tpu_sched_hedges_total" in names
        assert "trino_tpu_pageserde_crc_failures_total" in names
        assert "trino_tpu_http_requests_total" in names
    # counters moved for the known query
    assert QUERIES.value(state="FINISHED") >= finished0 + 1
    from trino_tpu.metrics import OPERATOR_ROWS, TASK_OUTPUT_BYTES
    assert OPERATOR_ROWS.value(operator="scan") > 0
    assert TASK_OUTPUT_BYTES.value() > 0


def test_explain_analyze_distributed_shows_stage_rows(cluster):
    coord, workers, session = cluster
    # cold spool: a durable-exchange hit would satisfy the query
    # without dispatching tasks (no TaskStats to roll up)
    coord.state.scheduler.spool.clear()
    client = Client(coord.uri, user="obs")
    r = client.execute("EXPLAIN ANALYZE " + DIST_SQL)
    assert client.query_info(r.query_id)["distributed"]
    text = "\n".join(row[0] for row in r.rows)
    assert "Distributed execution" in text
    m = re.search(r"Stage source: tasks=(\d+), splits=(\d+), "
                  r"rows=(\d+)", text)
    assert m, text
    assert int(m.group(1)) >= 2 and int(m.group(3)) > 0
    # per-operator rollup (worker profiling forced by EXPLAIN ANALYZE)
    assert re.search(r"operator \w+: rows=\d+, wall=", text), text


def test_completed_event_carries_distributed_rollup(cluster):
    coord, workers, session = cluster
    # cold spool: a durable-exchange hit would satisfy the query
    # without dispatching tasks (no TaskStats to roll up)
    coord.state.scheduler.spool.clear()

    class Recorder2(EventListener):
        def __init__(self):
            self.completed = []

        def query_completed(self, ev):
            self.completed.append(ev)

    rec = Recorder2()
    coord.state.dispatcher.event_listeners.register(rec)
    client = Client(coord.uri, user="obs")
    r = client.execute(DIST_SQL)
    ev = next(e for e in rec.completed if e.query_id == r.query_id)
    assert ev.state == "FINISHED"
    assert ev.tasks >= 2
    assert ev.bytes_shuffled > 0
    assert ev.stages >= 2


def test_system_runtime_tasks_and_operator_stats(cluster):
    coord, workers, session = cluster
    # cold spool: a durable-exchange hit would satisfy the query
    # without dispatching tasks (no TaskStats to roll up)
    coord.state.scheduler.spool.clear()
    client = Client(coord.uri, user="obs")
    client.execute(DIST_SQL)
    r = client.execute("SELECT node_id, rows, bytes FROM "
                      "system.runtime.tasks")
    assert len(r.rows) >= 2
    assert any(int(row[2]) > 0 for row in r.rows)
    # operator_stats fills from profiled runs (EXPLAIN ANALYZE above or
    # traced queries); at minimum the table is queryable
    r2 = client.execute("SELECT operator, rows FROM "
                       "system.runtime.operator_stats")
    assert r2.state == "FINISHED"


def test_verifier_detects_match_and_mismatch():
    session = Session(default_schema="tiny")
    v = Verifier(session, ["region", "nation"])
    r = v.verify("q", "SELECT n_regionkey, count(*) FROM nation "
                      "GROUP BY n_regionkey ORDER BY n_regionkey")
    assert r.status == "MATCH"
    # control differs: compare against a deliberately different query
    r2 = v.verify("bad", "SELECT count(*) FROM nation",
                  control_sql="SELECT count(*) FROM region")
    assert r2.status == "MISMATCH"
