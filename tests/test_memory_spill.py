"""Memory accounting + bounded-memory aggregation tests.

Reference patterns: MemoryPool reserve/kill (memory/MemoryPool.java:44),
SpillableHashAggregationBuilder — results must be identical with and
without spilling (the reference's spill tests assert the same).
"""

import pytest

from oracle import assert_rows_match
from trino_tpu.exec.memory import ExceededMemoryLimitError
from trino_tpu.exec.session import Session

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) q, count(*) c,
       avg(l_extendedprice) p, min(l_discount) mn, max(l_tax) mx
FROM lineitem
WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


@pytest.fixture()
def session():
    return Session(default_schema="tiny")


def test_chunked_aggregation_identical_results(session):
    want = session.execute(Q1).rows
    session.execute("SET SESSION spill_chunk_rows = 7000")
    got = session.execute(Q1).rows
    assert session.executor.stats.agg_spill_chunks >= 8
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)


def test_chunked_global_aggregate(session):
    want = session.execute(
        "SELECT count(*), sum(l_quantity), min(l_shipdate) FROM lineitem"
    ).rows
    session.execute("SET SESSION spill_chunk_rows = 9999")
    got = session.execute(
        "SELECT count(*), sum(l_quantity), min(l_shipdate) FROM lineitem"
    ).rows
    assert got == want
    assert session.executor.stats.agg_spill_chunks >= 6


def test_memory_limit_kills_query(session):
    session.execute("SET SESSION query_max_memory_mb = 1")
    with pytest.raises(ExceededMemoryLimitError):
        session.execute(
            "SELECT sum(l_quantity), sum(l_extendedprice), "
            "sum(l_discount), sum(l_tax) FROM lineitem")
    # raising the limit restores service
    session.execute("SET SESSION query_max_memory_mb = 4096")
    r = session.execute("SELECT count(*) FROM nation")
    assert r.rows[0][0] == 25


def test_peak_memory_tracked(session):
    session.execute("SELECT count(*) FROM orders")
    assert session.executor.pool.peak > 0
