"""Memory accounting + bounded-memory aggregation tests.

Reference patterns: MemoryPool reserve/kill (memory/MemoryPool.java:44),
SpillableHashAggregationBuilder — results must be identical with and
without spilling (the reference's spill tests assert the same).
"""

import pytest

pytestmark = pytest.mark.slow

from oracle import assert_rows_match
from trino_tpu.exec.memory import ExceededMemoryLimitError
from trino_tpu.exec.session import Session

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) q, count(*) c,
       avg(l_extendedprice) p, min(l_discount) mn, max(l_tax) mx
FROM lineitem
WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""


@pytest.fixture()
def session():
    return Session(default_schema="tiny")


def test_chunked_aggregation_identical_results(session):
    want = session.execute(Q1).rows
    session.execute("SET SESSION spill_chunk_rows = 7000")
    got = session.execute(Q1).rows
    assert session.executor.stats.agg_spill_chunks >= 8
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)


def test_chunked_global_aggregate(session):
    want = session.execute(
        "SELECT count(*), sum(l_quantity), min(l_shipdate) FROM lineitem"
    ).rows
    session.execute("SET SESSION spill_chunk_rows = 9999")
    got = session.execute(
        "SELECT count(*), sum(l_quantity), min(l_shipdate) FROM lineitem"
    ).rows
    assert got == want
    assert session.executor.stats.agg_spill_chunks >= 6


def test_memory_limit_kills_query(session):
    session.execute("SET SESSION query_max_memory_mb = 1")
    with pytest.raises(ExceededMemoryLimitError):
        session.execute(
            "SELECT sum(l_quantity), sum(l_extendedprice), "
            "sum(l_discount), sum(l_tax) FROM lineitem")
    # raising the limit restores service
    session.execute("SET SESSION query_max_memory_mb = 4096")
    r = session.execute("SELECT count(*) FROM nation")
    assert r.rows[0][0] == 25


def test_peak_memory_tracked(session):
    session.execute("SELECT count(*) FROM orders")
    assert session.executor.pool.peak > 0


Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate, l_orderkey
LIMIT 10
"""

Q9ISH = """
SELECT n_name, EXTRACT(YEAR FROM o_orderdate) AS o_year,
       sum(l_extendedprice * (1 - l_discount)) AS profit
FROM lineitem, orders, supplier, nation
WHERE o_orderkey = l_orderkey
  AND s_suppkey = l_suppkey
  AND s_nationkey = n_nationkey
GROUP BY n_name, EXTRACT(YEAR FROM o_orderdate)
ORDER BY n_name, o_year DESC
"""


def test_chunked_join_pipeline_identical_results(session):
    """The driver scan streams through joins to the partial aggregate
    (the spilling-join partition-at-a-time analog, PartitionedConsumption
    with the fact table as the streamed side)."""
    want = session.execute(Q3).rows
    session.execute("SET SESSION spill_chunk_rows = 8192")
    got = session.execute(Q3).rows
    assert session.executor.stats.agg_spill_chunks >= 7
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)


def test_chunked_multiway_join_identical_results(session):
    want = session.execute(Q9ISH).rows
    session.execute("SET SESSION spill_chunk_rows = 10000")
    got = session.execute(Q9ISH).rows
    assert session.executor.stats.agg_spill_chunks >= 6
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0)


def test_chunked_concat_no_aggregate(session):
    """No aggregate above the driver scan: per-chunk outputs concatenate
    on host (merge point = plan root)."""
    q = ("SELECT l_orderkey, l_quantity FROM lineitem "
         "WHERE l_shipdate > DATE '1998-11-01'")
    want = sorted(session.execute(q).rows)
    session.execute("SET SESSION spill_chunk_rows = 9000")
    got = sorted(session.execute(q).rows)
    assert session.executor.stats.agg_spill_chunks >= 6
    assert got == want


def test_chunked_bounded_memory_actually_bounds(session):
    """A memory limit that kills the single-shot plan passes chunked —
    spill exists to keep HBM bounded, so prove it does."""
    q = ("SELECT sum(l_quantity), sum(l_extendedprice), sum(l_discount), "
         "sum(l_tax), min(l_shipdate), max(l_commitdate) FROM lineitem")
    session.execute("SET SESSION query_max_memory_mb = 2")
    with pytest.raises(ExceededMemoryLimitError):
        session.execute(q)
    session.execute("SET SESSION spill_chunk_rows = 4096")
    rows = session.execute(q).rows
    assert rows[0][0] is not None


@pytest.mark.parametrize("qnum", [5, 7, 9, 18, 21])
def test_chunked_tpch_big_build_queries(session, qnum):
    """The big-build TPC-H queries (VERDICT: q9/q18 shapes) must give
    identical results with the fact table streamed in chunks; queries
    whose plan shape can't chunk must fall back, not break."""
    import sys
    sys.path.insert(0, "tests")
    from tpch_full import QUERIES
    session.execute("SET SESSION spill_chunk_rows = 0")
    want = session.execute(QUERIES[qnum]).rows
    session.execute("SET SESSION spill_chunk_rows = 8000")
    got = session.execute(QUERIES[qnum]).rows
    session.execute("SET SESSION spill_chunk_rows = 0")
    assert_rows_match(got, want, rel_tol=1e-9, abs_tol=0.02)


def test_streaming_build_join_matches_resident():
    """Spill tier v2: a build side above the streaming threshold runs
    chunk-wise through the dense LUT with host payload gathers; results
    must equal the resident-build join."""
    s = Session(default_schema="tiny")
    sql = ("SELECT o_orderkey, o_totalprice, c_name, c_acctbal"
           " FROM orders o JOIN customer c ON o.o_custkey = c.c_custkey"
           " WHERE o_orderdate < DATE '1993-01-01'"
           " ORDER BY o_orderkey LIMIT 200")
    want = s.execute(sql).rows
    s2 = Session(default_schema="tiny")
    s2.execute("SET SESSION stream_build_min_kb = 1")
    s2.executor.spill_chunk_rows = 500                 # many build chunks
    got = s2.execute(sql).rows
    assert s2.executor.stats.agg_spill_chunks >= 2
    assert got == want and len(got) == 200


def test_streaming_build_semi_join():
    s = Session(default_schema="tiny")
    sql = ("SELECT count(*) FROM orders WHERE o_custkey IN"
           " (SELECT c_custkey FROM customer WHERE c_acctbal > 0)")
    want = s.execute(sql).rows
    s2 = Session(default_schema="tiny")
    s2.execute("SET SESSION stream_build_min_kb = 1")
    s2.executor.spill_chunk_rows = 400
    got = s2.execute(sql).rows
    assert got == want
