"""Authentication + access control through the HTTP protocol.

Reference: security/AccessControlManager.java (layered authz at
dispatch), plugin/trino-password-authenticators (authn at intake),
FileBasedSystemAccessControl (rule lists). The denial must surface
through POST /v1/statement, not just the Python API.
"""

import urllib.error

import pytest

from trino_tpu.client.client import Client, QueryError
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer
from trino_tpu.server.security import (AccessDeniedError, AccessRule,
                                       PasswordAuthenticator,
                                       RuleAccessControl,
                                       check_statement_access)


@pytest.fixture
def coord():
    c = CoordinatorServer(Session(default_schema="tiny")).start()
    yield c
    c.stop()


def test_password_authn_gates_http(coord):
    coord.state.dispatcher.authenticator = PasswordAuthenticator(
        {"alice": "s3cret"})
    ok = Client(coord.uri, user="alice", password="s3cret")
    assert ok.execute("SELECT count(*) FROM region").rows == [[5]]
    bad = Client(coord.uri, user="alice", password="wrong")
    with pytest.raises(urllib.error.HTTPError) as e:
        bad.execute("SELECT 1")
    assert e.value.code == 401
    anon = Client(coord.uri, user="mallory")
    with pytest.raises(urllib.error.HTTPError) as e:
        anon.execute("SELECT 1")
    assert e.value.code == 401
    coord.state.dispatcher.authenticator = None


def test_table_authz_denial_over_http(coord):
    """Round-4 verdict missing #2 done-criterion: an authz denial
    through the HTTP protocol."""
    coord.state.dispatcher.access_control = RuleAccessControl([
        AccessRule(user="analyst", catalog="tpch", schema="tiny",
                   table="nation", privileges=("select",)),
        AccessRule(user="admin"),
    ])
    allowed = Client(coord.uri, user="analyst")
    assert allowed.execute(
        "SELECT count(*) FROM nation").rows == [[25]]
    with pytest.raises(QueryError, match="Access Denied"):
        allowed.execute("SELECT count(*) FROM lineitem")
    # resolution-based: hiding the denied table inside a join or
    # subquery is still caught (refs come from the PLAN's scans)
    with pytest.raises(QueryError, match="Access Denied"):
        allowed.execute("""
            SELECT count(*) FROM nation,
              (SELECT l_orderkey FROM lineitem LIMIT 5) t""")
    admin = Client(coord.uri, user="admin")
    assert admin.execute("SELECT count(*) FROM lineitem").rows[0][0] > 0


def test_write_privilege_separate_from_select():
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    s.execute("CREATE TABLE m.s.t (x bigint)")
    ac = RuleAccessControl([
        AccessRule(user="reader", catalog="m",
                   privileges=("select",)),
    ])
    check_statement_access(ac, s, "SELECT * FROM m.s.t", "reader")
    with pytest.raises(AccessDeniedError, match="cannot write"):
        check_statement_access(
            ac, s, "INSERT INTO m.s.t VALUES (1)", "reader")
    with pytest.raises(AccessDeniedError):
        check_statement_access(ac, s, "DROP TABLE m.s.t", "reader")


def test_rules_first_match_wins_and_default_deny():
    ac = RuleAccessControl([
        AccessRule(user="bob", table="secret_*", allow=False),
        AccessRule(user="bob"),
    ])
    ac.check("bob", "c", "s", "open", "select")
    with pytest.raises(AccessDeniedError):
        ac.check("bob", "c", "s", "secret_plans", "select")
    with pytest.raises(AccessDeniedError):     # no rule for carol
        ac.check("carol", "c", "s", "open", "select")


def test_merge_source_reads_are_checked():
    """MERGE's USING relation is a READ: a denied source table must not
    leak through the write-side check (review finding)."""
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.tpch.connector import TpchConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    cat.register("tpch", TpchConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    s.execute("CREATE TABLE m.s.t (k bigint, v bigint)")
    ac = RuleAccessControl([
        AccessRule(user="w", catalog="m"),          # full access to m
    ])
    with pytest.raises(AccessDeniedError, match="nation"):
        check_statement_access(ac, s, """
            MERGE INTO m.s.t USING tpch.tiny.nation n
              ON t.k = n.n_nationkey
            WHEN MATCHED THEN UPDATE SET v = n.n_regionkey""", "w")


def _mem_tpch_session():
    from trino_tpu.catalog import Catalog
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.tpch.connector import TpchConnector
    cat = Catalog()
    cat.register("m", MemoryConnector())
    cat.register("tpch", TpchConnector())
    s = Session(catalog=cat, default_cat="m", default_schema="s")
    s.execute("CREATE TABLE m.s.t (k bigint, v bigint)")
    return s


def test_update_where_subquery_reads_are_checked():
    """Round-5 high finding: UPDATE/DELETE access control missed reads
    in WHERE subqueries — a write grant on one catalog could exfiltrate
    any denied table via `WHERE k IN (SELECT ... FROM denied)`. The
    shadow query is now planned and its ScanNodes collected as READ
    refs, like the MERGE USING fix."""
    s = _mem_tpch_session()
    ac = RuleAccessControl([AccessRule(user="w", catalog="m")])
    with pytest.raises(AccessDeniedError, match="nation"):
        check_statement_access(ac, s, """
            UPDATE m.s.t SET v = 1
            WHERE k IN (SELECT n_nationkey FROM tpch.tiny.nation)""",
            "w")
    with pytest.raises(AccessDeniedError, match="nation"):
        check_statement_access(ac, s, """
            DELETE FROM m.s.t
            WHERE k IN (SELECT n_nationkey FROM tpch.tiny.nation)""",
            "w")
    with pytest.raises(AccessDeniedError, match="region"):
        check_statement_access(ac, s, """
            DELETE FROM m.s.t WHERE EXISTS (
              SELECT 1 FROM tpch.tiny.region WHERE r_regionkey = k)""",
            "w")
    # statements confined to the granted catalog still pass
    check_statement_access(ac, s, "UPDATE m.s.t SET v = 2 WHERE k = 1",
                           "w")
    check_statement_access(ac, s, "DELETE FROM m.s.t WHERE k = 1", "w")


def test_update_set_subquery_reads_are_checked():
    """SET-side scalar subqueries read too (the same round-5 hole)."""
    s = _mem_tpch_session()
    ac = RuleAccessControl([AccessRule(user="w", catalog="m")])
    with pytest.raises(AccessDeniedError, match="region"):
        check_statement_access(ac, s, """
            UPDATE m.s.t
            SET v = (SELECT max(r_regionkey) FROM tpch.tiny.region)
            WHERE k = 1""", "w")


def test_select_item_scalar_subquery_reads_are_checked():
    """Scalar subqueries embedded in select items carry their plan
    inside the expression tree; the checker now walks those subplans
    too instead of only the top-level plan children."""
    s = _mem_tpch_session()
    ac = RuleAccessControl([AccessRule(user="w", catalog="m")])
    with pytest.raises(AccessDeniedError, match="nation"):
        check_statement_access(ac, s, """
            SELECT (SELECT max(n_nationkey) FROM tpch.tiny.nation)
            FROM m.s.t""", "w")


def test_liveness_stays_open_on_secured_cluster(coord):
    """Load-balancer probes must not need credentials (documented
    contract; the failure detector pings /v1/status the same way)."""
    import json
    from urllib.request import urlopen
    coord.state.dispatcher.authenticator = PasswordAuthenticator(
        {"alice": "pw"})
    try:
        for route in ("/v1/status", "/v1/info"):
            with urlopen(f"{coord.uri}{route}") as resp:
                assert resp.status == 200
                json.loads(resp.read())
    finally:
        coord.state.dispatcher.authenticator = None
