"""Query-lifetime enforcement: deadlines, cancellation propagation,
orphan reaping, overload admission control (round-22).

Reference: Trino's QueryTracker enforces query_max_run_time /
query_max_queued_time against QueryInfo timestamps and SqlTaskManager
abandons tasks no coordinator call referenced for
task.info-update-interval-derived timeouts (failTaskOnAbandonment);
LowMemoryKiller, user DELETE and enforcement all converge on the same
QueryStateMachine terminal transition, which fans task cancellation out
to every worker.

Unit tier: terminate() taxonomy per reason, the deadline-enforcer
sweep, queued-time timeline attribution for queries that died while
QUEUED, the load-shed admission gate, the micro-batch follower's
deadline/cancel-aware wait, and the orphan reaper. Cluster tier (real
HTTP, 3 workers): a user DELETE fans task DELETEs out to every
in-flight worker task, a HANG-stuck distributed query is terminated by
its deadline end-to-end, and overload rejections surface as retryable
protocol errors.
"""

import threading
import time
from types import SimpleNamespace

import pytest

from trino_tpu.client.client import Client, QueryError
from trino_tpu.exec.session import Session
from trino_tpu.server.coordinator import CoordinatorServer, CoordinatorState
from trino_tpu.server.failureinjector import DELAY, HANG, FailureInjector
from trino_tpu.server.statemachine import QueryStateMachine, TrackedQuery
from trino_tpu.server.worker import WorkerServer


def _tracked(state, sql="SELECT 1", user="u"):
    """Register a bare TrackedQuery with the dispatcher (no execution)."""
    disp = state.dispatcher
    qid = disp.tracker.next_query_id()
    tq = TrackedQuery(qid, sql, user, QueryStateMachine(qid))
    disp.tracker.register(tq)
    return tq


# ---------------------------------------------------------------------------
# terminate(): the single cancellation path, per-reason taxonomy
# ---------------------------------------------------------------------------

def test_deadline_stamped_at_admission():
    session = Session(default_schema="tiny")
    session.execute("SET SESSION query_max_run_time_s = 2.5")
    session.execute("SET SESSION query_max_queued_time_s = 0.5")
    state = CoordinatorState(session)
    t0 = time.time()
    tq = state.dispatcher.submit("SELECT count(*) FROM nation", "u")
    assert tq.deadline is not None
    assert t0 + 2.0 < tq.deadline <= time.time() + 2.5
    assert tq.queued_deadline is not None
    assert tq.queued_deadline < tq.deadline
    # the enforcer thread lazily starts with the first deadline
    assert state.dispatcher._enforcer is not None
    state.dispatcher.pool.shutdown(wait=True)


def test_no_deadline_without_session_property():
    state = CoordinatorState(Session(default_schema="tiny"))
    tq = _tracked(state)
    assert tq.deadline is None and tq.queued_deadline is None
    # a sweep over deadline-free queries terminates nothing
    assert state.dispatcher.enforce_deadlines() == 0
    assert not tq.state_machine.is_done()


def test_terminate_taxonomy_per_reason():
    from trino_tpu.exec.memory import ExceededMemoryLimitError
    from trino_tpu.metrics import CANCEL_PROPAGATIONS
    state = CoordinatorState(Session(default_schema="tiny"))
    disp = state.dispatcher

    want = [
        ("user", "CANCELED", "USER_CANCELED", 2),
        ("deadline", "FAILED", "QUERY_EXCEEDED_RUN_TIME", 4),
        ("queued_deadline", "FAILED", "QUERY_EXCEEDED_QUEUED_TIME", 6),
        ("oom", "FAILED", ExceededMemoryLimitError.error_name,
         ExceededMemoryLimitError.error_code),
        ("stuck", "FAILED", "GENERIC_INTERNAL_ERROR", 1),
    ]
    for reason, terminal, error_name, error_code in want:
        before = CANCEL_PROPAGATIONS.value(reason=reason)
        tq = _tracked(state)
        assert disp.terminate(tq.query_id, reason=reason) is True
        sm = tq.state_machine
        assert sm.state == terminal
        assert tq.terminate_reason == reason
        if terminal == "FAILED":
            assert sm.error_name == error_name
            assert sm.error_code == error_code
        assert CANCEL_PROPAGATIONS.value(reason=reason) == before + 1
        # the race-safety contract: a second terminator loses cleanly
        assert disp.terminate(tq.query_id, reason=reason) is False
        assert CANCEL_PROPAGATIONS.value(reason=reason) == before + 1
    assert disp.terminate("no-such-query") is False


def test_deadline_expiry_is_not_retryable_queue_errors_are():
    """Protocol taxonomy: QUERY_EXCEEDED_RUN_TIME must not be retried
    (the re-run would expire again), while the two admission rejections
    are explicitly safe to resubmit."""
    from trino_tpu.server.resourcegroups import (
        QueryQueueFullError, QueryQueuedTimeExceededError)
    assert QueryQueueFullError.retryable is True
    assert QueryQueuedTimeExceededError.retryable is True
    state = CoordinatorState(Session(default_schema="tiny"))
    tq = _tracked(state)
    state.dispatcher.terminate(tq.query_id, reason="deadline")
    assert "query_max_run_time_s" in tq.state_machine.error


# ---------------------------------------------------------------------------
# the deadline-enforcer sweep
# ---------------------------------------------------------------------------

def test_enforce_deadlines_sweep():
    from trino_tpu.metrics import QUERIES_DEADLINE_EXCEEDED
    state = CoordinatorState(Session(default_schema="tiny"))
    disp = state.dispatcher
    before = QUERIES_DEADLINE_EXCEEDED.value()

    expired = _tracked(state)
    expired.deadline = time.time() - 0.1
    queued_expired = _tracked(state)
    queued_expired.queued_deadline = time.time() - 0.1
    alive = _tracked(state)
    alive.deadline = time.time() + 60

    assert disp.enforce_deadlines() == 2
    assert expired.state == "FAILED"
    assert expired.state_machine.error_name == "QUERY_EXCEEDED_RUN_TIME"
    assert queued_expired.state == "FAILED"
    assert queued_expired.state_machine.error_name == \
        "QUERY_EXCEEDED_QUEUED_TIME"
    assert not alive.state_machine.is_done()
    assert QUERIES_DEADLINE_EXCEEDED.value() == before + 2
    # idempotent: the next sweep finds nothing left to terminate
    assert disp.enforce_deadlines() == 0


def test_queued_deadline_only_applies_while_queued():
    state = CoordinatorState(Session(default_schema="tiny"))
    tq = _tracked(state)
    tq.queued_deadline = time.time() - 0.1
    tq.state_machine.transition("PLANNING")
    tq.state_machine.transition("RUNNING")
    # the query escaped the queue before the bound: it keeps running
    assert state.dispatcher.enforce_deadlines() == 0
    assert not tq.state_machine.is_done()


def test_expired_queued_query_charges_queue_wait():
    """Satellite: a query that died while QUEUED must attribute its
    whole wall to the `queued` phase (dominant phase included), not
    launder the admission hold into `other`."""
    from trino_tpu.server.timeline import build_timeline
    state = CoordinatorState(Session(default_schema="tiny"))
    tq = _tracked(state)
    tq.queued_deadline = time.time()
    time.sleep(0.05)
    assert state.dispatcher.enforce_deadlines() == 1
    tl = build_timeline(tq)
    assert tl["state"] == "FAILED"
    assert tl["wall_s"] > 0
    assert tl["phases"]["queued"] == pytest.approx(tl["wall_s"])
    assert tl["dominant"] == "queued"
    assert sum(tl["phases"].values()) == pytest.approx(tl["wall_s"])


# ---------------------------------------------------------------------------
# overload admission: the load-shed gate
# ---------------------------------------------------------------------------

def test_load_shed_gate_sheds_heaviest_tenant_only(monkeypatch):
    from trino_tpu.metrics import QUERIES_REJECTED
    monkeypatch.setenv("TRINO_TPU_LOAD_SHED_QUEUE_DEPTH", "1")
    state = CoordinatorState(Session(default_schema="tiny"))
    disp = state.dispatcher
    # force the overload condition and a fair-share view in which the
    # submitting tenant ("default") already holds the most device work
    disp.resource_groups.total_queued = lambda: 5
    disp.serving.fair_share.inflight = \
        lambda: {"default": 3, "light": 0}
    before = QUERIES_REJECTED.value(reason="load_shed")
    tq = disp.submit("SELECT 1", "u")
    assert tq.state == "FAILED"
    assert tq.state_machine.error_name == "QUERY_QUEUE_FULL"
    assert QUERIES_REJECTED.value(reason="load_shed") == before + 1

    # the least-loaded tenant keeps admission even under overload
    disp.serving.fair_share.inflight = \
        lambda: {"default": 0, "heavy": 4}
    tq2 = disp.submit("SELECT count(*) FROM nation", "u")
    deadline = time.time() + 15
    while not tq2.state_machine.is_done() and time.time() < deadline:
        time.sleep(0.02)
    assert tq2.state == "FINISHED", tq2.state_machine.error
    disp.pool.shutdown(wait=True)


def test_load_shed_disabled_by_default(monkeypatch):
    monkeypatch.delenv("TRINO_TPU_LOAD_SHED_QUEUE_DEPTH", raising=False)
    state = CoordinatorState(Session(default_schema="tiny"))
    disp = state.dispatcher
    disp.resource_groups.total_queued = lambda: 10 ** 6
    tq = _tracked(state)
    assert disp._should_shed(tq) is False


# ---------------------------------------------------------------------------
# micro-batch follower: deadline/cancel-aware window wait
# ---------------------------------------------------------------------------

def _wedged_batcher():
    """A MicroBatcher whose window leader never flushes (wedged)."""
    from trino_tpu.server.serving import MicroBatcher, _Window
    serving = SimpleNamespace(
        session=SimpleNamespace(properties={}),
        route_and_run=lambda entry, tq: "degraded")
    mb = MicroBatcher(serving)
    mb._windows["shape"] = _Window()        # open, never flushed
    entry = SimpleNamespace(point_shape=("shape", "k", "'x'"))
    return mb, entry


def test_microbatch_follower_bails_when_query_terminated():
    from trino_tpu.exec.executor import QueryTerminatedError
    mb, entry = _wedged_batcher()
    sm = QueryStateMachine("q-mb-1")
    tq = SimpleNamespace(state_machine=sm, deadline=None)

    def cancel_soon():
        time.sleep(0.15)
        sm.cancel()

    threading.Thread(target=cancel_soon, daemon=True).start()
    t0 = time.monotonic()
    with pytest.raises(QueryTerminatedError):
        mb.submit(entry, tq)
    # the follower noticed between poll slices, not after the 60s bound
    assert time.monotonic() - t0 < 5.0


def test_microbatch_follower_deadline_expiry_counted():
    from trino_tpu.exec.executor import QueryDeadlineError
    from trino_tpu.metrics import MICROBATCH_FOLLOWER_TIMEOUTS
    mb, entry = _wedged_batcher()
    tq = SimpleNamespace(state_machine=QueryStateMachine("q-mb-2"),
                         deadline=time.time() + 0.2)
    before = MICROBATCH_FOLLOWER_TIMEOUTS.value()
    with pytest.raises(QueryDeadlineError, match="query_max_run_time_s"):
        mb.submit(entry, tq)
    assert MICROBATCH_FOLLOWER_TIMEOUTS.value() == before + 1


# ---------------------------------------------------------------------------
# orphan reaping (worker task manager)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def finished_task_factory():
    from trino_tpu.server.tasks import TaskManager, encode_fragment
    session = Session(default_schema="tiny")
    _stmt, pr = session.plan("SELECT count(*) FROM nation")
    frag = encode_fragment({"root": pr.node, "driver": None})
    tm = TaskManager(session.catalog, node_id="reap-w")

    def make(task_id):
        task = tm.create_or_update(task_id, frag, [])
        deadline = time.time() + 30
        while task.state in ("PENDING", "RUNNING") and \
                time.time() < deadline:
            time.sleep(0.02)
        assert task.state == "FINISHED"
        return task

    return tm, make


def test_orphan_reaper_abandons_unreferenced_tasks(finished_task_factory):
    from trino_tpu.metrics import TASKS_ABANDONED
    tm, make = finished_task_factory
    task = make("t-reap-1")
    # recently referenced: never reaped
    assert tm.reap_orphans(timeout_s=60.0) == []
    assert task.state == "FINISHED"
    # stale: abandoned, buffers freed
    task.last_referenced = time.monotonic() - 100
    before = TASKS_ABANDONED.value()
    assert tm.reap_orphans(timeout_s=60.0) == ["t-reap-1"]
    assert task.state == "ABANDONED"
    assert task.buffers == {} and task.buffered_bytes == 0
    assert TASKS_ABANDONED.value() == before + 1
    # already-abandoned tasks are not reaped twice
    assert tm.reap_orphans(timeout_s=60.0) == []


def test_touch_is_the_reapers_liveness_signal(finished_task_factory):
    tm, make = finished_task_factory
    task = make("t-reap-2")
    task.last_referenced = time.monotonic() - 100
    # a coordinator reference (status/results/delete pull) resets the
    # abandonment clock
    tm.touch("t-reap-2")
    assert tm.reap_orphans(timeout_s=60.0) == []
    assert task.state == "FINISHED"
    tm.touch("no-such-task")              # unknown ids are a no-op


# ---------------------------------------------------------------------------
# cluster tier: real HTTP, 3 workers
# ---------------------------------------------------------------------------

Q_AGG = ("SELECT l_returnflag, l_linestatus, sum(l_quantity) AS q, "
         "count(*) AS c FROM lineitem WHERE l_shipdate <= DATE "
         "'1998-09-02' GROUP BY l_returnflag, l_linestatus "
         "ORDER BY l_returnflag, l_linestatus")


@pytest.fixture(scope="module")
def cluster():
    session = Session(default_schema="tiny")
    coord = CoordinatorServer(session, retry_policy="QUERY").start()
    sched = coord.state.scheduler
    sched.split_rows = 8192
    workers = [WorkerServer(f"dl-worker-{i}", coord.uri,
                            announce_interval_s=0.1,
                            catalog=session.catalog).start()
               for i in range(3)]
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)
    yield coord, workers, session
    for w in workers:
        w.stop()
    coord.stop()


@pytest.fixture(autouse=True)
def _clean(request):
    if "cluster" not in request.fixturenames:
        yield
        return
    coord, workers, session = request.getfixturevalue("cluster")
    sched = coord.state.scheduler
    sched.spool.clear()
    yield
    sched.failure_injector = None
    for w in workers:
        inj = w.task_manager.injector
        if inj is not None:
            inj.clear()                   # releases any live HANGs
        w.task_manager.injector = None
    # session properties are shared module-wide: drop the deadline knobs
    session.properties.pop("query_max_run_time_s", None)
    session.properties.pop("query_max_queued_time_s", None)
    deadline = time.time() + 5
    while len(coord.state.active_nodes()) < 3 and time.time() < deadline:
        time.sleep(0.05)


def _wait(pred, timeout_s, interval_s=0.02):
    deadline = time.time() + timeout_s
    while not pred() and time.time() < deadline:
        time.sleep(interval_s)
    return pred()


def test_user_cancel_fans_out_task_deletes(cluster):
    """Satellite regression: canceling a mid-flight distributed query
    must DELETE every in-flight worker task (hedge twins included) —
    the workers see CANCELED tasks, not abandoned RUNNING ones."""
    from trino_tpu.metrics import CANCEL_PROPAGATIONS
    coord, workers, session = cluster
    sched = coord.state.scheduler
    inj = FailureInjector(seed=221)
    inj.inject("WORKER_TASK_RUN", times=3, fault=DELAY, delay_s=1.5)
    for w in workers:
        w.task_manager.injector = inj
    client = Client(coord.uri, user="dl")
    doc = client._submit(Q_AGG)
    qid = doc["id"]
    # wait until the scheduler has live remote tasks for this query
    assert _wait(lambda: sched._live_tasks.get(qid), 10.0), \
        "query never dispatched remote tasks"
    task_ids = [t.task_id for t in sched._live_tasks[qid]]
    assert task_ids
    before = CANCEL_PROPAGATIONS.value(reason="user")
    client._request("DELETE", client._rewrite(doc["nextUri"], client.uri))
    tq = coord.state.tracker.get(qid)
    assert _wait(tq.state_machine.is_done, 10.0)
    assert tq.state == "CANCELED"
    assert tq.terminate_reason == "user"
    assert CANCEL_PROPAGATIONS.value(reason="user") == before + 1
    # every assigned worker task reaches a terminal state within grace
    # (the injected 1.5s delay bounds how long a split can linger)
    held = [t for w in workers for t in [w.task_manager.get(tid)
                                         for tid in task_ids]
            if t is not None]
    assert held, "no worker held any of the query's tasks"
    assert _wait(lambda: all(t.state not in ("PENDING", "RUNNING")
                             for t in held), 10.0), \
        [(t.task_id, t.state) for t in held]
    assert any(t.state == "CANCELED" for t in held)


def test_hang_stuck_query_terminated_by_deadline_end_to_end(cluster):
    """Acceptance: a distributed query wedged by a HANG fault is
    terminated cluster-wide by its coordinator-stamped deadline —
    QUERY_EXCEEDED_RUN_TIME to the client, terminal tasks and zero
    memory reservations on every worker within grace."""
    coord, workers, session = cluster
    client = Client(coord.uri, user="dl")
    client.execute("SET SESSION query_max_run_time_s = 1.0")
    inj = FailureInjector(seed=222)
    # hang every worker's split loop; delay_s is the safety bound, well
    # past the 1.0s deadline that must fire first
    inj.inject("WORKER_TASK_RUN", times=3, fault=HANG, delay_s=4.0)
    for w in workers:
        w.task_manager.injector = inj
    t0 = time.monotonic()
    with pytest.raises(QueryError) as ei:
        client.execute(Q_AGG)
    assert ei.value.error_name == "QUERY_EXCEEDED_RUN_TIME"
    assert "query_max_run_time_s" in str(ei.value)
    # the deadline fired, not the HANG's 4s safety release
    assert time.monotonic() - t0 < 3.5
    tq = next(t for t in reversed(coord.state.tracker.all())
              if t.sql == Q_AGG)
    assert tq.state == "FAILED"
    assert tq.terminate_reason == "deadline"
    inj.release_hangs()
    # all worker tasks terminal and pools drained within grace
    for w in workers:
        tm = w.task_manager
        assert _wait(lambda: all(t.state not in ("PENDING", "RUNNING")
                                 for t in tm.tasks.values()), 10.0), \
            [(t.task_id, t.state) for t in tm.tasks.values()]
    assert _wait(lambda: all(
        w.task_manager.memory_info().get("reserved", 0) == 0
        for w in workers), 10.0)


def test_queue_full_rejection_is_retryable_over_protocol(cluster):
    """Overload degrades to fast rejection: past the queue bound the
    statement fails QUERY_QUEUE_FULL with the payload-level retryable
    flag set, and the client surfaces actionable guidance."""
    import json
    from urllib.request import Request, urlopen
    coord, workers, session = cluster
    root = coord.state.dispatcher.resource_groups.root
    saved = (root.config.hard_concurrency_limit, root.config.max_queued)
    root.config.hard_concurrency_limit = 0
    root.config.max_queued = 0
    try:
        req = Request(f"{coord.uri}/v1/statement", data=b"SELECT 1",
                      headers={"X-Trino-User": "dl"})
        with urlopen(req, timeout=10) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["error"]["errorName"] == "QUERY_QUEUE_FULL"
        assert doc["error"]["errorCode"] == 5
        assert doc["error"]["retryable"] is True
        with pytest.raises(QueryError, match="retryable") as ei:
            Client(coord.uri, user="dl").execute("SELECT 1")
        assert ei.value.error_name == "QUERY_QUEUE_FULL"
    finally:
        root.config.hard_concurrency_limit, root.config.max_queued = saved


# ---------------------------------------------------------------------------
# full overload soak (slow tier; bench.py --overload is the standalone
# runner that emits BENCH_overload.json for the regression gate)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_overload_soak(cluster):
    from bench import overload_soak
    coord, workers, session = cluster
    rec = overload_soak(cluster=(coord, workers, session), out_path=None)
    assert rec["passed"], rec
    assert rec["wrong_answers"] == 0
    assert rec["rejected_total"] > 0
    assert rec["deadline_kills"] == 3 and rec["canceled"] == 4
    assert rec["tasks_terminal"] and rec["pools_drained"]
