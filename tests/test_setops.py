"""Set operations, VALUES, and FROM-less SELECT.

Reference behavior: UNION/INTERSECT/EXCEPT semantics per the SQL spec as
implemented by Trino (sql/planner/plan/UnionNode.java, IntersectNode.java,
ExceptNode.java; set-op NULLs compare as equal, like GROUP BY keys).
"""

import pytest

from trino_tpu.exec.session import Session


@pytest.fixture(scope="module")
def session():
    return Session()


def rows(session, sql):
    return session.execute(sql).rows


def test_union_all(session):
    assert rows(session, "SELECT 1 AS x UNION ALL SELECT 2") == [(1,), (2,)]


def test_union_distinct(session):
    got = rows(session, "SELECT 1 AS x UNION SELECT 1 UNION SELECT 2 "
                        "ORDER BY x")
    assert got == [(1,), (2,)]


def test_union_keeps_duplicates_within_all(session):
    got = rows(session,
               "SELECT * FROM (VALUES 1, 2, 2) t(x) UNION ALL "
               "SELECT * FROM (VALUES 2) u(y) ORDER BY 1")
    assert got == [(1,), (2,), (2,), (2,)]


def test_intersect(session):
    got = rows(session,
               "SELECT * FROM (VALUES 1, 2, 2, 3) t(x) INTERSECT "
               "SELECT * FROM (VALUES 2, 3, 4) u(y) ORDER BY 1")
    assert got == [(2,), (3,)]


def test_intersect_all_bag_semantics(session):
    got = rows(session,
               "SELECT * FROM (VALUES 1, 2, 2, 2) t(x) INTERSECT ALL "
               "SELECT * FROM (VALUES 2, 2, 4) u(y) ORDER BY 1")
    assert got == [(2,), (2,)]


def test_except(session):
    got = rows(session,
               "SELECT * FROM (VALUES 1, 2, 3, 2) t(x) EXCEPT "
               "SELECT 2 ORDER BY 1")
    assert got == [(1,), (3,)]


def test_except_all_bag_semantics(session):
    got = rows(session,
               "SELECT * FROM (VALUES 1, 2, 2, 3) t(x) EXCEPT ALL "
               "SELECT 2 ORDER BY 1")
    assert got == [(1,), (2,), (3,)]


def test_set_op_nulls_compare_equal(session):
    got = rows(session,
               "SELECT * FROM (VALUES 1, NULL, NULL) t(x) UNION "
               "SELECT * FROM (VALUES NULL) u(y)")
    assert sorted(got, key=lambda r: (r[0] is None, r[0])) == \
        [(1,), (None,)]


def test_union_varchar_dictionary_merge(session):
    got = rows(session, "SELECT 'a' AS s UNION SELECT 'b' UNION SELECT 'a' "
                        "ORDER BY s")
    assert got == [("a",), ("b",)]


def test_union_over_table_strings(session):
    got = rows(session,
               "SELECT l_returnflag AS f FROM lineitem UNION "
               "SELECT l_linestatus FROM lineitem ORDER BY f")
    assert got == [("A",), ("F",), ("N",), ("O",), ("R",)]


def test_union_type_coercion(session):
    got = rows(session,
               "SELECT 1 AS x UNION ALL SELECT CAST(2.5 AS decimal(3,1)) "
               "ORDER BY 1")
    assert [float(x) for (x,) in got] == [1.0, 2.5]


def test_set_op_order_and_limit_bind_to_whole(session):
    got = rows(session, "SELECT 3 AS x UNION ALL SELECT 1 UNION ALL "
                        "SELECT 2 ORDER BY x DESC LIMIT 2")
    assert got == [(3,), (2,)]


def test_intersect_precedence_over_union(session):
    # INTERSECT binds tighter: 1 UNION ALL (2 INTERSECT 2)
    got = rows(session, "SELECT 1 AS x UNION ALL "
                        "(SELECT 2 INTERSECT SELECT 2) ORDER BY 1")
    assert got == [(1,), (2,)]


def test_values_table(session):
    got = rows(session,
               "SELECT y, x FROM (VALUES (1, 'a'), (2, 'b')) AS t(x, y) "
               "ORDER BY x")
    assert got == [("a", 1), ("b", 2)]


def test_bare_values_statement(session):
    assert rows(session, "VALUES 1, 2, 3") == [(1,), (2,), (3,)]


def test_values_row_nulls(session):
    got = rows(session,
               "SELECT * FROM (VALUES (1, NULL), (NULL, 'x')) AS t(a, b)")
    assert got == [(1, None), (None, "x")]


def test_values_aggregate(session):
    got = rows(session,
               "SELECT sum(x), count(*) FROM (VALUES 1, 2, 3, NULL) t(x)")
    assert got == [(6, 4)]


def test_select_without_from(session):
    assert rows(session, "SELECT 1 + 2") == [(3,)]
    assert rows(session, "SELECT 'hello' AS g, 42 AS n") == [("hello", 42)]


def test_cte_from_less(session):
    got = rows(session, "WITH t AS (SELECT 1 AS x) SELECT x + 1 FROM t")
    assert got == [(2,)]


def test_union_in_subquery(session):
    got = rows(session,
               "SELECT count(*) FROM (SELECT 1 AS x UNION ALL SELECT 2 "
               "UNION ALL SELECT 1) t")
    assert got == [(3,)]


def test_values_join_table(session):
    got = rows(session,
               "SELECT count(*) FROM lineitem, (VALUES 'A') t(f) "
               "WHERE l_returnflag = f")
    base = rows(session,
                "SELECT count(*) FROM lineitem WHERE l_returnflag = 'A'")
    assert got == base


def test_in_subquery_inside_or(session):
    """IN-subquery in a disjunction folds to InList (non-conjunct
    position; conjunct-position IN still decorrelates to semi joins)."""
    got = rows(session, """
        SELECT n_name FROM nation
        WHERE n_regionkey = 0
           OR n_nationkey IN (SELECT r_regionkey FROM region
                              WHERE r_name = 'ASIA')
        ORDER BY n_name""")
    # region-0 nations plus nationkey 2 (= ASIA's regionkey) -> BRAZIL
    assert got == [("ALGERIA",), ("BRAZIL",), ("ETHIOPIA",), ("KENYA",),
                   ("MOROCCO",), ("MOZAMBIQUE",)]


def test_not_in_subquery_inside_or(session):
    # the subquery covers every nationkey, so NOT IN is always false and
    # only the regionkey=4 branch contributes
    got = rows(session, """
        SELECT n_name FROM nation
        WHERE n_regionkey = 4
           OR n_nationkey NOT IN (SELECT n_nationkey FROM nation
                                  WHERE n_regionkey <> 9)
        ORDER BY n_name""")
    assert got == [("EGYPT",), ("IRAN",), ("IRAQ",), ("JORDAN",),
                   ("SAUDI ARABIA",)]
