"""Table/column statistics — the cost model's input.

Reference: cost/StatsCalculator.java:22 propagates PlanNodeStatsEstimate
(row count + per-symbol NDV/min/max/null fraction) bottom-up; connectors
supply base stats via the statistics SPI (spi/statistics/). Here base
stats are computed from materialized table data (numpy pass, sampled NDV)
and cached by the catalog; the planner propagates them through filters
and joins (FilterStatsCalculator / JoinStatsRule roles).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np


@dataclass(frozen=True)
class ColumnStats:
    ndv: float                    # distinct values (estimate)
    min_val: Optional[float]      # numeric/date min (None for varchar)
    max_val: Optional[float]
    null_frac: float


@dataclass(frozen=True)
class TableStats:
    row_count: int
    columns: Dict[str, ColumnStats]


_SAMPLE = 1 << 18


def _ndv_estimate(col: np.ndarray, n: int) -> float:
    """Sampled distinct-count via the Duj1 estimator (Haas & Stokes —
    what Postgres' ANALYZE uses): D = d / (1 - ((N-r)/N)(f1/r)), where
    f1 counts sample singletons. Exact for true keys (f1=r => D=N),
    asymptotically d for heavily repeated columns, and ~N/repeat for
    clustered fact keys — the strided sampler it replaces read sorted
    key columns as all-distinct and overestimated NDV by 4-20x, which
    flattened every join-cardinality estimate the reorderer relies on.
    The sample is RANDOM: strided sampling is biased on sorted data."""
    if n <= _SAMPLE:
        return float(len(np.unique(col)))
    rng = np.random.default_rng(0x5EED)
    sample = col[rng.integers(0, n, _SAMPLE)]
    r = len(sample)
    counts = np.unique(sample, return_counts=True)[1]
    d = len(counts)
    f1 = int((counts == 1).sum())
    denom = 1.0 - ((n - r) / n) * (f1 / r)
    est = d / max(denom, d / n)          # clamp keeps D <= N
    return float(min(est, n))


def compute_table_stats(data) -> TableStats:
    """One numpy pass per column over TableData."""
    n = data.num_rows
    cols: Dict[str, ColumnStats] = {}
    for i, f in enumerate(data.schema):
        arr = np.asarray(data.columns[i])
        valid = None if data.valids is None else data.valids[i]
        null_frac = 0.0
        if valid is not None:
            valid = np.asarray(valid)
            null_frac = 1.0 - (valid.sum() / max(1, n))
            arr_v = arr[valid]
        else:
            arr_v = arr
        if len(arr_v) == 0:
            cols[f.name] = ColumnStats(0.0, None, None, null_frac)
            continue
        from .types import TypeKind
        if f.dtype.kind is TypeKind.VARCHAR:
            ndv = float(min(len(f.dictionary or ()),
                            len(arr_v))) or 1.0
            cols[f.name] = ColumnStats(ndv, None, None, null_frac)
            continue
        ndv = _ndv_estimate(arr_v, len(arr_v))
        lo, hi = float(arr_v.min()), float(arr_v.max())
        if np.issubdtype(arr_v.dtype, np.integer):
            # integers cannot have more distincts than their value range
            ndv = min(ndv, hi - lo + 1.0)
        cols[f.name] = ColumnStats(ndv, lo, hi, null_frac)
    return TableStats(n, cols)
