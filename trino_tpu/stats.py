"""Table/column statistics — the cost model's input.

Reference: cost/StatsCalculator.java:22 propagates PlanNodeStatsEstimate
(row count + per-symbol NDV/min/max/null fraction) bottom-up; connectors
supply base stats via the statistics SPI (spi/statistics/). Here base
stats are computed from materialized table data (numpy pass, sampled NDV)
and cached by the catalog; the planner propagates them through filters
and joins (FilterStatsCalculator / JoinStatsRule roles).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np


@dataclass(frozen=True)
class ColumnStats:
    ndv: float                    # distinct values (estimate)
    min_val: Optional[float]      # numeric/date min (None for varchar)
    max_val: Optional[float]
    null_frac: float


@dataclass(frozen=True)
class TableStats:
    row_count: int
    columns: Dict[str, ColumnStats]


_SAMPLE = 1 << 18


def _ndv_estimate(col: np.ndarray, n: int) -> float:
    """Sampled distinct-count with linear scale-up for saturated samples
    (the bias direction that keeps keys looking key-like)."""
    if n <= _SAMPLE:
        return float(len(np.unique(col)))
    step = n // _SAMPLE
    sample = col[::step][:_SAMPLE]
    d = len(np.unique(sample))
    if d >= 0.8 * len(sample):        # nearly all distinct: key-like
        return float(n) * d / len(sample)
    return float(min(n, d * max(1, n // len(sample)) ** 0.5 * 4 + d))


def compute_table_stats(data) -> TableStats:
    """One numpy pass per column over TableData."""
    n = data.num_rows
    cols: Dict[str, ColumnStats] = {}
    for i, f in enumerate(data.schema):
        arr = np.asarray(data.columns[i])
        valid = None if data.valids is None else data.valids[i]
        null_frac = 0.0
        if valid is not None:
            valid = np.asarray(valid)
            null_frac = 1.0 - (valid.sum() / max(1, n))
            arr_v = arr[valid]
        else:
            arr_v = arr
        if len(arr_v) == 0:
            cols[f.name] = ColumnStats(0.0, None, None, null_frac)
            continue
        from .types import TypeKind
        if f.dtype.kind is TypeKind.VARCHAR:
            ndv = float(min(len(f.dictionary or ()),
                            len(arr_v))) or 1.0
            cols[f.name] = ColumnStats(ndv, None, None, null_frac)
            continue
        ndv = _ndv_estimate(arr_v, len(arr_v))
        cols[f.name] = ColumnStats(
            ndv, float(arr_v.min()), float(arr_v.max()), null_frac)
    return TableStats(n, cols)
