"""Zone maps: per-row-range min/max/null statistics for scan pruning.

Reference: Trino's stripe/row-group skipping in trino-orc
(StripeReader.java) and trino-parquet (TupleDomainParquetPredicate) —
TupleDomain pushdown decides from column statistics whether a range of
rows can possibly satisfy a predicate, and skips decoding it otherwise.

Here the same idea covers every connector uniformly: a ZoneMap slices a
materialized TableData into fixed `zone_rows` ranges and records, per
zone per column, (min, max, null_count, row_count) in the column's
PHYSICAL representation (scaled int64 for DECIMAL, int32 days for DATE,
int32 dictionary codes for VARCHAR — pools are sorted engine-wide, so
code order is string order).

Evaluation is strictly conservative three-valued logic: a zone is pruned
only when the pushed conjunction provably cannot evaluate to TRUE for
any row in the zone. NULLs follow SQL semantics (a comparison against a
zone of all NULLs is never TRUE; IS NULL survives it), floating-point
zones containing NaN record unknown bounds and always survive, and
DECIMAL bounds compare through ops/project's exact scaled-int helpers so
HALF_UP semantics cannot drift from the device filter. The residual
FilterNode always re-runs, so pruning is a pure skip optimization.
"""

from __future__ import annotations

import operator
import threading
from collections import OrderedDict
from dataclasses import dataclass
from fractions import Fraction
from typing import Optional, Tuple

import numpy as np

from .. import ir
from ..types import TypeKind

DEFAULT_ZONE_ROWS = 65536

_FLIP = {"=": "=", "<>": "<>", "<": ">", "<=": ">=", ">": "<", ">=": "<="}
_OPS = {"=": operator.eq, "<>": operator.ne, "<": operator.lt,
        "<=": operator.le, ">": operator.gt, ">=": operator.ge}


@dataclass(frozen=True)
class ColumnZone:
    """Physical-representation bounds for one column over one row range.
    min/max are None when unknown (all-NULL zone, NaN present, or a type
    with no meaningful order) — an unknown bound never prunes."""
    min: Optional[object]
    max: Optional[object]
    null_count: int
    row_count: int


@dataclass(frozen=True)
class ZoneMap:
    zone_rows: int
    row_count: int
    starts: Tuple[int, ...]
    counts: Tuple[int, ...]
    zones: Tuple[Tuple[ColumnZone, ...], ...]   # [zone][table column]

    @property
    def num_zones(self) -> int:
        return len(self.starts)


def build_zone_map(data, zone_rows: int = DEFAULT_ZONE_ROWS) -> ZoneMap:
    """One numpy pass per (zone, column) over a TableData."""
    n = data.num_rows
    zone_rows = max(1, int(zone_rows))
    cols = [np.asarray(c) for c in data.columns]
    valids = [None] * len(cols) if data.valids is None else \
        [None if v is None else np.asarray(v) for v in data.valids]
    starts, counts, zones = [], [], []
    for start in range(0, max(n, 1), zone_rows):
        count = min(zone_rows, n - start)
        if count <= 0:
            break
        zcols = []
        for arr, valid in zip(cols, valids):
            sl = arr[start:start + count]
            if valid is not None:
                v = valid[start:start + count]
                nulls = int(count - v.sum())
                sl = sl[v]
            else:
                nulls = 0
            if len(sl) == 0:
                zcols.append(ColumnZone(None, None, nulls, count))
                continue
            if np.issubdtype(sl.dtype, np.floating) and \
                    bool(np.isnan(sl).any()):
                # NaN breaks min/max ordering: leave bounds unknown so
                # the zone always survives
                zcols.append(ColumnZone(None, None, nulls, count))
                continue
            zcols.append(ColumnZone(sl.min().item(), sl.max().item(),
                                    nulls, count))
        starts.append(start)
        counts.append(count)
        zones.append(tuple(zcols))
    return ZoneMap(zone_rows, n, tuple(starts), tuple(counts),
                   tuple(zones))


# ---- cache (keyed by table-data identity) --------------------------------
#
# The cache holds a strong reference to the TableData it describes, so a
# live entry can never alias a recycled id(); connector mutations rebuild
# TableData (memory connector INSERT/UPDATE/DELETE produce a new object),
# which self-invalidates by key.

_CACHE_MAX = 32
_cache: "OrderedDict[int, tuple]" = OrderedDict()
_cache_lock = threading.Lock()


def zone_map_for(data, zone_rows: int = DEFAULT_ZONE_ROWS) -> ZoneMap:
    key = id(data)
    with _cache_lock:
        hit = _cache.get(key)
        if hit is not None and hit[0] is data and zone_rows in hit[1]:
            _cache.move_to_end(key)
            return hit[1][zone_rows]
    zm = build_zone_map(data, zone_rows)
    with _cache_lock:
        hit = _cache.get(key)
        if hit is None or hit[0] is not data:
            hit = (data, {})
            _cache[key] = hit
        hit[1][zone_rows] = zm
        _cache.move_to_end(key)
        while len(_cache) > _CACHE_MAX:
            _cache.popitem(last=False)
    return zm


def note_table(data, zone_rows: int = DEFAULT_ZONE_ROWS) -> ZoneMap:
    """Eager collection hook (memory-connector insert/CTAS time)."""
    return zone_map_for(data, zone_rows)


def invalidate_zone_maps() -> None:
    with _cache_lock:
        _cache.clear()


# ---- conservative zone evaluation ----------------------------------------


def _scalar_cmp(op: str, a, adt, b, bdt) -> bool:
    """Exact comparison of two physical scalars of (possibly different)
    SQL types. DECIMAL pairs go through ops/project's scaled-int compare
    (the device path's helper — HALF_UP semantics shared by
    construction); DECIMAL-vs-DOUBLE compares exactly via Fraction;
    everything else is exact native Python comparison (int vs float is
    exact in Python)."""
    a_dec = adt.kind is TypeKind.DECIMAL
    b_dec = bdt.kind is TypeKind.DECIMAL
    if a_dec or b_dec:
        sa = adt.scale if a_dec else 0
        sb = bdt.scale if b_dec else 0
        if adt.kind is TypeKind.DOUBLE or bdt.kind is TypeKind.DOUBLE:
            fa = Fraction(int(a), 10 ** sa) if a_dec else Fraction(a)
            fb = Fraction(int(b), 10 ** sb) if b_dec else Fraction(b)
            return bool(_OPS[op](fa, fb))
        from ..ops.project import _decimal_compare
        return bool(_decimal_compare(np.int64(int(a)), sa,
                                     np.int64(int(b)), sb, op, xp=np))
    return bool(_OPS[op](a, b))


def _zone_of(expr: ir.ColumnRef, zone_cols, column_indices):
    return zone_cols[column_indices[expr.index]]


def _may_match(e: ir.Expr, zone_cols, column_indices) -> bool:
    """May `e` evaluate to TRUE for some row in the zone? True unless
    provably impossible. Any shape (or failure) we cannot reason about
    returns True — pruning is advisory only."""
    try:
        if isinstance(e, ir.Logical):
            if e.op == "and":
                return all(_may_match(a, zone_cols, column_indices)
                           for a in e.args)
            return True                       # OR et al: no pruning
        if isinstance(e, ir.IsNull):
            if not isinstance(e.arg, ir.ColumnRef):
                return True
            z = _zone_of(e.arg, zone_cols, column_indices)
            if e.negated:                     # IS NOT NULL
                return z.null_count < z.row_count
            return z.null_count > 0           # IS NULL
        if isinstance(e, ir.DictPredicate):
            if not isinstance(e.arg, ir.ColumnRef):
                return True
            z = _zone_of(e.arg, zone_cols, column_indices)
            if z.null_count >= z.row_count:
                return False                  # all NULL: never TRUE
            if z.min is None or z.max is None:
                return True
            lo = max(0, int(z.min))
            hi = min(len(e.lut) - 1, int(z.max))
            return any(e.lut[lo:hi + 1])
        if isinstance(e, ir.Compare):
            left, right, op = e.left, e.right, e.op
            if isinstance(left, ir.Literal) and \
                    isinstance(right, ir.ColumnRef):
                left, right, op = right, left, _FLIP[op]
            if not (isinstance(left, ir.ColumnRef) and
                    isinstance(right, ir.Literal)):
                return True
            if left.dtype.kind in (TypeKind.VARCHAR, TypeKind.ARRAY):
                return True                   # strings go via DictPredicate
            z = _zone_of(left, zone_cols, column_indices)
            if z.null_count >= z.row_count or right.value is None:
                return False                  # NULL comparand: never TRUE
            if z.min is None or z.max is None:
                return True
            cdt, v, ldt = left.dtype, right.value, right.dtype
            if op == "<":
                return _scalar_cmp("<", z.min, cdt, v, ldt)
            if op == "<=":
                return _scalar_cmp("<=", z.min, cdt, v, ldt)
            if op == ">":
                return _scalar_cmp(">", z.max, cdt, v, ldt)
            if op == ">=":
                return _scalar_cmp(">=", z.max, cdt, v, ldt)
            if op == "=":
                return _scalar_cmp("<=", z.min, cdt, v, ldt) and \
                    _scalar_cmp(">=", z.max, cdt, v, ldt)
            if op == "<>":
                # only impossible when the zone is the single value v
                return not (_scalar_cmp("=", z.min, cdt, v, ldt) and
                            _scalar_cmp("=", z.max, cdt, v, ldt))
            return True
        if isinstance(e, ir.Between):
            if not (isinstance(e.arg, ir.ColumnRef) and
                    isinstance(e.low, ir.Literal) and
                    isinstance(e.high, ir.Literal)):
                return True
            if e.arg.dtype.kind in (TypeKind.VARCHAR, TypeKind.ARRAY):
                return True
            z = _zone_of(e.arg, zone_cols, column_indices)
            if z.null_count >= z.row_count or e.low.value is None or \
                    e.high.value is None:
                return False
            if z.min is None or z.max is None:
                return True
            return _scalar_cmp(">=", z.max, e.arg.dtype,
                               e.low.value, e.low.dtype) and \
                _scalar_cmp("<=", z.min, e.arg.dtype,
                            e.high.value, e.high.dtype)
        if isinstance(e, ir.InList):
            if not isinstance(e.arg, ir.ColumnRef) or \
                    not all(isinstance(v, ir.Literal) for v in e.values):
                return True
            if e.arg.dtype.kind in (TypeKind.VARCHAR, TypeKind.ARRAY):
                return True
            z = _zone_of(e.arg, zone_cols, column_indices)
            if z.null_count >= z.row_count:
                return False
            if z.min is None or z.max is None:
                return True
            return any(
                v.value is not None and
                _scalar_cmp("<=", z.min, e.arg.dtype, v.value, v.dtype) and
                _scalar_cmp(">=", z.max, e.arg.dtype, v.value, v.dtype)
                for v in e.values)
        return True
    except Exception:
        return True


def surviving_zone_indices(zm: ZoneMap, predicate: ir.Expr,
                           column_indices) -> list:
    """Zone indices that may contain matching rows. `predicate`
    references scan OUTPUT positions; `column_indices` maps them to
    table columns."""
    return [i for i, zcols in enumerate(zm.zones)
            if _may_match(predicate, zcols, column_indices)]


def surviving_ranges(zm: ZoneMap, predicate: ir.Expr,
                     column_indices) -> list:
    """Merged (start, count) row ranges covering every surviving zone."""
    ranges = []
    for i in surviving_zone_indices(zm, predicate, column_indices):
        s, c = zm.starts[i], zm.counts[i]
        if ranges and ranges[-1][0] + ranges[-1][1] == s:
            ranges[-1][1] += c
        else:
            ranges.append([s, c])
    return [(s, c) for s, c in ranges]


def range_may_match(zm: ZoneMap, predicate: ir.Expr, column_indices,
                    start: int, count: int) -> bool:
    """May any row in [start, start+count) match? Used by the scheduler
    to drop whole splits and by the chunked driver to skip chunks."""
    end = start + count
    for i, (s, c) in enumerate(zip(zm.starts, zm.counts)):
        if s >= end:
            break
        if s + c <= start:
            continue
        if _may_match(predicate, zm.zones[i], column_indices):
            return True
    return False


def column_ranges(predicate: ir.Expr, column_indices, schema) -> dict:
    """Lower the pushed conjunction to {column_name: (lo, hi)} inclusive
    physical bounds for file readers (ORC stripe / Parquet row-group
    skipping). Only closed, numeric, single-column bounds translate;
    anything else is simply not tightened (None side = unbounded)."""
    out: dict = {}

    def tighten(name, lo, hi):
        plo, phi = out.get(name, (None, None))
        if lo is not None:
            plo = lo if plo is None else max(plo, lo)
        if hi is not None:
            phi = hi if phi is None else min(phi, hi)
        out[name] = (plo, phi)

    stack = [predicate]
    while stack:
        e = stack.pop()
        if isinstance(e, ir.Logical) and e.op == "and":
            stack.extend(e.args)
            continue
        if isinstance(e, ir.Compare):
            left, right, op = e.left, e.right, e.op
            if isinstance(left, ir.Literal) and \
                    isinstance(right, ir.ColumnRef):
                left, right, op = right, left, _FLIP[op]
            if not (isinstance(left, ir.ColumnRef) and
                    isinstance(right, ir.Literal)) or right.value is None:
                continue
            if left.dtype.kind in (TypeKind.VARCHAR, TypeKind.ARRAY) or \
                    left.dtype != right.dtype:
                continue                      # readers compare same-type
            name = schema.fields[column_indices[left.index]].name
            v = right.value
            if op in ("<", "<="):
                tighten(name, None, v)
            elif op in (">", ">="):
                tighten(name, v, None)
            elif op == "=":
                tighten(name, v, v)
        elif isinstance(e, ir.Between):
            if isinstance(e.arg, ir.ColumnRef) and \
                    isinstance(e.low, ir.Literal) and \
                    isinstance(e.high, ir.Literal) and \
                    e.arg.dtype.kind not in (TypeKind.VARCHAR,
                                             TypeKind.ARRAY) and \
                    e.low.value is not None and e.high.value is not None \
                    and e.arg.dtype == e.low.dtype == e.high.dtype:
                name = schema.fields[column_indices[e.arg.index]].name
                tighten(name, e.low.value, e.high.value)
    return {k: v for k, v in out.items() if v != (None, None)}
