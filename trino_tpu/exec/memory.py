"""Memory accounting: pool + hierarchical contexts + revocation.

Reference: lib/trino-memory-context (AggregatedMemoryContext.java:16,
LocalMemoryContext.java:18) + MemoryPool.java:44 — operators reserve
against a per-query pool; exceeding the limit kills the query (or triggers
revocation/spill). TPU edition: reservations track device HBM batch bytes;
host RAM plays the disk's role as the spill tier (exec/spill.py).

Round-9 growth — the full reservation model:

- USER reservations (`reserve`/`free`): bytes an operator needs resident
  to make progress. Exceeding the limit first *requests revocation* —
  registered callbacks (spillable build caches, pinned batches) free
  revocable bytes by moving them to host — and only then raises
  ExceededMemoryLimitError (MemoryPool.java's reserve + the
  MemoryRevokingScheduler.java:47 watermark trigger, collapsed into the
  reserve path).
- REVOCABLE reservations (`reserve_revocable`): bytes the holder can give
  back at any time (a spillable hash-build, cached build batches). They
  count toward pressure but never fail — by definition their owner
  registered a callback that can spill them.
- Per-holder ledger: every reservation is tagged (query id / cache name)
  so the coordinator's LowMemoryKiller can run its
  total-reservation-dominant policy, and `close()` can prove a query
  freed everything it took.
- Leak/double-free detection: the old `free` clamped negative
  reservations to 0, silently masking accounting bugs. Now a free
  exceeding the outstanding bytes raises MemoryAccountingError under
  strict mode (tests; TRINO_TPU_STRICT_MEMORY=1) and otherwise clamps
  while counting trino_tpu_memory_accounting_errors_total.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple


class ExceededMemoryLimitError(RuntimeError):
    """The query's working set cannot fit its pool even after revocation.
    Surfaced to clients as errorName QUERY_EXCEEDED_MEMORY — a user
    error, never retried (retrying an OOM reproduces it)."""

    error_name = "QUERY_EXCEEDED_MEMORY"
    error_code = 3

    def __init__(self, pool: str, requested: int, limit: int):
        super().__init__(
            f"Query exceeded per-query memory limit of {limit} bytes "
            f"in pool {pool} (requested {requested})")
        self.requested = requested
        self.limit = limit


class MemoryKilledError(ExceededMemoryLimitError):
    """Query killed by the cluster LowMemoryKiller (the dominant
    reservation under cluster-wide pressure). Same user-facing error
    code as a per-query limit hit."""

    def __init__(self, reason: str):
        RuntimeError.__init__(self, reason)
        self.requested = 0
        self.limit = 0


class MemoryAccountingError(RuntimeError):
    """A free exceeded the outstanding reservation (double-free) or a
    pool closed with bytes still reserved (leak)."""


def _strict_default() -> bool:
    return os.environ.get("TRINO_TPU_STRICT_MEMORY", "") == "1"


def parse_bytes(text: str) -> int:
    """'17179869184', '16GB', '512MB', '64kB' -> bytes (env knob parse)."""
    t = text.strip().upper()
    for suffix, mult in (("GB", 1 << 30), ("MB", 1 << 20),
                         ("KB", 1 << 10), ("B", 1)):
        if t.endswith(suffix):
            return int(float(t[:-len(suffix)])) * mult
    return int(t)


class MemoryPool:
    """Byte budget shared by a query's operators (memory/MemoryPool.java:44
    reserve:127), grown with revocable reservations and a revocation
    callback registry (context/MemoryTrackingContext + the operators'
    setRevocationHandler wiring)."""

    def __init__(self, limit_bytes: int, name: str = "general",
                 strict: Optional[bool] = None):
        self.limit = limit_bytes
        self.name = name
        self.reserved = 0            # user bytes
        self.revocable = 0           # revocable bytes (spillable)
        self.peak = 0
        self.accounting_errors = 0
        self.revocations_requested = 0
        self.strict = _strict_default() if strict is None else strict
        self._lock = threading.Lock()
        # holder tag -> outstanding user bytes (LowMemoryKiller's per-query
        # attribution); revocable tracked separately
        self.holder_bytes: Dict[str, int] = {}
        self.holder_revocable: Dict[str, int] = {}
        self._current_tag = ""
        # handle -> (tag, callback(target_bytes) -> bytes freed)
        self._revocation_cbs: Dict[int, Tuple[str, Callable[[int], int]]] = {}
        self._next_handle = 0
        # grace depth: while > 0, reserve() never raises — used by the
        # spill paths for the TRANSIENT materialization of a side that is
        # immediately moved to host (its bytes are revocable in spirit:
        # the very next statement revokes them)
        self._grace = 0

    # -- configuration -----------------------------------------------------

    def set_limit(self, limit_bytes: int) -> None:
        """Adjust the budget in place — outstanding reservations (cached
        builds, undrained results) keep their ledger; replacing the pool
        object would leak them."""
        with self._lock:
            self.limit = limit_bytes

    def set_current_tag(self, tag: str) -> None:
        """Default holder for untagged reserve/free calls (the dispatcher
        sets the running query id; operators don't thread it through)."""
        self._current_tag = tag or ""

    # -- user reservations -------------------------------------------------

    def _gauges(self) -> None:
        from ..metrics import MEMORY_RESERVED, MEMORY_REVOCABLE
        MEMORY_RESERVED.set(self.reserved, pool=self.name)
        MEMORY_REVOCABLE.set(self.revocable, pool=self.name)

    def reserve(self, bytes_: int, tag: Optional[str] = None) -> None:
        tag = self._current_tag if tag is None else tag
        with self._lock:
            deficit = self.reserved + self.revocable + bytes_ - self.limit
            grace = self._grace > 0
        if deficit > 0 and not grace:
            # memory pressure: ask revocable holders to spill before
            # failing the query (MemoryRevokingScheduler's trigger)
            freed = self.request_revocation(deficit)
            with self._lock:
                still = self.reserved + self.revocable + bytes_ - self.limit
                if still > 0:
                    raise ExceededMemoryLimitError(
                        self.name, self.reserved + self.revocable + bytes_,
                        self.limit)
            del freed
        with self._lock:
            self.reserved += bytes_
            self.holder_bytes[tag] = self.holder_bytes.get(tag, 0) + bytes_
            self.peak = max(self.peak, self.reserved + self.revocable)
            self._gauges()

    def try_reserve(self, bytes_: int, tag: Optional[str] = None) -> bool:
        try:
            self.reserve(bytes_, tag)
            return True
        except ExceededMemoryLimitError:
            return False

    def free(self, bytes_: int, tag: Optional[str] = None) -> None:
        explicit = tag is not None
        tag = self._current_tag if tag is None else tag
        with self._lock:
            if bytes_ > self.reserved:
                self._accounting_error(
                    f"free of {bytes_} bytes exceeds pool reservation "
                    f"{self.reserved} (double-free)")
                bytes_ = self.reserved
            self.reserved -= bytes_
            held = self.holder_bytes.get(tag, 0)
            take = min(held, bytes_)
            self.holder_bytes[tag] = held - take
            rest = bytes_ - take
            if rest:
                if explicit:
                    # an explicitly-tagged holder over-freed: that is an
                    # accounting bug in its own ledger
                    self._accounting_error(
                        f"holder {tag!r} freed {bytes_} bytes but held "
                        f"{held}")
                else:
                    # untagged frees legitimately cross query boundaries
                    # (a result batch reserved under query A is released
                    # when query B starts) — drain other holders so
                    # sum(holders) keeps tracking `reserved`
                    for h in list(self.holder_bytes):
                        if rest <= 0:
                            break
                        d = min(self.holder_bytes[h], rest)
                        self.holder_bytes[h] -= d
                        rest -= d
            for h in [k for k, v in self.holder_bytes.items() if v == 0]:
                self.holder_bytes.pop(h, None)
            self._gauges()

    # -- revocable reservations --------------------------------------------

    def reserve_revocable(self, bytes_: int,
                          tag: Optional[str] = None) -> None:
        """Never fails: revocable bytes are spillable by contract (their
        owner registered a callback that can give them back)."""
        tag = self._current_tag if tag is None else tag
        with self._lock:
            self.revocable += bytes_
            self.holder_revocable[tag] = \
                self.holder_revocable.get(tag, 0) + bytes_
            self.peak = max(self.peak, self.reserved + self.revocable)
            self._gauges()

    def free_revocable(self, bytes_: int, tag: Optional[str] = None) -> None:
        tag = self._current_tag if tag is None else tag
        with self._lock:
            if bytes_ > self.revocable:
                self._accounting_error(
                    f"revocable free of {bytes_} exceeds {self.revocable}")
                bytes_ = self.revocable
            self.revocable -= bytes_
            held = self.holder_revocable.get(tag, 0)
            if bytes_ > held:
                self._accounting_error(
                    f"revocable holder {tag!r} freed {bytes_} but held "
                    f"{held}")
            self.holder_revocable[tag] = max(0, held - bytes_)
            if self.holder_revocable.get(tag) == 0:
                self.holder_revocable.pop(tag, None)
            self._gauges()

    def register_revocation(self, callback: Callable[[int], int],
                            tag: str = "") -> int:
        """Register a spill callback: callback(target_bytes) frees up to
        target_bytes of revocable memory (calling free_revocable itself)
        and returns the bytes it freed. Returns an unregister handle."""
        with self._lock:
            self._next_handle += 1
            h = self._next_handle
            self._revocation_cbs[h] = (tag, callback)
            return h

    def unregister_revocation(self, handle: int) -> None:
        with self._lock:
            self._revocation_cbs.pop(handle, None)

    def request_revocation(self, target_bytes: int) -> int:
        """Drive callbacks (outside the lock — they free through this
        pool) until target_bytes are freed or every holder was asked.
        Returns bytes actually freed."""
        with self._lock:
            cbs = list(self._revocation_cbs.values())
            before = self.revocable
            self.revocations_requested += 1
        from ..metrics import MEMORY_REVOCATIONS
        MEMORY_REVOCATIONS.inc()
        freed = 0
        for _tag, cb in cbs:
            if freed >= target_bytes:
                break
            try:
                freed += int(cb(target_bytes - freed) or 0)
            except Exception:    # noqa: BLE001 — a broken spiller must
                pass             # not mask the real memory error
        with self._lock:
            return max(freed, before - self.revocable)

    # -- transient grace (spill materialization) ---------------------------

    class _Grace:
        def __init__(self, pool: "MemoryPool"):
            self.pool = pool

        def __enter__(self):
            with self.pool._lock:
                self.pool._grace += 1
            return self.pool

        def __exit__(self, *exc):
            with self.pool._lock:
                self.pool._grace -= 1
            return False

    def grace(self) -> "MemoryPool._Grace":
        """Context manager: reservations inside never raise. Used only by
        spill paths to materialize a side that is immediately moved to
        host — the accounting stays truthful, the limit check defers to
        the bounded per-partition phase that follows."""
        return MemoryPool._Grace(self)

    # -- diagnostics -------------------------------------------------------

    def _accounting_error(self, msg: str) -> None:
        # called under self._lock
        self.accounting_errors += 1
        from ..metrics import MEMORY_ACCOUNTING_ERRORS
        MEMORY_ACCOUNTING_ERRORS.inc()
        if self.strict:
            raise MemoryAccountingError(f"pool {self.name}: {msg}")

    def available(self) -> int:
        with self._lock:
            return max(0, self.limit - self.reserved - self.revocable)

    def query_bytes(self, tag: str) -> int:
        with self._lock:
            return self.holder_bytes.get(tag, 0) + \
                self.holder_revocable.get(tag, 0)

    def snapshot(self) -> dict:
        """Heartbeat/status payload (ClusterMemoryManager consumes this
        shape from every worker)."""
        with self._lock:
            return {"pool": self.name, "limit": self.limit,
                    "reserved": self.reserved,
                    "revocable": self.revocable, "peak": self.peak,
                    "holders": dict(self.holder_bytes),
                    "revocable_holders": dict(self.holder_revocable)}

    def close(self) -> None:
        """End-of-life check: every byte must have been freed. A leak is
        an accounting bug — strict mode raises (tests), production counts
        the metric and zeroes the ledger so gauges don't lie forever."""
        with self._lock:
            leaked = self.reserved + self.revocable
            if leaked:
                self._accounting_error(
                    f"closed with {leaked} bytes outstanding "
                    f"(holders: {dict(self.holder_bytes)})")
            self.reserved = 0
            self.revocable = 0
            self.holder_bytes.clear()
            self.holder_revocable.clear()
            self._gauges()


class MemoryContext:
    """One operator/node's reservation against the pool
    (LocalMemoryContext.setBytes semantics: delta-adjusted)."""

    def __init__(self, pool: MemoryPool, name: str):
        self.pool = pool
        self.name = name
        self.bytes = 0

    def set_bytes(self, new_bytes: int) -> None:
        delta = new_bytes - self.bytes
        if delta > 0:
            self.pool.reserve(delta)
        elif delta < 0:
            self.pool.free(-delta)
        self.bytes = new_bytes

    def close(self) -> None:
        self.set_bytes(0)


def batch_bytes(batch) -> int:
    """Device bytes of a Batch (data + validity + live mask)."""
    total = batch.live.size  # bool mask
    for col in batch.columns:
        total += col.data.size * col.data.dtype.itemsize + col.valid.size
    return int(total)
