"""Memory accounting: pool + hierarchical contexts.

Reference: lib/trino-memory-context (AggregatedMemoryContext.java:16,
LocalMemoryContext.java:18) + MemoryPool.java:44 — operators reserve
against a per-query pool; exceeding the limit kills the query (or triggers
revocation/spill). TPU edition: reservations track device HBM batch bytes;
the revocation analog is the executor's chunked aggregation (bounded-memory
scan processing) rather than disk spill — host RAM plays the disk's role.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class ExceededMemoryLimitError(RuntimeError):
    def __init__(self, pool: str, requested: int, limit: int):
        super().__init__(
            f"Query exceeded per-query memory limit of {limit} bytes "
            f"in pool {pool} (requested {requested})")


class MemoryPool:
    """Byte budget shared by a query's operators (memory/MemoryPool.java:44
    reserve:127)."""

    def __init__(self, limit_bytes: int, name: str = "general"):
        self.limit = limit_bytes
        self.name = name
        self.reserved = 0
        self.peak = 0
        self._lock = threading.Lock()

    def reserve(self, bytes_: int) -> None:
        with self._lock:
            if self.reserved + bytes_ > self.limit:
                raise ExceededMemoryLimitError(self.name,
                                               self.reserved + bytes_,
                                               self.limit)
            self.reserved += bytes_
            self.peak = max(self.peak, self.reserved)

    def free(self, bytes_: int) -> None:
        with self._lock:
            self.reserved = max(0, self.reserved - bytes_)


class MemoryContext:
    """One operator/node's reservation against the pool
    (LocalMemoryContext.setBytes semantics: delta-adjusted)."""

    def __init__(self, pool: MemoryPool, name: str):
        self.pool = pool
        self.name = name
        self.bytes = 0

    def set_bytes(self, new_bytes: int) -> None:
        delta = new_bytes - self.bytes
        if delta > 0:
            self.pool.reserve(delta)
        elif delta < 0:
            self.pool.free(-delta)
        self.bytes = new_bytes

    def close(self) -> None:
        self.set_bytes(0)


def batch_bytes(batch) -> int:
    """Device bytes of a Batch (data + validity + live mask)."""
    total = batch.live.size  # bool mask
    for col in batch.columns:
        total += col.data.size * col.data.dtype.itemsize + col.valid.size
    return int(total)
